"""Preemption-tolerant pod training (PR 9): announced failures.

Covers the PreemptionHandler lifecycle (notice idempotence, grace from
env/CLI, signal installation), the grace-window emergency checkpoint
(deflate vs ZIP_STORED fallback, bit-exact restore), the ElasticTrainer
step-boundary check, the Membership leaving ledger + torn-JSON
hardening, heartbeat step-time/durable-step derivation, launcher-side
straggler flagging, coordinator election/failover, planned-leave
restart-budget semantics, and the signal paths (SIGTERM during step /
during checkpoint write, grace-expiry SIGKILL escalation) — the
subprocess/signal tests are slow-marked so tier-1 stays fast."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.multilayer import (
    MultiLayerNetwork, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel import (
    CheckpointManager, CoordinatorUnreachableError, ElasticTrainer,
    FailureDetector, FaultKind, FaultSchedule, Heartbeat, HostLostError,
    Membership, PodLauncher, PreemptedError, PreemptionHandler,
    ProcessFailureDetector, PREEMPTED_EXIT_CODE, elect_coordinator,
)
from deeplearning4j_tpu.parallel.chaos import ChaosInjector
from deeplearning4j_tpu.parallel.distributed import (
    ENV_COORD_PORTS, ENV_COORDINATOR, ENV_GRACE_S, ENV_NUM_PROCESSES,
    ENV_PROCESS_ID, ENV_RUN_DIR,
)
from deeplearning4j_tpu.parallel.launcher import maybe_bootstrap_from_env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _mlp(seed=3, lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr=lr))
            .layer(Dense(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _data(batch=32):
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(batch, 4)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)]
    return DataSet(xs, ys)


class _Plain:
    def __init__(self, net):
        self.net = net

    def fit_batch(self, ds):
        return self.net.fit_batch(ds)


# ---------------------------------------------------------------------------
# PreemptionHandler lifecycle
# ---------------------------------------------------------------------------

class TestHandlerLifecycle:
    def test_notice_is_idempotent(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), clock=clock)
        h = PreemptionHandler(grace_s=10.0, membership=m, process_id=3,
                              clock=clock)
        assert not h.requested and h.remaining_s == 10.0
        h.notice(signal.SIGTERM)
        clock.t += 4.0
        h.notice(signal.SIGTERM)        # scheduler re-signals
        h.notice(signal.SIGUSR1)        # launcher forwards on top
        assert h.requested and h.notice_count == 3
        # the deadline is anchored at the FIRST notice
        assert h.remaining_s == pytest.approx(6.0)
        # exactly one leaving marker, stamped at the first notice
        assert sorted(m.leaving()) == [3]
        assert m.leaving()[3]["t"] == 1000.0

    def test_grace_from_env_and_validation(self, monkeypatch):
        monkeypatch.setenv(ENV_GRACE_S, "12.5")
        assert PreemptionHandler().grace_s == 12.5
        assert PreemptionHandler(grace_s=3.0).grace_s == 3.0
        with pytest.raises(ValueError, match="grace_s"):
            PreemptionHandler(grace_s=0)

    def test_install_uninstall_roundtrip(self):
        prev_term = signal.getsignal(signal.SIGTERM)
        h = PreemptionHandler(grace_s=5.0).install()
        try:
            assert signal.getsignal(signal.SIGTERM) == h._on_signal
            assert signal.getsignal(signal.SIGUSR1) == h._on_signal
        finally:
            h.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev_term

    def test_preempted_error_is_not_recoverable(self):
        exc = PreemptedError(7, "/tmp/x.zip", stored=True, seconds=0.1)
        assert not FailureDetector().is_recoverable(exc)
        assert exc.exit_code == PREEMPTED_EXIT_CODE
        assert PREEMPTED_EXIT_CODE not in (0, 1, 2)


# ---------------------------------------------------------------------------
# emergency checkpoint: codec decision + restore fidelity
# ---------------------------------------------------------------------------

class TestEmergencyCheckpoint:
    def _codecs(self, path):
        with zipfile.ZipFile(path) as zf:
            return {i.compress_type for i in zf.infolist()}

    def test_deflate_when_grace_affords_it(self, tmp_path):
        net = _mlp()
        ds = _data()
        [net.fit_batch(ds) for _ in range(2)]
        ckpt = CheckpointManager(str(tmp_path))
        h = PreemptionHandler(grace_s=30.0)
        h.notice()
        path, stored, seconds = h.emergency_checkpoint(ckpt, net, 2)
        assert not stored and self._codecs(path) == {zipfile.ZIP_DEFLATED}
        assert seconds <= 30.0

    def test_stored_fallback_when_grace_is_tight(self, tmp_path):
        clock = FakeClock()
        net = _mlp()
        ds = _data()
        [net.fit_batch(ds) for _ in range(2)]
        ckpt = CheckpointManager(str(tmp_path))
        # pretend the last deflate write took 2s: with 3s of a 4s budget
        # already burned, deflate (3x2s margin) cannot fit -> ZIP_STORED
        ckpt.last_save_seconds = 2.0
        h = PreemptionHandler(grace_s=4.0, clock=clock)
        h.notice()
        clock.t += 3.0
        path, stored, _ = h.emergency_checkpoint(ckpt, net, 5)
        assert stored and self._codecs(path) == {zipfile.ZIP_STORED}
        # the uncompressed emergency checkpoint restores bit-identically
        from deeplearning4j_tpu.utils.serializer import load_model
        loaded = load_model(path)
        flat = lambda t: np.concatenate(  # noqa: E731
            [np.ravel(x) for x in
             __import__("jax").tree_util.tree_leaves(t)])
        assert np.array_equal(flat(loaded.params), flat(net.params))

    def test_non_writer_host_skips_the_write(self, tmp_path):
        net = _mlp()
        ckpt = CheckpointManager(str(tmp_path), role="reader")
        h = PreemptionHandler(grace_s=10.0)
        h.notice()
        path, stored, seconds = h.emergency_checkpoint(ckpt, net, 3)
        assert path is None and seconds is not None


# ---------------------------------------------------------------------------
# ElasticTrainer step-boundary integration
# ---------------------------------------------------------------------------

class TestElasticBoundary:
    def test_notice_mid_run_checkpoints_and_resumes_bitwise(self, tmp_path):
        ds = _data()
        ref_net = _mlp()
        ref = [float(ref_net.fit_batch(ds)) for _ in range(10)]

        h = PreemptionHandler(grace_s=30.0)
        et = ElasticTrainer(_Plain(_mlp()), str(tmp_path),
                            checkpoint_every=4, preemption=h)
        losses = [float(et.fit_batch(ds)) for _ in range(6)]
        h.notice(signal.SIGTERM)          # arrives "mid-step"
        with pytest.raises(PreemptedError) as ei:
            et.fit_batch(ds)
        assert ei.value.step == 6
        assert et.last_checkpoint_step == 6
        # a fresh process (relaunch) resumes at EXACTLY the preempted step
        et2 = ElasticTrainer(_Plain(_mlp()), str(tmp_path),
                             checkpoint_every=4)
        assert et2.resume() == 6
        tail = [float(et2.fit_batch(ds)) for _ in range(4)]
        assert losses + tail == ref       # zero steps lost, bit-exact

    def test_notice_during_checkpoint_write_defers_to_boundary(
            self, tmp_path):
        """A notice landing while ckpt.save is mid-write (the signal
        handler only flips the flag) must let the write complete and be
        processed at the NEXT boundary with a fresh emergency
        checkpoint."""
        ds = _data()
        h = PreemptionHandler(grace_s=30.0)
        et = ElasticTrainer(_Plain(_mlp()), str(tmp_path),
                            checkpoint_every=3, preemption=h)
        real_save = et.ckpt.save

        def noisy_save(net, step):
            h.notice(signal.SIGTERM)      # "signal" arrives mid-write
            return real_save(net, step)

        et.ckpt.save = noisy_save
        for _ in range(2):
            et.fit_batch(ds)
        # step 3 checkpoints (notice fires inside the write, write lands),
        # the step itself completes, and the NEXT call preempts at 3
        float(et.fit_batch(ds))
        assert (tmp_path / "checkpoint_0000000003.zip").exists()
        et.ckpt.save = real_save          # emergency path uses save_snapshot
        with pytest.raises(PreemptedError) as ei:
            et.fit_batch(ds)
        assert ei.value.step == 3

    def test_preemption_not_swallowed_by_recovery(self, tmp_path):
        """PreemptedError must propagate even with a permissive detector
        and retries configured — the host is going away."""
        class EverythingRecovers(FailureDetector):
            def is_recoverable(self, exc):
                return super().is_recoverable(exc) or True

        h = PreemptionHandler(grace_s=30.0)
        et = ElasticTrainer(_Plain(_mlp()), str(tmp_path), max_restarts=99,
                            failure_detector=EverythingRecovers(),
                            preemption=h)
        ds = _data()
        et.fit_batch(ds)
        h.notice()
        with pytest.raises(PreemptedError):
            et.fit_batch(ds)

    def test_fit_flushes_inflight_async_checkpoint(self, tmp_path):
        """Satellite: fit() must wait() the in-flight save_async so the
        final checkpoint is durable and intact on disk when it returns."""
        from deeplearning4j_tpu.datasets import ListDataSetIterator
        from deeplearning4j_tpu.utils.serializer import load_model

        et = ElasticTrainer(_Plain(_mlp()), str(tmp_path),
                            checkpoint_every=2, async_checkpoints=True)
        ds = _data()
        et.fit(ListDataSetIterator([ds] * 5), epochs=1)
        latest = et.ckpt.latest()
        assert latest is not None and latest[1] == 5
        loaded = load_model(latest[0])    # intact: loads + digests verify
        assert loaded.iteration == 5
        assert et.last_checkpoint_step == 5


# ---------------------------------------------------------------------------
# membership: torn JSON hardening + leaving ledger (satellites)
# ---------------------------------------------------------------------------

class TestMembershipHardening:
    def test_scan_survives_torn_and_garbage_heartbeats(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        # a worker killed mid-beat() leaves every flavor of torn file:
        (tmp_path / "hb_1.json").write_text("")              # empty
        (tmp_path / "hb_2.json").write_text('{"process_id"')  # truncated
        (tmp_path / "hb_3.json").write_text("null")          # non-dict
        (tmp_path / "hb_4.json").write_text('{"pid": 7}')    # missing id
        (tmp_path / "hb_5.json").write_text('{"process_id": "x"}')
        assert m.alive() == [0]           # torn beats = missed beats
        assert m.refresh() == 1           # monitor loop must not raise
        assert m.last_beat(3) is None
        assert m.last_checkpoint_step() == -1

    def test_truncated_ledger_degrades_to_default(self, tmp_path):
        """Regression: a truncated membership.json must read as the empty
        default (re-persisted by the next refresh), not raise
        JSONDecodeError in the coordinator's monitor loop."""
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.refresh()
        ledger = tmp_path / Membership.LEDGER
        full = ledger.read_text()
        ledger.write_text(full[:len(full) // 2])   # torn write
        assert m.read() == {"epoch": 0, "members": []}
        assert m.refresh() == 1           # recovers by re-persisting
        ledger.write_text("[1, 2]")       # garbage of the wrong shape
        assert m.read() == {"epoch": 0, "members": []}

    def test_leaving_marker_is_a_fast_leave(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.beat(1)
        assert m.refresh() == 1 and m.members() == [0, 1]
        # preemption notice: worker 1 still BEATS (it is writing its
        # emergency checkpoint) but is logically gone immediately
        m.mark_leaving(1, grace_s=10.0)
        m.beat(1)
        assert m.alive() == [0]
        assert m.refresh() == 2 and m.members() == [0]
        # relaunch clears the marker: the new incarnation rejoins
        m.clear_leaving(1)
        m.beat(1)
        assert m.alive() == [0, 1]

    def test_detector_sees_fast_leave_without_heartbeat_expiry(
            self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.beat(1)
        det = ProcessFailureDetector(m)
        det.check()                       # baseline
        m.mark_leaving(1)                 # no clock advance at all
        with pytest.raises(HostLostError) as ei:
            det.check()
        assert ei.value.lost == [1]

    def test_beat_carries_ckpt_step(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), clock=clock)
        m.beat(0, step=10, ckpt_step=8)
        m.beat(1, step=12, ckpt_step=12)
        assert m.last_checkpoint_step() == 12


# ---------------------------------------------------------------------------
# heartbeat-derived step time
# ---------------------------------------------------------------------------

class TestHeartbeatStepTime:
    def test_first_sample_discarded_then_derived(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), clock=clock)
        state = {"step": 0, "ckpt": -1}
        hb = Heartbeat(m, 0, step_fn=lambda: state["step"],
                       ckpt_step_fn=lambda: state["ckpt"],
                       export_metrics=False)
        hb._beat_once()                               # step 0 baseline
        clock.t += 5.0
        state["step"] = 1                             # compile-polluted
        hb._beat_once()
        assert m.last_beat(0)["step_s"] is None       # discarded
        clock.t += 0.4
        state["step"] = 2
        state["ckpt"] = 2
        hb._beat_once()
        rec = m.last_beat(0)
        assert rec["step_s"] == pytest.approx(0.4)
        assert rec["ckpt_step"] == 2
        clock.t += 0.8
        state["step"] = 4                             # 2 steps per beat
        hb._beat_once()
        assert m.last_beat(0)["step_s"] == pytest.approx(0.4)


# ---------------------------------------------------------------------------
# launcher: straggler detection (driven directly, no processes)
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self):
        self.killed = 0

    def poll(self):
        return None

    def kill(self):
        self.killed += 1


class TestStragglerDetection:
    def _launcher(self, tmp_path, n=3, policy="flag", **kw):
        clock = FakeClock()
        lp = PodLauncher(["true"], num_workers=n, run_dir=str(tmp_path),
                         straggler_policy=policy, straggler_beats=3,
                         straggler_factor=2.0, **kw)
        lp.membership.clock = clock
        for h in lp.handles:
            h.state = "running"
            h.proc = _FakeProc()
        return lp, clock

    def _beat_all(self, lp, clock, step_times):
        clock.t += 1.0
        for i, s in enumerate(step_times):
            lp.membership.beat(i, step_s=s)

    def test_flagged_after_m_consecutive_beats(self, tmp_path):
        lp, clock = self._launcher(tmp_path)
        for round_i in range(3):
            self._beat_all(lp, clock, [0.3, 0.31, 1.0])   # 1.0 > 2x0.305
            lp._check_stragglers()
        events = [e for e in lp.events if e["kind"] == "straggler"]
        assert len(events) == 1 and events[0]["worker"] == 2
        assert events[0]["streak"] == 3
        assert lp.stats()["stragglers_flagged"] == 1
        # flagged once per incarnation — more beats don't re-flag
        self._beat_all(lp, clock, [0.3, 0.31, 1.0])
        lp._check_stragglers()
        assert len([e for e in lp.events
                    if e["kind"] == "straggler"]) == 1

    def test_streak_resets_when_pace_recovers(self, tmp_path):
        lp, clock = self._launcher(tmp_path)
        self._beat_all(lp, clock, [0.3, 0.3, 1.0])
        lp._check_stragglers()
        self._beat_all(lp, clock, [0.3, 0.3, 0.32])       # recovered
        lp._check_stragglers()
        self._beat_all(lp, clock, [0.3, 0.3, 1.0])
        lp._check_stragglers()
        self._beat_all(lp, clock, [0.3, 0.3, 1.0])
        lp._check_stragglers()
        assert not [e for e in lp.events if e["kind"] == "straggler"]

    def test_same_beat_not_recounted(self, tmp_path):
        lp, clock = self._launcher(tmp_path)
        self._beat_all(lp, clock, [0.3, 0.3, 1.0])
        for _ in range(5):                # poll 5x on ONE beat
            lp._check_stragglers()
        assert not [e for e in lp.events if e["kind"] == "straggler"]

    def test_relaunch_policy_kills(self, tmp_path):
        lp, clock = self._launcher(tmp_path, policy="relaunch")
        for _ in range(3):
            self._beat_all(lp, clock, [0.3, 0.3, 1.0])
            lp._check_stragglers()
        assert lp.handles[2].straggler_killed
        assert lp.handles[2].proc.killed == 1

    def test_off_policy_and_single_worker_no_scan(self, tmp_path):
        lp, clock = self._launcher(tmp_path, policy="off")
        for _ in range(3):
            self._beat_all(lp, clock, [0.3, 0.3, 9.9])
            lp._check_stragglers()
        assert not [e for e in lp.events if e["kind"] == "straggler"]
        with pytest.raises(ValueError, match="straggler_policy"):
            PodLauncher(["true"], 1, str(tmp_path / "x"),
                        straggler_policy="maybe")


# ---------------------------------------------------------------------------
# launcher stats / run-report surfaces (satellite)
# ---------------------------------------------------------------------------

class TestPodLivenessSurfaces:
    def test_stats_carries_pod_liveness(self, tmp_path):
        clock = FakeClock()
        lp = PodLauncher(["true"], num_workers=2, run_dir=str(tmp_path))
        lp.membership.clock = clock
        lp.membership.beat(0, step=9, ckpt_step=8)
        lp.membership.beat(1, step=9, ckpt_step=8)
        lp.membership.refresh()
        lp.membership.mark_leaving(1)
        s = lp.stats()
        assert s["epoch"] == 1
        assert s["alive"] == [0]
        assert s["leaving"] == [1]
        assert s["last_checkpoint_step"] == 8
        assert s["planned_leaves"] == 0

    def test_metrics_registry_exposes_launcher_collector(self, tmp_path):
        from deeplearning4j_tpu.obs.metrics import get_registry

        lp = PodLauncher(["true"], num_workers=2, run_dir=str(tmp_path))
        lp.membership.beat(0, ckpt_step=4)
        snap = get_registry().snapshot()
        mine = [v for k, v in snap.get("collected", {}).items()
                if k.startswith("launcher") and isinstance(v, dict)
                and v.get("last_checkpoint_step") == 4]
        assert mine and {"epoch", "alive", "leaving",
                         "last_checkpoint_step"} <= set(mine[0])


# ---------------------------------------------------------------------------
# coordinator election + bootstrap failover
# ---------------------------------------------------------------------------

class TestCoordinatorFailover:
    def test_elect_lowest_alive_id(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        for i in (0, 1, 2):
            m.beat(i)
        assert elect_coordinator(m, [9000, 9001, 9002]) == \
            (0, "127.0.0.1:9000")
        clock.t += 6.0                    # coordinator's beat expires
        m.beat(1)
        m.beat(2)
        assert elect_coordinator(m, [9000, 9001, 9002]) == \
            (1, "127.0.0.1:9001")
        # a LEAVING survivor is skipped too (it announced departure)
        m.mark_leaving(1)
        assert elect_coordinator(m, [9000, 9001, 9002])[0] == 2

    def test_elect_uses_advertised_addr(self, tmp_path):
        m = Membership(str(tmp_path))
        m.beat(1, addr="10.0.0.7")
        assert elect_coordinator(m, {1: 8476}) == (1, "10.0.0.7:8476")

    def test_elect_raises_when_nobody_alive(self, tmp_path):
        m = Membership(str(tmp_path))
        with pytest.raises(CoordinatorUnreachableError, match="no alive"):
            elect_coordinator(m, [9000])

    def test_bootstrap_fails_over_to_elected_survivor(self, tmp_path,
                                                      monkeypatch):
        """Coordinator restart: a worker whose initialize() finds the
        configured coordinator dead must re-initialize against the
        survivor with the lowest alive id — not die terminal."""
        m = Membership(str(tmp_path))
        m.beat(1)
        m.beat(2)
        monkeypatch.setenv(ENV_COORDINATOR, "127.0.0.1:9000")
        monkeypatch.setenv(ENV_NUM_PROCESSES, "3")
        monkeypatch.setenv(ENV_PROCESS_ID, "2")
        monkeypatch.setenv(ENV_RUN_DIR, str(tmp_path))
        monkeypatch.setenv(ENV_COORD_PORTS, "9000,9001,9002")
        calls = []

        def fake_init(addr, n, i, timeout_s=None):
            calls.append(addr)
            if addr == "127.0.0.1:9000":
                raise CoordinatorUnreachableError("dead")

        assert maybe_bootstrap_from_env(_initialize=fake_init)
        assert calls == ["127.0.0.1:9000", "127.0.0.1:9001"]

    def test_bootstrap_stays_terminal_without_failover_contract(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_COORDINATOR, "127.0.0.1:9000")
        monkeypatch.setenv(ENV_NUM_PROCESSES, "2")
        monkeypatch.setenv(ENV_PROCESS_ID, "1")
        monkeypatch.delenv(ENV_COORD_PORTS, raising=False)
        monkeypatch.delenv(ENV_RUN_DIR, raising=False)

        def fake_init(addr, n, i, timeout_s=None):
            raise CoordinatorUnreachableError("dead")

        with pytest.raises(CoordinatorUnreachableError):
            maybe_bootstrap_from_env(_initialize=fake_init)

    def test_launcher_exports_coord_ports_in_distributed_mode(
            self, tmp_path):
        lp = PodLauncher(["true"], num_workers=3, run_dir=str(tmp_path),
                         bootstrap="distributed", coordinator_port=7001)
        env = lp._env_for(lp.handles[1])
        ports = [int(p) for p in env[ENV_COORD_PORTS].split(",")]
        assert len(ports) == 3 and ports[0] == 7001
        assert env[ENV_COORDINATOR] == "127.0.0.1:7001"
        assert float(env[ENV_GRACE_S]) == 30.0


# ---------------------------------------------------------------------------
# chaos kinds
# ---------------------------------------------------------------------------

class TestNewChaosKinds:
    def test_kinds_registered_and_parseable(self):
        for kind in (FaultKind.PREEMPT_NOTICE, FaultKind.COORD_KILL,
                     FaultKind.SLOW_WORKER):
            assert kind in FaultKind.ALL
        from deeplearning4j_tpu.cli import _parse_chaos
        sched, seed, hang, slow = _parse_chaos(
            "preempt_notice@4,slow_worker@2,slow=0.9")
        assert sched.faults == {4: ["preempt_notice"], 2: ["slow_worker"]}
        assert slow == 0.9

    def test_slow_worker_drags_every_later_step(self):
        class Recorder:
            def __init__(self):
                self.net = self
                self.sleeps = []

            def fit_batch(self, ds):
                return 0.0

        rec = Recorder()
        inj = ChaosInjector(rec, FaultSchedule.scripted(
            {2: FaultKind.SLOW_WORKER}), slow_seconds=0.5,
            sleep_fn=rec.sleeps.append)
        for _ in range(4):
            inj.fit_batch(None)
        assert rec.sleeps == [0.5, 0.5, 0.5]    # steps 2, 3, 4

    def test_coord_kill_rejected_off_coordinator(self, monkeypatch):
        monkeypatch.setenv(ENV_PROCESS_ID, "1")
        inj = ChaosInjector(object(), FaultSchedule.scripted(
            {1: FaultKind.COORD_KILL}))
        with pytest.raises(RuntimeError, match="non-coordinator"):
            inj._kill_self(FaultKind.COORD_KILL)

    def test_preempt_notice_signals_not_kills(self, tmp_path):
        """The announced kind delivers SIGTERM and RETURNS — the step
        completes; with a handler installed the flag flips in-process."""
        h = PreemptionHandler(grace_s=30.0).install()
        try:
            class T:
                net = None

                def fit_batch(self, ds):
                    return 1.25

            inj = ChaosInjector(T(), FaultSchedule.scripted(
                {2: FaultKind.PREEMPT_NOTICE}))
            assert inj.fit_batch(None) == 1.25
            assert not h.requested
            assert inj.fit_batch(None) == 1.25   # step 2 still completes
            assert h.requested                   # but the notice is in
        finally:
            h.uninstall()


# ---------------------------------------------------------------------------
# signal paths through real processes (slow: subprocess + signals)
# ---------------------------------------------------------------------------

def _run_py(body, env=None, timeout=120):
    code = ("import os, sys\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(body))
    full_env = dict(os.environ)
    full_env.pop("XLA_FLAGS", None)
    full_env["JAX_PLATFORMS"] = "cpu"
    full_env.update(env or {})
    return subprocess.run([sys.executable, "-c", code], env=full_env,
                          capture_output=True, text=True, timeout=timeout)


@pytest.mark.slow
class TestSignalPaths:
    def test_sigterm_during_step_exits_preempted(self, tmp_path):
        """A real SIGTERM delivered while the training loop runs: the
        worker must write an emergency checkpoint and exit with the
        distinct PREEMPTED code, well inside the grace budget."""
        script = f"""
        import time
        import numpy as np
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.updaters import Sgd
        from deeplearning4j_tpu.parallel import (
            ElasticTrainer, PreemptedError, PreemptionHandler)
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(lr=.05))
                .layer(Dense(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf); net.init()
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(size=(16, 4)).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])
        class P:
            def __init__(s, n): s.net = n
            def fit_batch(s, d):
                time.sleep(0.05)
                return s.net.fit_batch(d)
        h = PreemptionHandler.install_from_env(grace_s=15.0)
        et = ElasticTrainer(P(net), {str(tmp_path)!r}, checkpoint_every=50,
                            preemption=h)
        print("READY", flush=True)
        try:
            for _ in range(2000):
                et.fit_batch(ds)
            raise SystemExit("never preempted")
        except PreemptedError as e:
            print("PREEMPTED", e.step, e.seconds, flush=True)
            raise SystemExit(e.exit_code)
        """
        code = ("import os, sys\n"
                "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
                f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(script))
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        p = subprocess.Popen([sys.executable, "-c", code], env=env,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                             text=True)
        assert p.stdout.readline().strip() == "READY"
        time.sleep(1.0)                   # mid-training
        t0 = time.monotonic()
        p.send_signal(signal.SIGTERM)
        out, err = p.communicate(timeout=60)
        elapsed = time.monotonic() - t0
        assert p.returncode == PREEMPTED_EXIT_CODE, (out, err)
        assert "PREEMPTED" in out
        assert elapsed < 15.0, f"emergency exit took {elapsed:.1f}s"
        ckpts = [f for f in os.listdir(tmp_path) if f.endswith(".zip")]
        assert ckpts, "no emergency checkpoint on disk"

    def test_grace_expired_launcher_escalates_to_sigkill(self, tmp_path):
        """A worker that ignores its notice must be SIGKILLed by the
        launcher once the grace budget (plus margin) expires, then
        relaunched through the budgeted leave path."""
        worker = tmp_path / "stubborn.py"
        worker.write_text(textwrap.dedent(f"""
            import os, signal, sys, time
            sys.path.insert(0, {_REPO!r})
            from deeplearning4j_tpu.parallel.launcher import (
                Heartbeat, Membership)
            signal.signal(signal.SIGTERM, signal.SIG_IGN)   # stubborn
            hb = Heartbeat.start_from_env()
            inc = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
            # first incarnation ignores its notice forever; the relaunch
            # behaves and completes
            time.sleep(30.0 if inc == 0 else 0.5)
            hb.stop()
        """))
        lp = PodLauncher([sys.executable, str(worker)], num_workers=1,
                         run_dir=str(tmp_path / "run"), grace_s=0.6,
                         heartbeat_timeout=5.0, deadline_s=60.0,
                         max_restarts=2, poll_interval=0.05)
        t = threading.Thread(target=lambda: setattr(
            lp, "_report", lp.run()), daemon=True)
        t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if lp.handles[0].state == "running" and \
                    lp.membership.last_beat(0) is not None:
                break
            time.sleep(0.05)
        assert lp.preempt_worker(0)
        t.join(timeout=45)
        assert not t.is_alive()
        report = lp._report
        assert report["grace_escalations"] == 1
        causes = [e["cause"] for e in report["leaves"]]
        assert "grace_expired" in causes
        assert report["completed"] == [0]      # relaunched and finished
        assert report["budget_used"][0] == 1   # escalation consumes budget
        assert report["leaked_killed"] == 0

    def test_planned_leave_does_not_consume_budget(self, tmp_path):
        """A worker that self-notices (handler installed) and exits with
        the PREEMPTED code must be relaunched with the restart budget
        untouched — even with max_restarts=0."""
        worker = tmp_path / "polite.py"
        worker.write_text(textwrap.dedent(f"""
            import os, sys, time
            sys.path.insert(0, {_REPO!r})
            from deeplearning4j_tpu.parallel.launcher import Heartbeat
            from deeplearning4j_tpu.parallel.preemption import (
                PreemptionHandler)
            from deeplearning4j_tpu.parallel.distributed import (
                PREEMPTED_EXIT_CODE)
            hb = Heartbeat.start_from_env()
            h = PreemptionHandler.install_from_env()
            inc = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
            if inc == 0:
                h.notice()                 # the scheduler's SIGTERM
                hb.stop(deregister=True)
                raise SystemExit(PREEMPTED_EXIT_CODE)
            time.sleep(0.3)
            hb.stop()
        """))
        lp = PodLauncher([sys.executable, str(worker)], num_workers=1,
                         run_dir=str(tmp_path / "run"), grace_s=10.0,
                         max_restarts=0, deadline_s=60.0,
                         poll_interval=0.05)
        report = lp.run()
        assert report["completed"] == [0]
        assert report["planned_leaves"] == 1
        assert report["restarts"] == 0         # budget untouched
        assert report["budget_used"][0] == 0
        causes = [(e["cause"], e.get("planned")) for e in report["leaves"]]
        assert ("preempted", True) in causes
        assert report["preempt_notices"] == 1  # observed via the ledger
        assert report["leaked_killed"] == 0

    def test_preempt_soak_quick_end_to_end(self, tmp_path):
        """The full announced-failure soak (the bench gate's engine) in
        quick mode — the acceptance e2e."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "chaos_soak", os.path.join(_REPO, "scripts", "chaos_soak.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        out = mod.run_preempt_soak(quick=True, root=str(tmp_path))
        assert out["soak_ok"], json.dumps(out, indent=1)[:3000]
        assert out["emergency_within_grace"] and out["zero_steps_lost"]
        assert out["budget_untouched"] and out["straggler_flagged"]
        assert out["coord_ok"] and out["off_bitwise"]
