"""The production flywheel (serving/lifecycle.py) + its seams:

  - registry lineage provenance: records, ``lineage()``,
    ``rollback_target()`` picking the last *eval-passing* ancestor
    (never an audit-only eval_passed=False version, never merely v−1)
  - typed CanaryRejectedError off the set_alias canary path (including
    the unfilled-window → rollback-not-promote regression through a
    real Engine), with the default return-record back-compat intact
  - fleet promote() racing a host death between canary pass and the
    first roll step: the alias never moves, the lineage target is
    untouched
  - ElasticTrainer run_id / final_checkpoint_path + CheckpointManager
    registry-provenance sidecar (which checkpoint became which version)
  - PromotionPipeline: happy path through a live fleet, eval-gate
    rollback, canary rollback, mid-roll host-death rollback to the
    lineage target, bounded retries, per-stage deadlines, and
    controller-crash resume from the journal
"""

import json
import math
import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.multilayer import (
    MultiLayerNetwork, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel.elastic import CheckpointManager, ElasticTrainer
from deeplearning4j_tpu.serving import (
    CanaryRejectedError, Engine, EvalGate, FleetRouter, ModelRegistry,
    PipelineJournal, PipelineStageError, PromotionPipeline,
    StageDeadlineError, data_fingerprint, weights_sha,
)


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr=0.05))
            .layer(Dense(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _toy_data(n=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(features=x, labels=y)


class _Host:
    """Scriptable fleet host (test_fleet.py's _FakeEngine, trimmed)."""

    def __init__(self, tag="m:v1"):
        self.tag = tag
        self.swap_exc = None
        self.swaps = []

    def output_async(self, x, slo_ms=None):
        from concurrent.futures import Future
        fut = Future()
        fut.set_result(self.tag)
        return fut

    def swap_model(self, model, tag=None, warm_bundle=None):
        if self.swap_exc is not None:
            exc, self.swap_exc = self.swap_exc, None
            raise exc
        self.swaps.append(tag)
        self.tag = tag

    @property
    def current_tag(self):
        return self.tag

    def metrics_snapshot(self):
        return {"queue_depth": 0}

    def shutdown(self):
        pass


def _fleet(n=2, tag="m:v1"):
    router = FleetRouter(start_watchdog=False)
    hosts = []
    for i in range(n):
        h = _Host(tag=tag)
        hosts.append(h)
        router.add_host(f"h{i}", engine=h)
    return router, hosts


class _Model:
    """Cheap model with distinguishable params per version."""

    def __init__(self, v):
        self.v = v
        self.params = {"w": np.full((2, 2), float(v), np.float32)}

    def output(self, x):
        return np.asarray(x, np.float32) * self.v


class _Calc:
    minimize_score = False

    def __init__(self, score=0.9):
        self.score = score

    def calculate_score(self, model):
        s = self.score
        return s(model) if callable(s) else s


# ---------------------------------------------------------------------------
# registry lineage
# ---------------------------------------------------------------------------

class TestLineage:
    def test_records_are_normalized_and_immutable_copies(self):
        reg = ModelRegistry()
        v = reg.register("m", _Model(1),
                         lineage={"run_id": "r1", "eval_score": 0.9,
                                  "eval_passed": True, "extra": "kept"})
        rec = reg.lineage("m", v)
        assert rec["run_id"] == "r1" and rec["extra"] == "kept"
        assert rec["name"] == "m" and rec["version"] == v
        # unset LINEAGE_FIELDS are present as None (stable schema)
        assert rec["weights_sha"] is None and rec["parent_version"] is None
        rec["run_id"] = "tampered"
        assert reg.lineage("m", v)["run_id"] == "r1"
        assert reg.lineage("m", 999) is None

    def test_lineage_listing_version_ascending(self):
        reg = ModelRegistry()
        reg.register("m", _Model(1), version=3, lineage={"run_id": "c"})
        reg.register("m", _Model(2), version=1, lineage={"run_id": "a"})
        reg.register("m", _Model(3), version=2)   # no lineage — skipped
        assert [r["run_id"] for r in reg.lineage("m")] == ["a", "c"]

    def test_rollback_target_follows_parent_chain_not_version_minus_1(self):
        reg = ModelRegistry()
        v1 = reg.register("m", _Model(1),
                          lineage={"eval_passed": True, "run_id": "a"})
        v2 = reg.register("m", _Model(2),
                          lineage={"eval_passed": False, "run_id": "b",
                                   "parent_version": v1})
        v3 = reg.register("m", _Model(3),
                          lineage={"eval_passed": False, "run_id": "c",
                                   "parent_version": v2})
        # v3's rollback target skips the failing v2 straight to v1
        assert reg.rollback_target("m", version=v3) == v1
        assert reg.rollback_target("m") == v1   # default: newest

    def test_rollback_target_descending_fallback_without_chain(self):
        reg = ModelRegistry()
        v1 = reg.register("m", _Model(1),
                          lineage={"eval_passed": True})
        reg.register("m", _Model(2))              # no lineage — not passing
        v3 = reg.register("m", _Model(3),
                          lineage={"eval_passed": False})
        assert reg.rollback_target("m", version=v3) == v1

    def test_rollback_target_none_when_no_passing_ancestor(self):
        reg = ModelRegistry()
        reg.register("m", _Model(1), lineage={"eval_passed": False})
        assert reg.rollback_target("m") is None
        with pytest.raises(KeyError):
            reg.rollback_target("ghost")

    def test_rollback_target_survives_parent_cycle(self):
        reg = ModelRegistry()
        v1 = reg.register("m", _Model(1),
                          lineage={"eval_passed": False, "parent_version": 2})
        reg.register("m", _Model(2),
                     lineage={"eval_passed": False, "parent_version": v1})
        assert reg.rollback_target("m") is None   # terminates, no hang

    def test_load_stamps_checkpoint_path_into_lineage(self, tmp_path):
        from deeplearning4j_tpu.utils.serializer import save_model
        net = _mlp()
        p = str(tmp_path / "m.zip")
        save_model(net, p)
        reg = ModelRegistry()
        v = reg.load("m", p, lineage={"run_id": "r9", "eval_passed": True})
        rec = reg.lineage("m", v)
        assert rec["checkpoint_path"] == p and rec["run_id"] == "r9"
        assert reg.checkpoint_path("m", v) == p


# ---------------------------------------------------------------------------
# typed canary rejection
# ---------------------------------------------------------------------------

class TestCanaryRejectedError:
    def _reg_with_canary_vote(self, vote):
        reg = ModelRegistry()
        v1 = reg.register("m", _Model(1))
        v2 = reg.register("m", _Model(2))
        reg.set_alias("m", "prod", v1)
        swaps = []
        reg.subscribe("m", "prod", lambda v, m: swaps.append(v),
                      canary=lambda v, m, **kw: dict(vote))
        return reg, v1, v2, swaps

    def test_raise_on_reject_surfaces_typed_error(self):
        vote = {"promote": False, "tag": "m:v2",
                "reasons": ["error rate 0.5 > max 0.0"]}
        reg, v1, v2, _ = self._reg_with_canary_vote(vote)
        with pytest.raises(CanaryRejectedError) as ei:
            reg.set_alias("m", "prod", v2, canary=0.5, raise_on_reject=True)
        err = ei.value
        assert err.name == "m" and err.alias == "prod"
        assert err.incumbent == v1 and err.candidate == v2
        assert err.reasons == ["error rate 0.5 > max 0.0"]
        assert err.record["promoted"] is False
        assert "error rate" in str(err)
        # the alias never moved; the rejection is in canary_history
        assert reg.resolve("m", "prod")[0] == v1
        assert reg.canary_history("m")[-1]["promoted"] is False

    def test_default_returns_record_back_compat(self):
        vote = {"promote": False, "reasons": ["nope"]}
        reg, v1, v2, _ = self._reg_with_canary_vote(vote)
        record = reg.set_alias("m", "prod", v2, canary=0.5)
        assert record["promoted"] is False
        assert reg.resolve("m", "prod")[0] == v1

    def test_promoted_canary_never_raises(self):
        vote = {"promote": True, "reasons": []}
        reg, v1, v2, _ = self._reg_with_canary_vote(vote)
        record = reg.set_alias("m", "prod", v2, canary=0.5,
                               raise_on_reject=True)
        assert record["promoted"] is True
        assert reg.resolve("m", "prod")[0] == v2

    def test_unfilled_window_rolls_back_not_promotes_through_engine(self):
        """Regression (PR 7 gap): a canary whose mirror window never
        fills — zero traffic during the evaluation — must vote rollback
        ("window incomplete"), and through the new API that is a typed
        rejection with the alias still on the incumbent."""
        reg = ModelRegistry()
        v1 = reg.register("m", _mlp(1))
        reg.set_alias("m", "prod", v1)
        v2 = reg.register("m", _mlp(2))
        eng = Engine.from_registry(reg, "m", "prod", replicas=1,
                                   max_batch=4, slo_ms=10_000.0)
        eng.load()
        try:
            with pytest.raises(CanaryRejectedError) as ei:
                reg.set_alias("m", "prod", v2, canary=0.5,
                              canary_window=4, canary_timeout_s=0.3,
                              raise_on_reject=True)
            assert any("window incomplete" in r for r in ei.value.reasons)
            assert reg.resolve("m", "prod")[0] == v1
            assert eng.current_tag == "m:v1"
        finally:
            eng.shutdown()


# ---------------------------------------------------------------------------
# fleet promote() vs host death before the first roll step
# ---------------------------------------------------------------------------

class TestPromoteRace:
    def test_host_death_after_canary_before_first_roll_step(self):
        """A host dying in the gap between canary pass and the first
        roll step: promote() must fail the roll, never move the alias,
        and leave the lineage rollback target untouched."""
        reg = ModelRegistry()
        v1 = reg.register("m", _Model(1),
                          lineage={"eval_passed": True, "run_id": "a"})
        reg.set_alias("m", "prod", v1)
        v2 = reg.register("m", _Model(2),
                          lineage={"eval_passed": True, "run_id": "b",
                                   "parent_version": v1})
        router, hosts = _fleet(n=3, tag="m:v1")
        # the FIRST host to be rolled dies at its swap — nothing swapped
        hosts[0].swap_exc = RuntimeError("host died before first roll step")
        report = router.promote(reg, "m", version=v2)
        assert not report["ok"] and report["swapped"] == []
        assert reg.resolve("m", "prod")[0] == v1          # alias never moved
        assert router.current_tag == "m:v1"
        assert reg.rollback_target("m", version=v2) == v1  # target untouched
        assert all(h.swaps == [] for h in hosts)
        router.shutdown()


# ---------------------------------------------------------------------------
# elastic seams: run_id, final checkpoint, registry provenance
# ---------------------------------------------------------------------------

class TestElasticSeams:
    def test_run_id_and_final_checkpoint_path(self, tmp_path):
        net = _mlp()
        tr = ElasticTrainer(net, checkpoint_dir=str(tmp_path),
                            checkpoint_every=2, run_id="run-abc")
        assert tr.run_id == "run-abc"
        assert tr.final_checkpoint_path is None
        tr.fit(_toy_data(), epochs=1)
        p = tr.final_checkpoint_path
        assert p is not None and os.path.exists(p)
        assert tr.recovery_stats()["run_id"] == "run-abc"
        # default run_id: generated, unique per trainer
        ids = {ElasticTrainer(_mlp(), checkpoint_dir=str(tmp_path / f"d{i}"),
                              ).run_id for i in range(3)}
        assert len(ids) == 3 and all(ids)

    def test_resume_recovers_final_checkpoint_path(self, tmp_path):
        tr = ElasticTrainer(_mlp(), checkpoint_dir=str(tmp_path),
                            checkpoint_every=2)
        tr.fit(_toy_data(), epochs=1)
        p = tr.final_checkpoint_path
        tr2 = ElasticTrainer(_mlp(), checkpoint_dir=str(tmp_path),
                             checkpoint_every=2)
        tr2.resume()
        assert tr2.final_checkpoint_path == p

    def test_note_registered_sidecar_persists(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        net = _mlp()
        p = mgr.save(net, 10)
        mgr.note_registered(p, "m", 3)
        assert mgr.registered_version(p) == ("m", 3)
        # a fresh manager over the same directory reloads the sidecar
        mgr2 = CheckpointManager(str(tmp_path))
        assert mgr2.registered_version(p) == ("m", 3)
        assert mgr2.registered_version("nope.zip") is None

    def test_unreadable_sidecar_is_tolerated(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with open(mgr._provenance_path(), "w") as f:
            f.write("{not json")
        mgr2 = CheckpointManager(str(tmp_path))   # must not raise
        assert mgr2.registered == {}


# ---------------------------------------------------------------------------
# journal + gate + fingerprints
# ---------------------------------------------------------------------------

class TestJournalAndGate:
    def test_journal_replay_drops_torn_final_line(self, tmp_path):
        j = PipelineJournal(str(tmp_path / "j.jsonl"))
        j.append({"gen": 1, "stage": "TRAIN", "status": "done"})
        j.append({"gen": 1, "stage": "EVAL", "status": "done"})
        with open(j.path, "a") as f:
            f.write('{"gen": 1, "stage": "REGI')   # torn by a crash
        recs = j.replay()
        assert [r["stage"] for r in recs] == ["TRAIN", "EVAL"]
        assert PipelineJournal(str(tmp_path / "absent.jsonl")).replay() == []

    def test_eval_gate_direction_and_nonfinite(self):
        up = EvalGate(_Calc(0.8), threshold=0.5)       # maximize (accuracy)
        assert up.check(None)["passed"]
        assert not EvalGate(_Calc(0.4), threshold=0.5).check(None)["passed"]

        class Loss:
            minimize_score = True
            def calculate_score(self, model): return 0.3
        down = EvalGate(Loss(), threshold=0.5)         # minimize (loss)
        assert down.minimize and down.check(None)["passed"]

        nan = EvalGate(_Calc(float("nan")), threshold=0.5)
        verdict = nan.check(None)
        assert not verdict["passed"] and "non-finite" in verdict["reason"]
        assert math.isnan(verdict["score"])

    def test_weights_sha_and_data_fingerprint(self):
        a, b = _Model(1), _Model(1)
        assert weights_sha(a) == weights_sha(b)
        assert weights_sha(a) != weights_sha(_Model(2))
        ds = _toy_data()
        assert data_fingerprint(ds) == data_fingerprint(ds)
        assert data_fingerprint(ds) != data_fingerprint(_toy_data(seed=1))
        assert data_fingerprint(ds.features) != data_fingerprint(ds)


# ---------------------------------------------------------------------------
# the flywheel controller
# ---------------------------------------------------------------------------

def _pipeline(reg, fleet, train_fn, tmp_path, calc=None, **kw):
    kw.setdefault("build_warm_bundle", False)
    kw.setdefault("journal_path", str(tmp_path / "pipeline.jsonl"))
    gate = EvalGate(calc or _Calc(0.9), threshold=0.5)
    return PromotionPipeline(reg, fleet, "m", train_fn, gate, **kw)


class TestPromotionPipeline:
    def test_happy_path_promotes_through_fleet(self, tmp_path):
        reg = ModelRegistry()
        router, hosts = _fleet(n=2, tag="")
        pipe = _pipeline(reg, router, lambda g: _Model(g), tmp_path,
                         data_slice=_toy_data())
        rep = pipe.run_generation()
        assert rep["outcome"] == "PROMOTED"
        v = rep["version"]
        assert reg.resolve("m", "prod")[0] == v
        assert router.current_tag == f"m:v{v}"
        rec = reg.lineage("m", v)
        assert rec["eval_passed"] and rec["weights_sha"]
        assert rec["data_fingerprint"] == data_fingerprint(_toy_data())
        assert rec["parent_version"] is None
        # second generation chains lineage to the first
        rep2 = pipe.run_generation()
        assert rep2["outcome"] == "PROMOTED"
        assert reg.lineage("m", rep2["version"])["parent_version"] == v
        router.shutdown()

    def test_eval_failure_registers_audit_record_and_rolls_back(self, tmp_path):
        reg = ModelRegistry()
        router, hosts = _fleet(n=2, tag="")
        calc = _Calc(0.9)
        pipe = _pipeline(reg, router, lambda g: _Model(g), tmp_path, calc=calc)
        good = pipe.run_generation()
        calc.score = 0.1
        bad = pipe.run_generation()
        assert bad["outcome"] == "ROLLED_BACK"
        assert bad["rolled_back_to"] == good["version"]
        # the failing version IS registered (audit) but flagged
        rec = reg.lineage("m", bad["version"])
        assert rec["eval_passed"] is False
        assert reg.rollback_target("m") == good["version"]
        assert reg.resolve("m", "prod")[0] == good["version"]
        assert router.current_tag == f"m:v{good['version']}"
        router.shutdown()

    def test_canary_rejection_rolls_back_alias(self, tmp_path):
        reg = ModelRegistry()
        votes = []
        def canary_cb(v, m, **kw):
            vote = {"promote": len(votes) == 0, "reasons": ["regressed p99"]}
            votes.append(vote)
            return vote
        swaps = []
        reg.subscribe("m", "prod", lambda v, m: swaps.append(v),
                      canary=canary_cb)
        pipe = _pipeline(reg, None, lambda g: _Model(g), tmp_path,
                         canary_frac=0.5)
        g1 = pipe.run_generation()        # no incumbent -> plain alias move
        g2 = pipe.run_generation()        # canary vote #1: promote
        assert g2["outcome"] == "PROMOTED"
        g3 = pipe.run_generation()        # canary vote #2: reject
        assert g3["outcome"] == "ROLLED_BACK"
        assert "canary rejected" in g3["reason"]
        assert g3["rolled_back_to"] == g2["version"]
        assert reg.resolve("m", "prod")[0] == g2["version"]
        assert pipe.stats()["rolled_back"] == 1

    def test_mid_roll_host_death_rolls_back_to_lineage_target(self, tmp_path):
        reg = ModelRegistry()
        router, hosts = _fleet(n=3, tag="")
        pipe = _pipeline(reg, router, lambda g: _Model(g), tmp_path,
                         stage_retries=0)
        good = pipe.run_generation()
        hosts[1].swap_exc = RuntimeError("host killed mid-roll")
        bad = pipe.run_generation()
        assert bad["outcome"] == "ROLLED_BACK"
        assert "rolling swap failed" in bad["reason"]
        assert bad["rolled_back_to"] == good["version"]
        # alias (moved by the canary-less flip) came BACK to the target,
        # and the surviving hosts serve it
        assert reg.resolve("m", "prod")[0] == good["version"]
        assert router.current_tag == f"m:v{good['version']}"
        assert router.hosts()["h1"] == "down"
        router.shutdown()

    def test_stage_retries_bounded_and_counted(self, tmp_path):
        reg = ModelRegistry()
        attempts = []
        def flaky(g):
            attempts.append(g)
            if len(attempts) < 3:
                raise OSError("preempted")
            return _Model(g)
        pipe = _pipeline(reg, None, flaky, tmp_path,
                         stage_retries={"TRAIN": 2})
        rep = pipe.run_generation()
        assert rep["outcome"] == "PROMOTED" and len(attempts) == 3
        # exhausted budget -> PipelineStageError -> rolled back
        attempts.clear()
        def dead(g):
            attempts.append(g)
            raise OSError("gone")
        pipe2 = _pipeline(reg, None, dead, tmp_path,
                          journal_path=str(tmp_path / "j2.jsonl"),
                          stage_retries={"TRAIN": 1})
        rep2 = pipe2.run_generation()
        assert rep2["outcome"] == "ROLLED_BACK" and len(attempts) == 2
        assert "TRAIN" in rep2["reason"]

    def test_stage_deadline_enforced(self, tmp_path):
        reg = ModelRegistry()
        t = [0.0]
        def clock():
            return t[0]
        def slow(g):
            t[0] += 99.0
            return _Model(g)
        pipe = _pipeline(reg, None, slow, tmp_path, clock=clock,
                         stage_retries=0, stage_deadline_s={"TRAIN": 5.0})
        rep = pipe.run_generation()
        assert rep["outcome"] == "ROLLED_BACK"
        assert "deadline" in rep["reason"]

    def test_controller_crash_resumes_from_journal(self, tmp_path):
        reg = ModelRegistry()
        trained = []
        def train_fn(g):
            trained.append(g)
            return _Model(g)
        class _Crash(Exception):
            """Simulated controller kill — the stage hook runs OUTSIDE
            the retry machinery, so this propagates like SIGKILL would."""
        boom = {"armed": True}
        def crash_at_canary(stage, gen):
            if stage == "CANARY" and gen == 2 and boom["armed"]:
                boom["armed"] = False
                raise _Crash("controller killed")
        pipe = _pipeline(reg, None, train_fn, tmp_path,
                         stage_hook=crash_at_canary)
        pipe.run_generation()                       # gen 1 promotes clean
        with pytest.raises(_Crash):
            pipe.run_generation()                   # gen 2 dies at CANARY
        assert trained == [1, 2]
        # a NEW controller over the same journal resumes gen 2 at CANARY:
        # TRAIN is NOT re-run, the registered version is reused
        pipe2 = _pipeline(reg, None, train_fn, tmp_path)
        state = pipe2.resume()
        assert state["partial"] == 2
        rep = pipe2.run_generation()
        assert rep["gen"] == 2 and rep["outcome"] == "PROMOTED"
        assert trained == [1, 2]                    # no retrain
        assert len(reg.versions("m")) == 2          # no duplicate register
        assert pipe2.stats()["resumes"] == 1

    def test_run_counts_journaled_generations(self, tmp_path):
        reg = ModelRegistry()
        pipe = _pipeline(reg, None, lambda g: _Model(g), tmp_path)
        reports = pipe.run(generations=3)
        assert [r["gen"] for r in reports] == [1, 2, 3]
        # a resumed controller sees them complete; run(3) is a no-op
        pipe2 = _pipeline(reg, None, lambda g: _Model(g), tmp_path)
        assert len(pipe2.run(generations=3)) == 3
        assert len(reg.versions("m")) == 3

    def test_elastic_trainer_result_stamps_lineage(self, tmp_path):
        reg = ModelRegistry()
        def train_fn(g):
            tr = ElasticTrainer(_mlp(g), checkpoint_dir=str(tmp_path / f"g{g}"),
                                checkpoint_every=2, run_id=f"run-{g}")
            tr.fit(_toy_data(), epochs=1)
            return tr
        pipe = _pipeline(reg, None, train_fn, tmp_path)
        rep = pipe.run_generation()
        assert rep["outcome"] == "PROMOTED"
        rec = reg.lineage("m", rep["version"])
        assert rec["run_id"] == "run-1"
        assert rec["checkpoint_path"] and os.path.exists(rec["checkpoint_path"])
        assert reg.checkpoint_path("m", rep["version"]) == rec["checkpoint_path"]
        # CheckpointManager knows which checkpoint became which version
        mgr = CheckpointManager(str(tmp_path / "g1"))
        assert mgr.registered_version(rec["checkpoint_path"]) == \
            ("m", rep["version"])
