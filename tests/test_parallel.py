"""Parallelism: mesh building, DP/TP sharded training parity, inference.

The reference's distributed tests run Spark on local[N] in-process
(BaseSparkTest.java:89); ours run on the 8-virtual-device CPU mesh.
The key test is PARITY: sharded training must produce the same loss curve
as single-device training — the property the reference only approximates
(model averaging) but GSPMD per-step psum achieves exactly.
"""

import numpy as np
import pytest
import jax

from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.parallel import ParallelInference, ShardedTrainer, build_mesh
from deeplearning4j_tpu.parallel.mesh import infer_param_shardings


def _blobs(n=128, f=12, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, f)) * 3
    ys = rng.integers(0, classes, size=n)
    xs = (centers[ys] + rng.normal(size=(n, f))).astype(np.float32)
    return xs, np.eye(classes, dtype=np.float32)[ys]


def _mlp(seed=7, lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr=lr))
            .layer(Dense(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(12)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestMesh:
    def test_build_default(self):
        mesh = build_mesh()
        assert mesh.shape["data"] == len(jax.devices())

    def test_build_factored(self):
        mesh = build_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_build_inferred_axis(self):
        mesh = build_mesh({"data": -1, "model": 2})
        assert mesh.shape["data"] == len(jax.devices()) // 2

    def test_bad_factorization(self):
        with pytest.raises(ValueError, match="devices"):
            build_mesh({"data": 3, "model": 5})

    def test_param_sharding_rules(self):
        mesh = build_mesh({"data": 4, "model": 2})
        net = _mlp()
        sh = infer_param_shardings(net.params, mesh)
        # Dense W [12,16] → last axis sharded on model
        assert sh[0]["W"].spec == jax.sharding.PartitionSpec(None, "model")
        # bias [16] divisible → sharded
        assert sh[0]["b"].spec in (jax.sharding.PartitionSpec("model"),
                                   jax.sharding.PartitionSpec())


class TestShardedTraining:
    def test_dp_matches_single_device(self):
        """Same data, same seed: DP-sharded loss curve == single-device curve.
        (The reference's CPU-vs-backend parity test style, SURVEY.md §4.4.)"""
        xs, ys = _blobs()
        single = _mlp(seed=3)
        sharded_net = _mlp(seed=3)
        mesh = build_mesh({"data": 8})
        trainer = ShardedTrainer(sharded_net, mesh)
        ds = DataSet(xs, ys)
        for i in range(5):
            l1 = single.fit_batch(ds)
            l2 = trainer.fit_batch(ds)
            np.testing.assert_allclose(l1, l2, rtol=2e-4,
                                       err_msg=f"divergence at step {i}")

    def test_tp_matches_single_device(self):
        xs, ys = _blobs()
        single = _mlp(seed=4)
        sharded_net = _mlp(seed=4)
        mesh = build_mesh({"data": 2, "model": 4})
        trainer = ShardedTrainer(sharded_net, mesh)
        ds = DataSet(xs, ys)
        for _ in range(5):
            l1 = single.fit_batch(ds)
            l2 = trainer.fit_batch(ds)
            np.testing.assert_allclose(l1, l2, rtol=2e-4)

    def test_sharded_learns(self):
        xs, ys = _blobs(n=256)
        net = _mlp(seed=5, lr=0.1)
        trainer = ShardedTrainer(net, build_mesh({"data": 4, "model": 2}))
        losses = trainer.fit(ListDataSetIterator.from_arrays(xs, ys, 64), epochs=20)
        assert losses[-1] < 0.3 * losses[0]

    def test_batch_not_divisible_raises(self):
        net = _mlp()
        trainer = ShardedTrainer(net, build_mesh({"data": 8}))
        xs, ys = _blobs(n=30)  # 30 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            trainer.fit_batch(DataSet(xs, ys))


class TestParallelInference:
    def test_batched_requests(self):
        net = _mlp()
        xs, _ = _blobs(n=64)
        server = ParallelInference(net, max_batch=16)
        try:
            direct = net.output(xs[:4])
            futs = [server.output_async(xs[i:i + 4]) for i in range(0, 32, 4)]
            outs = [f.result(timeout=60) for f in futs]
            assert all(o.shape == (4, 3) for o in outs)
            np.testing.assert_allclose(outs[0], direct, rtol=2e-5, atol=1e-6)
        finally:
            server.shutdown()

    def test_error_propagates(self):
        class Broken:
            def output(self, x):
                raise RuntimeError("boom")
        server = ParallelInference(Broken())
        try:
            with pytest.raises(RuntimeError, match="boom"):
                server.output(np.ones((2, 3), np.float32))
        finally:
            server.shutdown()
