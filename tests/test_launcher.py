"""Pod-scale elastic runtime (PR 6): coordinator bootstrap timeout,
membership epochs with a fake clock (heartbeat expiry, join during
recovery, two concurrent leaves), the process-liveness FailureDetector,
the multi-host CheckpointManager write guard, slice-granular
ElasticTrainer recovery over a shrunken dcn mesh, proc_kill/proc_hang
fault determinism, and the PodLauncher's fork/heal/leak-check loop."""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.multilayer import (
    MultiLayerNetwork, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel import (
    CheckpointManager, CoordinatorUnreachableError, ElasticTrainer,
    FailureDetector, FaultKind, FaultSchedule, Heartbeat, HostLostError,
    Membership, MembershipChangedError, PodLauncher, ProcessFailureDetector,
    ShardedTrainer, build_two_tier_mesh, surviving_mesh,
    validate_coordinator_address,
)
from deeplearning4j_tpu.parallel.distributed import (
    ENV_PROCESS_ID, ENV_RUN_DIR, initialize,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# coordinator bootstrap: bounded timeout, no hang (satellite + acceptance)
# ---------------------------------------------------------------------------

class TestCoordinatorBootstrap:
    def test_address_validation(self):
        assert validate_coordinator_address("10.0.0.1:8476") == \
            ("10.0.0.1", 8476)
        assert validate_coordinator_address("[::1]:99") == ("::1", 99)
        for bad in ("nohost", ":1234", "host:", "host:0", "host:70000",
                    "host:port", 12345):
            with pytest.raises(ValueError):
                validate_coordinator_address(bad)

    def test_initialize_rejects_bad_address_up_front(self):
        with pytest.raises(ValueError, match="coordinator_address"):
            initialize("not-an-address", 2, 1)

    def test_initialize_rejects_bad_process_id(self):
        with pytest.raises(ValueError, match="out of range"):
            initialize("127.0.0.1:9999", 2, 5)

    def test_initialize_rejects_bad_timeout(self):
        with pytest.raises(ValueError, match="timeout_s"):
            initialize("127.0.0.1:9999", 2, 1, timeout_s=0)

    def test_dead_coordinator_fails_within_timeout(self):
        """Regression (the indefinite-hang bug): joining a coordinator
        nobody listens on must raise CoordinatorUnreachableError within
        the configured budget, not block forever."""
        import socket
        with socket.socket() as s:      # a port that is definitely dead
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        t0 = time.monotonic()
        with pytest.raises(CoordinatorUnreachableError, match="unreachable"):
            initialize(f"127.0.0.1:{port}", num_processes=2, process_id=1,
                       timeout_s=1.5)
        elapsed = time.monotonic() - t0
        assert elapsed < 15.0, f"took {elapsed:.1f}s — the hang is back"


# ---------------------------------------------------------------------------
# membership transitions (fake clock)
# ---------------------------------------------------------------------------

class TestMembership:
    def test_beat_alive_and_expiry(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.beat(1)
        assert m.alive() == [0, 1]
        clock.t += 6.0
        m.beat(0)                       # only host 0 keeps beating
        assert m.alive() == [0]

    def test_epoch_bumps_once_per_transition_batch(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        assert m.epoch == 0             # no ledger before the first refresh
        for i in (0, 1, 2):
            m.beat(i)
        assert m.refresh() == 1         # formation
        assert m.refresh() == 1         # no change → no bump
        # two CONCURRENT leaves: both expire in the same scan → ONE bump
        clock.t += 6.0
        m.beat(0)
        assert m.refresh() == 2
        assert m.members() == [0]

    def test_join_during_recovery(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.beat(1)
        m.refresh()
        clock.t += 6.0                  # host 1 dies...
        m.beat(0)
        assert m.refresh() == 2
        m.beat(3)                       # ...and host 3 joins MID-recovery
        assert m.refresh() == 3
        assert m.members() == [0, 3]

    def test_ledger_persists_across_instances(self, tmp_path):
        clock = FakeClock()
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.refresh()
        m2 = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        assert m2.epoch == 1 and m2.members() == [0]

    def test_torn_and_foreign_heartbeat_files_ignored(self, tmp_path):
        m = Membership(str(tmp_path), heartbeat_timeout=5.0)
        (tmp_path / "hb_9.json").write_text("{torn")
        (tmp_path / "hb_x.json").write_text("{}")
        m.beat(2)
        assert m.alive() == [2]

    def test_remove_deregisters(self, tmp_path):
        m = Membership(str(tmp_path), heartbeat_timeout=5.0)
        m.beat(4)
        m.remove(4)
        assert m.alive() == []

    def test_rejects_nonpositive_timeout(self, tmp_path):
        with pytest.raises(ValueError):
            Membership(str(tmp_path), heartbeat_timeout=0)


class TestProcessFailureDetector:
    def _members(self, tmp_path, clock):
        m = Membership(str(tmp_path), heartbeat_timeout=5.0, clock=clock)
        m.beat(0)
        m.beat(1)
        m.refresh()
        return m

    def test_lost_host_raises_recoverable(self, tmp_path):
        clock = FakeClock()
        m = self._members(tmp_path, clock)
        det = ProcessFailureDetector(m)
        det.check()                     # baseline observation
        clock.t += 6.0
        m.beat(0)
        with pytest.raises(HostLostError) as exc:
            det.check()
        assert exc.value.lost == [1]
        assert FailureDetector().is_recoverable(exc.value)
        det.check()                     # transition consumed — no re-raise

    def test_join_raises_membership_changed(self, tmp_path):
        clock = FakeClock()
        m = self._members(tmp_path, clock)
        det = ProcessFailureDetector(m)
        det.check()
        m.beat(2)
        with pytest.raises(MembershipChangedError) as exc:
            det.check()
        assert exc.value.joined == [2]
        assert FailureDetector().is_recoverable(exc.value)

    def test_join_ignored_when_configured(self, tmp_path):
        clock = FakeClock()
        m = self._members(tmp_path, clock)
        det = ProcessFailureDetector(m, recover_on_join=False)
        det.check()
        m.beat(2)
        det.check()                     # no raise


# ---------------------------------------------------------------------------
# multi-host CheckpointManager (satellite)
# ---------------------------------------------------------------------------

class _StubNet:
    def save(self, path, save_updater=True):
        with open(path, "wb") as f:
            f.write(b"stub-checkpoint")


class TestCheckpointManagerMultiHost:
    def test_single_process_default_is_writer(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        assert cm.is_writer and cm.process_id == 0
        assert cm.save(_StubNet(), 3) is not None
        assert len(cm.list_checkpoints()) == 1

    def test_nonzero_process_is_reader_no_tmp_race(self, tmp_path):
        writer = CheckpointManager(str(tmp_path), process_id=0)
        other = CheckpointManager(str(tmp_path), process_id=1)
        assert not other.is_writer
        assert other.save(_StubNet(), 5) is None        # no-op, no .tmp
        assert other.save_async(_StubNet(), 5) is None
        assert os.listdir(tmp_path) == []
        path = writer.save(_StubNet(), 5)
        assert path is not None
        # readers still restore the coordinator's checkpoints (host rejoin)
        model, step = other.restore_latest(lambda p: "loaded")
        assert (model, step) == ("loaded", 5)

    def test_process_id_from_launcher_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv(ENV_PROCESS_ID, "3")
        cm = CheckpointManager(str(tmp_path))
        assert cm.process_id == 3 and not cm.is_writer
        monkeypatch.setenv(ENV_PROCESS_ID, "junk")
        with pytest.raises(ValueError):
            CheckpointManager(str(tmp_path))

    def test_forced_roles(self, tmp_path):
        assert CheckpointManager(str(tmp_path), role="writer",
                                 process_id=7).is_writer
        assert not CheckpointManager(str(tmp_path), role="reader",
                                     process_id=0).is_writer
        with pytest.raises(ValueError):
            CheckpointManager(str(tmp_path), role="bogus")

    def test_per_host_shards_distinct_names(self, tmp_path):
        h0 = CheckpointManager(str(tmp_path), role="per_host", process_id=0)
        h1 = CheckpointManager(str(tmp_path), role="per_host", process_id=1)
        h0.save(_StubNet(), 2)
        h1.save(_StubNet(), 2)          # same step, distinct file — no race
        names = sorted(os.listdir(tmp_path))
        assert names == ["checkpoint_0000000002.h0.zip",
                         "checkpoint_0000000002.h1.zip"]
        # each host lists only its OWN shards; a shared-writer manager
        # ignores per-host shards entirely
        assert [s for _, s in h0.list_checkpoints()] == [2]
        assert h0.list_checkpoints()[0][0].endswith(".h0.zip")
        assert CheckpointManager(str(tmp_path),
                                 process_id=0).list_checkpoints() == []

    def test_stale_tmp_cleanup_respects_ownership(self, tmp_path):
        mine = tmp_path / "checkpoint_0000000001.zip.tmp"
        theirs = tmp_path / "checkpoint_0000000001.h1.zip.tmp"
        mine.write_bytes(b"torn")
        theirs.write_bytes(b"torn")
        CheckpointManager(str(tmp_path), process_id=1)   # reader: cleans nothing
        assert mine.exists() and theirs.exists()
        CheckpointManager(str(tmp_path), process_id=0)   # writer: own names only
        assert not mine.exists() and theirs.exists()
        CheckpointManager(str(tmp_path), role="per_host", process_id=1)
        assert not theirs.exists()


# ---------------------------------------------------------------------------
# slice-granular recovery: host leave → smaller dcn mesh → restore → continue
# ---------------------------------------------------------------------------

def _small_net(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(lr=0.1))
            .layer(Dense(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _blob_data(n=64):
    rng = np.random.default_rng(0)
    xs = np.concatenate([rng.normal(-2, 1, (n // 2, 4)),
                         rng.normal(2, 1, (n // 2, 4))]).astype(np.float32)
    ys = np.zeros((n, 2), np.float32)
    ys[:n // 2, 0] = 1
    ys[n // 2:, 1] = 1
    return DataSet(xs, ys)


class TestSliceGranularRecovery:
    def test_surviving_mesh_shrinks_dcn(self):
        mesh = surviving_mesh([0], n_slices=2)
        assert dict(mesh.shape)["dcn"] == 1
        assert mesh.devices.size == 4
        import jax
        assert list(mesh.devices.flat) == jax.devices()[:4]
        both = surviving_mesh([0, 1], n_slices=2)
        assert dict(both.shape)["dcn"] == 2 and both.devices.size == 8

    def test_surviving_mesh_validation(self):
        with pytest.raises(ValueError):
            surviving_mesh([], n_slices=2)
        with pytest.raises(ValueError):
            surviving_mesh([2], n_slices=2)
        with pytest.raises(ValueError):
            surviving_mesh([0], n_slices=3)   # 8 devices % 3

    def test_two_tier_trainer_from_megascale_env(self, monkeypatch):
        """ShardedTrainer.two_tier sizes the dcn axis from the multislice
        runtime's env contract (which the launcher propagates)."""
        monkeypatch.setenv("MEGASCALE_NUM_SLICES", "2")
        trainer = ShardedTrainer.two_tier(_small_net())
        assert dict(trainer.mesh.shape) == {"dcn": 2, "data": 4}
        monkeypatch.delenv("MEGASCALE_NUM_SLICES")
        t1 = ShardedTrainer.two_tier(_small_net(), n_slices=1)
        assert dict(t1.mesh.shape) == {"dcn": 1, "data": 8}

    def test_launcher_exports_megascale_env(self, tmp_path):
        env = dict(os.environ)
        env.pop("MEGASCALE_NUM_SLICES", None)
        launcher = PodLauncher(["true"], num_workers=2,
                               run_dir=str(tmp_path), base_env=env,
                               bootstrap="distributed")
        worker_env = launcher._env_for(launcher.handles[1])
        assert worker_env["MEGASCALE_NUM_SLICES"] == "2"
        assert worker_env["DL4J_TPU_COORDINATOR"].startswith("127.0.0.1:")
        replica = PodLauncher(["true"], num_workers=2,
                              run_dir=str(tmp_path), base_env=env,
                              megascale_slices=4)
        assert replica._env_for(replica.handles[0])[
            "MEGASCALE_NUM_SLICES"] == "4"

    def test_host_leave_rebuilds_smaller_mesh_and_continues(self, tmp_path):
        """A lost slice mid-training: the membership check raises
        HostLostError, ElasticTrainer's EXISTING recovery loop (backoff →
        rebuild_fn → restore) re-provisions a dcn=1 mesh over the
        surviving half and training continues from the checkpoint."""
        net = _small_net()
        ds = _blob_data()
        lost = {"pending": None}

        def membership_check():
            if lost["pending"]:
                err = lost["pending"]
                lost["pending"] = None
                raise err

        def rebuild():
            return ShardedTrainer(net, surviving_mesh([0], n_slices=2))

        et = ElasticTrainer(ShardedTrainer(net, build_two_tier_mesh(2)),
                            str(tmp_path), checkpoint_every=2, sync_every=1,
                            rebuild_fn=rebuild,
                            membership_check=membership_check)
        before = [float(et.fit_batch(ds)) for _ in range(4)]
        lost["pending"] = HostLostError([1], epoch=2)
        after = [float(et.fit_batch(ds)) for _ in range(4)]
        assert et.total_restarts == 1
        assert dict(et.trainer.mesh.shape)["dcn"] == 1
        assert et.trainer.mesh.devices.size == 4
        # restored from the step-4 checkpoint and kept learning
        assert after[-1] < before[0]


# ---------------------------------------------------------------------------
# proc_kill / proc_hang faults
# ---------------------------------------------------------------------------

class TestProcessFaults:
    def test_process_kinds_registered(self):
        assert FaultKind.PROC_KILL in FaultKind.ALL
        assert FaultKind.PROC_HANG in FaultKind.ALL
        assert set(FaultKind.PROCESS_KINDS) == {FaultKind.PROC_KILL,
                                                FaultKind.PROC_HANG,
                                                FaultKind.COORD_KILL,
                                                FaultKind.PREEMPT_NOTICE}

    def test_scripted_schedule_accepts_proc_kinds(self):
        s = FaultSchedule.scripted({3: FaultKind.PROC_KILL,
                                    7: [FaultKind.PROC_HANG]})
        assert s.pop(3) == ["proc_kill"]
        assert s.pop(7) == ["proc_hang"]

    def test_random_schedule_with_proc_kinds_is_deterministic(self):
        kinds = list(FaultKind.PROCESS_KINDS)
        a = FaultSchedule.random(seed=11, n_steps=200, rate=0.1, kinds=kinds)
        b = FaultSchedule.random(seed=11, n_steps=200, rate=0.1, kinds=kinds)
        assert a.faults == b.faults and a.pending() > 0
        c = FaultSchedule.random(seed=12, n_steps=200, rate=0.1, kinds=kinds)
        assert a.faults != c.faults

    def test_cli_parse_proc_kinds(self):
        from deeplearning4j_tpu.cli import _parse_chaos
        sched, seed, hang, _slow = _parse_chaos(
            "proc_kill@4,proc_hang@9,seed=2")
        assert sched.faults == {4: ["proc_kill"], 9: ["proc_hang"]}
        assert seed == 2

    def test_proc_kill_self_injects_at_exact_step(self, tmp_path):
        """The fault is step-deterministic: a worker scheduled with
        proc_kill@3 dies by SIGKILL after completing exactly 2 steps —
        every run, no launcher-side polling race."""
        progress = tmp_path / "progress.txt"
        script = textwrap.dedent(f"""
            import os, sys
            os.environ["JAX_PLATFORMS"] = "cpu"
            sys.path.insert(0, {_REPO!r})
            from deeplearning4j_tpu.parallel.chaos import (
                ChaosInjector, FaultKind, FaultSchedule,
            )
            class T:
                net = None
                def fit_batch(self, ds):
                    return 0.0
            inj = ChaosInjector(
                T(), FaultSchedule.scripted({{3: FaultKind.PROC_KILL}}))
            with open({str(progress)!r}, "a") as f:
                for _ in range(5):
                    inj.fit_batch(None)
                    f.write("step\\n")
                    f.flush()
        """)
        p = subprocess.run([sys.executable, "-c", script],
                           capture_output=True, timeout=120)
        assert p.returncode == -9, p.stderr.decode()[-500:]
        assert progress.read_text().count("step") == 2


# ---------------------------------------------------------------------------
# Heartbeat + PodLauncher (stdlib workers — no jax import in children)
# ---------------------------------------------------------------------------

class TestHeartbeat:
    def test_beats_and_stops_clean(self, tmp_path):
        m = Membership(str(tmp_path), heartbeat_timeout=5.0)
        hb = Heartbeat(m, process_id=2, interval=0.02,
                       step_fn=lambda: 7).start()
        time.sleep(0.15)
        rec = m.last_beat(2)
        assert rec is not None and rec["step"] == 7
        thread = hb._thread
        hb.stop()
        assert not thread.is_alive()
        assert m.last_beat(2) is None        # deregistered

    def test_start_from_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(ENV_RUN_DIR, raising=False)
        assert Heartbeat.start_from_env() is None
        monkeypatch.setenv(ENV_RUN_DIR, str(tmp_path))
        monkeypatch.setenv(ENV_PROCESS_ID, "4")
        hb = Heartbeat.start_from_env(interval=0.02)
        try:
            assert hb is not None
            time.sleep(0.1)
            assert Membership(str(tmp_path)).last_beat(4) is not None
        finally:
            hb.stop()


# a stdlib-only launcher child: beats the Membership heartbeat format by
# hand (the on-disk contract), with failure modes driven by env
_STDLIB_WORKER = textwrap.dedent("""
    import json, os, sys, time
    i = int(os.environ["DL4J_TPU_PROCESS_ID"])
    run = os.environ["DL4J_TPU_RUN_DIR"]
    inc = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
    mode = os.environ.get("TEST_WORKER_MODE", "ok")
    def beat():
        tmp = os.path.join(run, "hb_%d.json.tmp%d" % (i, os.getpid()))
        with open(tmp, "w") as f:
            json.dump({"process_id": i, "pid": os.getpid(),
                       "step": None, "t": time.time()}, f)
        os.replace(tmp, os.path.join(run, "hb_%d.json" % i))
    if mode == "crash_once" and i == 1 and inc == 0:
        beat(); time.sleep(0.2); sys.exit(3)
    if mode == "hang" and i == 0 and inc == 0:
        beat(); time.sleep(0.3)
        time.sleep(600)            # alive but silent — heartbeat expiry
    for _ in range(8):
        beat(); time.sleep(0.05)
""")


def _stdlib_launcher(tmp_path, mode, **kw):
    env = dict(os.environ)
    env["TEST_WORKER_MODE"] = mode
    defaults = dict(num_workers=2, run_dir=str(tmp_path / "run"),
                    base_env=env, heartbeat_timeout=1.0, max_restarts=2,
                    poll_interval=0.05, deadline_s=60.0)
    defaults.update(kw)
    return PodLauncher([sys.executable, "-c", _STDLIB_WORKER], **defaults)


class TestPodLauncher:
    def test_clean_run_completes_no_leaks(self, tmp_path):
        report = _stdlib_launcher(tmp_path, "ok").run()
        assert report["ok"]
        assert report["completed"] == [0, 1]
        assert report["restarts"] == 0 and report["leaked_killed"] == 0
        assert report["epoch"] >= 1          # formation bumped the ledger

    def test_crash_restarts_worker_as_new_incarnation(self, tmp_path):
        report = _stdlib_launcher(tmp_path, "crash_once").run()
        assert report["ok"] and report["completed"] == [0, 1]
        assert report["restarts"] == 1
        leaves = report["leaves"]
        assert len(leaves) == 1 and leaves[0]["cause"] == "crash" \
            and leaves[0]["rc"] == 3 and leaves[0]["worker"] == 1
        assert report["joins"] == 1
        # the relaunched incarnation got its own log file
        assert (tmp_path / "run" / "logs" / "worker1.inc1.log").exists()

    def test_silent_worker_declared_hung_killed_and_relaunched(self, tmp_path):
        report = _stdlib_launcher(tmp_path, "hang").run()
        assert report["ok"] and report["completed"] == [0, 1]
        assert report["hang_detected"] >= 1
        assert any(e["cause"] == "hang" for e in report["leaves"])
        assert report["restarts"] >= 1 and report["leaked_killed"] == 0

    def test_restart_budget_exhaustion_is_unrecovered(self, tmp_path):
        env = dict(os.environ)
        env["TEST_WORKER_MODE"] = "ok"
        launcher = PodLauncher(
            [sys.executable, "-c", "import sys; sys.exit(4)"],
            num_workers=1, run_dir=str(tmp_path / "run"), base_env=env,
            heartbeat_timeout=1.0, max_restarts=1, poll_interval=0.05,
            deadline_s=30.0)
        report = launcher.run()
        assert not report["ok"] and report["unrecovered"] == [0]
        assert report["restarts"] == 1       # budget spent, then gave up

    def test_chaos_spec_only_reaches_first_incarnation(self, tmp_path):
        probe = textwrap.dedent("""
            import json, os, sys, time
            i = int(os.environ["DL4J_TPU_PROCESS_ID"])
            run = os.environ["DL4J_TPU_RUN_DIR"]
            inc = int(os.environ.get("DL4J_TPU_INCARNATION", "0"))
            spec = os.environ.get("DL4J_TPU_CHAOS")
            with open(os.path.join(run, "spec_%d_%d" % (i, inc)), "w") as f:
                f.write(repr(spec))
            if spec:
                sys.exit(9)    # "the fault fired" — relaunch must be clean
        """)
        launcher = PodLauncher(
            [sys.executable, "-c", probe], num_workers=2,
            run_dir=str(tmp_path / "run"), base_env=dict(os.environ),
            chaos={1: "proc_kill@3"}, heartbeat_timeout=5.0,
            max_restarts=2, poll_interval=0.05, deadline_s=30.0)
        report = launcher.run()
        assert report["ok"] and report["restarts"] == 1
        run = tmp_path / "run"
        assert (run / "spec_0_0").read_text() == "None"
        assert (run / "spec_1_0").read_text() == "'proc_kill@3'"
        assert (run / "spec_1_1").read_text() == "None"

    def test_validation(self, tmp_path):
        with pytest.raises(ValueError):
            PodLauncher(["x"], num_workers=0, run_dir=str(tmp_path))
        with pytest.raises(ValueError):
            PodLauncher(["x"], num_workers=2, run_dir=str(tmp_path),
                        bootstrap="bogus")
        with pytest.raises(ValueError):
            PodLauncher(["x"], num_workers=2, run_dir=str(tmp_path),
                        chaos={5: "proc_kill@1"})


# ---------------------------------------------------------------------------
# the process-scale soak itself (quick mode; heavier → slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestMultiprocSoak:
    def test_quick_multiproc_soak_all_gates(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "chaos_soak", os.path.join(_REPO, "scripts", "chaos_soak.py"))
        soak = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak)
        out = soak.run_multiproc_soak(quick=True, root=str(tmp_path))
        assert out["unrecovered"] == 0
        assert out["off_bitwise"], "launcher machinery changed the math"
        assert out["proc_kill_recovered"] >= 1
        assert out["proc_hang_recovered"] >= 1
        assert out["chaos_loss_bitwise"], \
            "post-resume trajectory diverged from baseline"
        assert out["leaked"] == 0 and out["off_leaked"] == 0
        assert out["writer_guard_ok"] and out["completion_steps_ok"]
        assert out["soak_ok"], out


class TestInjectableLauncherClock:
    def test_pod_launcher_shares_one_injected_clock(self, tmp_path):
        """GC201 regression (graftcheck): launcher event times, notice
        deadlines and heartbeat staleness all read ONE injectable clock
        (shared with the Membership ledger) instead of raw time.time()."""
        t = [5000.0]
        launcher = PodLauncher(["true"], num_workers=1,
                               run_dir=str(tmp_path),
                               clock=lambda: t[0])
        assert launcher.clock() == 5000.0
        assert launcher.membership.clock is launcher.clock
        launcher._t0 = launcher.clock()
        t[0] = 5001.5
        launcher._event("probe", 0)
        assert launcher.events[-1]["t"] == 1.5
