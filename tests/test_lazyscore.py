"""LazyScore / async-fit semantics.

The round-3 performance contract: ``fit_batch`` must not block on a
device→host readback every step (VERDICT round 2, Weak #1).  These tests
pin (a) float-compatibility of the returned score, (b) genuine laziness —
no materialization unless something reads the value, and (c) listener
throttling — only iterations a listener actually formats get synced.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.optimize import (
    CollectScoresIterationListener,
    LazyScore,
    ScoreIterationListener,
)


def _net():
    conf = (NeuralNetConfiguration.builder()
            .layer(Dense(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def _ds(n=32):
    rng = np.random.default_rng(0)
    return DataSet(rng.normal(size=(n, 8)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)])


class TestLazyScore:
    def test_float_protocol(self):
        import jax.numpy as jnp
        s = LazyScore(jnp.float32(2.5))
        assert float(s) == 2.5
        assert s == 2.5 and s < 3 and s > 2
        assert round(s, 1) == 2.5 and isinstance(round(s, 1), float)
        assert hash(s) == hash(2.5)
        assert f"{s:.2f}" == "2.50"
        assert s + 1 == 3.5 and 1 + s == 3.5 and s * 2 == 5.0
        assert np.asarray(s).item() == 2.5
        assert abs(-s) == 2.5

    def test_fit_batch_returns_unmaterialized(self):
        net = _net()
        losses = [net.fit_batch(_ds()) for _ in range(5)]
        assert all(isinstance(l, LazyScore) for l in losses)
        assert not any(l.materialized for l in losses)
        # reading one materializes just that one
        v = float(losses[2])
        assert losses[2].materialized and not losses[3].materialized
        assert np.isfinite(v)

    def test_losses_decrease_when_read(self):
        net = _net()
        ds = _ds()
        losses = [net.fit_batch(ds) for _ in range(40)]
        assert losses[-1] < losses[0]

    def test_listener_throttled_materialization(self):
        net = _net()
        msgs = []
        net.set_listeners(ScoreIterationListener(print_every=5, out=msgs.append))
        ds = _ds()
        scores = [net.fit_batch(ds) for _ in range(10)]
        # iterations 5 and 10 were printed → materialized; the rest stayed lazy
        materialized = [s.materialized for s in scores]
        assert materialized == [False] * 4 + [True] + [False] * 4 + [True]
        assert len(msgs) == 2

    def test_collect_scores_stays_lazy_until_read(self):
        net = _net()
        coll = CollectScoresIterationListener()
        net.set_listeners(coll)
        ds = _ds()
        for _ in range(5):
            net.fit_batch(ds)
        assert len(coll.scores) == 5
        assert not any(s.materialized for _, s in coll.scores)
        vals = [float(s) for _, s in coll.scores]
        assert all(np.isfinite(v) for v in vals)

    def test_device_value_accumulation(self):
        """Epoch-mean loss without per-step sync via device_value()."""
        import jax.numpy as jnp
        net = _net()
        ds = _ds()
        total = None
        for _ in range(4):
            dv = net.fit_batch(ds).device_value()
            total = dv if total is None else total + dv
        mean = float(total) / 4
        assert np.isfinite(mean)

    def test_int_index_streaming_matches_one_hot(self):
        """rnn_time_step accepts [mb]/[mb,t] integer ids and matches the
        dense one-hot stream (the training-side index path's inference
        counterpart)."""
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        V = 7
        conf = (NeuralNetConfiguration.builder()
                .layer(LSTM(n_out=10))
                .layer(RnnOutputLayer(n_out=V, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(V)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        rng = np.random.default_rng(3)
        ids = rng.integers(0, V, (4, 6)).astype(np.int32)
        oh = np.eye(V, dtype=np.float32)
        outs_oh, outs_id = [], []
        for t in range(6):
            outs_oh.append(net.rnn_time_step(oh[ids[:, t]]))
        net.rnn_clear_previous_state()
        for t in range(6):
            outs_id.append(net.rnn_time_step(ids[:, t]))
        np.testing.assert_allclose(np.asarray(outs_oh), np.asarray(outs_id),
                                   rtol=1e-5, atol=1e-6)

    def test_tbptt_stateful_listener_gets_per_chunk_params(self):
        """A requires_model_state listener forces per-chunk stepping so its
        callback observes each chunk's params, not end-of-batch params."""
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.optimize.listeners import TrainingListener

        class Snap(TrainingListener):
            requires_model_state = True

            def __init__(self):
                self.snaps = []

            def iteration_done(self, model, iteration, score):
                self.snaps.append(np.asarray(model.params[0]["W"]).copy())

        conf = (NeuralNetConfiguration.builder()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(5))
                .tbptt(5).build())
        net = MultiLayerNetwork(conf)
        net.init()
        snap = Snap()
        net.set_listeners(snap)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 15, 5)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 15))]
        net.fit_batch(DataSet(x, y))
        assert len(snap.snaps) == 3
        # params must differ between chunk callbacks (per-chunk stepping)
        assert not np.allclose(snap.snaps[0], snap.snaps[1])
        assert not np.allclose(snap.snaps[1], snap.snaps[2])

    def test_int_inputs_respect_bf16_compute_dtype(self):
        """Mixed precision + integer index inputs: the LSTM gather must
        produce COMPUTE-dtype activations (review finding: W.dtype leaked
        through, crashing the TBPTT scan carry under bf16)."""
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.builder()
                .layer(LSTM(n_out=12))
                .layer(RnnOutputLayer(n_out=5, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(5))
                .dtype("float32", "bfloat16")
                .tbptt(5).build())
        net = MultiLayerNetwork(conf)
        net.init()
        rng = np.random.default_rng(0)
        ids_x = rng.integers(0, 5, (4, 10)).astype(np.int32)
        ids_y = rng.integers(0, 5, (4, 10)).astype(np.int32)
        loss = net.fit_batch(DataSet(ids_x, ids_y))
        assert np.isfinite(float(loss))
        # non-TBPTT inference path too
        out = net.output(ids_x)
        assert out.shape == (4, 10, 5)

    def test_materialize_scores_batches_transfers(self):
        from deeplearning4j_tpu.optimize.score import materialize_scores
        net = _net()
        ds = _ds()
        scores = [net.fit_batch(ds) for _ in range(5)]
        assert not any(s.materialized for s in scores)
        materialize_scores(scores)
        assert all(s.materialized for s in scores)
        assert all(np.isfinite(float(s)) for s in scores)

    def test_tbptt_returns_lazy(self):
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        conf = (NeuralNetConfiguration.builder()
                .layer(LSTM(n_out=12))
                .layer(RnnOutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(6))
                .tbptt(5).build())
        net = MultiLayerNetwork(conf)
        net.init()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 10, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (8, 10))]
        loss = net.fit_batch(DataSet(x, y))
        assert isinstance(loss, LazyScore) and not loss.materialized
        assert np.isfinite(float(loss))
