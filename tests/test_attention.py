"""Attention stack tests: flash kernel parity, layer gradients, masking.

DL4J 0.9.2 has no attention; these exercise the TPU-first long-context
path (SURVEY.md §5/§7-M5): ops.attention (XLA + pallas flash kernel) and
the SelfAttention / LearnedSelfAttention layers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    LearnedSelfAttention, OutputLayer, RnnOutputLayer, SelfAttention,
    GlobalPooling,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.updaters import Adam, NoOp
from deeplearning4j_tpu.ops.attention import flash_mha, mha
from deeplearning4j_tpu.utils.gradient_check import check_gradients
from deeplearning4j_tpu.utils.jax_compat import enable_x64

RNG = np.random.default_rng(7)


def _qkv(b=2, h=4, t=128, d=64, seed=0):
    rng = jax.random.PRNGKey(seed)
    return tuple(jax.random.normal(r, (b, h, t, d)) for r in jax.random.split(rng, 3))


class TestFlashKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla_single_block(self, causal):
        q, k, v = _qkv()
        np.testing.assert_allclose(
            np.asarray(flash_mha(q, k, v, causal)),
            np.asarray(mha(q, k, v, causal=causal)), rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_xla_multi_block(self, causal):
        q, k, v = _qkv(b=1, h=2, t=256, d=32, seed=1)
        np.testing.assert_allclose(
            np.asarray(flash_mha(q, k, v, causal)),
            np.asarray(mha(q, k, v, causal=causal)), rtol=2e-5, atol=2e-5)

    def test_cross_attention_lengths(self):
        q, k, v = _qkv(t=128, seed=2)
        k2, v2 = k[:, :, :64], v[:, :, :64]
        np.testing.assert_allclose(
            np.asarray(flash_mha(q, k2, v2)),
            np.asarray(mha(q, k2, v2)), rtol=2e-5, atol=2e-5)

    def test_odd_length_falls_back(self):
        q, k, v = _qkv(t=100, seed=3)  # 100 has no pow2 block divisor ≥ 8
        np.testing.assert_allclose(
            np.asarray(flash_mha(q, k, v)),
            np.asarray(mha(q, k, v)), rtol=2e-5, atol=2e-5)

    def test_gradients_match_xla(self):
        q, k, v = _qkv(b=1, h=2, t=64, d=16, seed=4)

        def loss(fn, causal):
            return lambda q, k, v: jnp.sum(fn(q, k, v, causal) ** 2)

        g_ref = jax.grad(lambda q, k, v: jnp.sum(mha(q, k, v, causal=True) ** 2),
                         argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss(flash_mha, True), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_padding_mask_matches_xla(self, causal):
        """Variable-length batches (the DL4J-parity case) stay on the
        kernel: key-padding mask in both forward and fused backward."""
        q, k, v = _qkv(b=2, h=2, t=64, d=16, seed=5)
        mask = np.ones((2, 64), np.float32)
        mask[0, 41:] = 0.0
        mask[1, 13:] = 0.0
        mj = jnp.asarray(mask)
        ref = mha(q, k, v, causal=causal, mask=mj[:, None, None, :])
        out = flash_mha(q, k, v, causal, kmask=mj)
        w = mask[:, None, :, None]
        np.testing.assert_allclose(np.asarray(out) * w, np.asarray(ref) * w,
                                   rtol=2e-5, atol=2e-5)

        def loss_fl(q, k, v):
            o = flash_mha(q, k, v, causal, kmask=mj)
            return jnp.sum((o * mj[:, None, :, None]) ** 2)

        def loss_ref(q, k, v):
            o = mha(q, k, v, causal=causal, mask=mj[:, None, None, :])
            return jnp.sum((o * mj[:, None, :, None]) ** 2)

        g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_fully_masked_rows_finite_and_output_masked_equal(self):
        """All-keys-masked rows produce garbage-by-convention in BOTH paths
        (flash: the additive −LARGE bias is a constant row shift, softmax
        cancels it; mha: uniform over where()-replaced scores) — the DL4J
        contract is that such rows are zeroed DOWNSTREAM by the output
        mask, which is exactly what the attention layer does.  What must
        hold: finiteness, and output-masked loss gradients equal."""
        q, k, v = _qkv(b=2, h=2, t=32, d=16, seed=6)
        mask = np.ones((2, 32), np.float32)
        mask[0, :] = 0.0   # row 0: ALL keys masked
        mask[1, 20:] = 0.0
        mj = jnp.asarray(mask)
        w = mj[:, None, :, None]

        def loss_fl(q, k, v):
            return jnp.sum((flash_mha(q, k, v, False, kmask=mj) * w) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum((mha(q, k, v, mask=mj[:, None, None, :]) * w) ** 2)

        g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_fl, g_ref):
            assert np.all(np.isfinite(np.asarray(a)))
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def _seq_data(n=4, t=8, f=6, c=3):
    x = RNG.normal(size=(n, t, f))
    y = np.eye(c)[RNG.integers(0, c, (n, t))]
    return DataSet(x, y)


def _net(layers, input_type):
    b = NeuralNetConfiguration.builder().seed(0).updater(NoOp()).dtype("float64", "float64")
    for l in layers:
        b.layer(l)
    b.set_input_type(input_type)
    net = MultiLayerNetwork(b.build())
    with enable_x64(True):
        net.init()
    return net


class TestSelfAttentionLayer:
    def test_gradient_check(self):
        net = _net([SelfAttention(n_out=8, n_heads=2, kernel="xla"),
                    RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 8))
        with enable_x64(True):
            assert check_gradients(net, _seq_data(), epsilon=1e-6,
                                   max_rel_error=1e-4, verbose=True)

    def test_gradient_check_causal(self):
        net = _net([SelfAttention(n_out=8, n_heads=2, causal=True, kernel="xla"),
                    RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 8))
        with enable_x64(True):
            assert check_gradients(net, _seq_data(), epsilon=1e-6,
                                   max_rel_error=1e-4, verbose=True)

    def test_trains(self):
        # learnable pattern: class = argmax over time-mean of features
        n, t, f = 64, 16, 3
        x = RNG.normal(size=(n, t, f)).astype(np.float32)
        y_cls = np.argmax(x.mean(axis=1), axis=-1)
        y = np.eye(f, dtype=np.float32)[y_cls][:, None, :].repeat(t, axis=1)
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(lr=5e-3))
                .layer(SelfAttention(n_out=16, n_heads=4))
                .layer(RnnOutputLayer(n_out=f, activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(f, t)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        losses = [net.fit_batch(DataSet(x, y)) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_causal_is_causal(self):
        # causal attention: output at t must not depend on inputs after t
        net = _net([SelfAttention(n_out=8, n_heads=2, causal=True, kernel="xla"),
                    RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 8))
        x = RNG.normal(size=(1, 8, 6))
        with enable_x64(True):
            out1 = np.asarray(net.output(x))
            x2 = x.copy()
            x2[:, 5:] = 99.0  # corrupt the future
            out2 = np.asarray(net.output(x2))
        np.testing.assert_allclose(out1[:, :5], out2[:, :5], rtol=1e-6)

    def test_mask_blocks_padded_steps(self):
        net = _net([SelfAttention(n_out=8, n_heads=2, kernel="xla"),
                    RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 8))
        x = RNG.normal(size=(2, 8, 6))
        mask = np.ones((2, 8), np.float32)
        mask[:, 6:] = 0.0
        with enable_x64(True):
            out1 = np.asarray(net.output(x, mask=mask))
            x2 = x.copy()
            x2[:, 6:] = 123.0  # corrupt masked-out steps
            out2 = np.asarray(net.output(x2, mask=mask))
        np.testing.assert_allclose(out1[:, :6], out2[:, :6], rtol=1e-6)

    def test_serde_roundtrip(self):
        from deeplearning4j_tpu.nn.layers.base import layer_from_dict, layer_to_dict
        layer = SelfAttention(n_in=6, n_out=8, n_heads=2, causal=True)
        back = layer_from_dict(layer_to_dict(layer))
        assert back == layer


class TestLearnedSelfAttention:
    def test_fixed_length_summary(self):
        net = _net([LearnedSelfAttention(n_out=8, n_heads=2, n_queries=3, kernel="xla"),
                    RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 10))
        x = RNG.normal(size=(4, 10, 6))
        with enable_x64(True):
            out = np.asarray(net.output(x))
        assert out.shape == (4, 3, 2)

    def test_gradient_check(self):
        net = _net([LearnedSelfAttention(n_out=8, n_heads=2, n_queries=2, kernel="xla"),
                    RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent")],
                   InputType.recurrent(6, 8))
        x = RNG.normal(size=(4, 8, 6))
        y = np.eye(3)[RNG.integers(0, 3, (4, 2))]
        with enable_x64(True):
            assert check_gradients(net, DataSet(x, y), epsilon=1e-6,
                                   max_rel_error=1e-4, verbose=True)
