"""Data-layer breadth: EMNIST/SVHN/TinyImageNet/UCI fetchers parsing REAL
binary fixtures written to a temp cache dir, the RecordReader bridge, and
the new zoo models (forward pass + pretrained mechanism)."""

import gzip
import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader,
    ImageRecordReaderDataSetIterator, RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


def _write_idx(tmp, img_name, lbl_name, images, labels):
    with gzip.open(os.path.join(tmp, img_name), "wb") as f:
        n, r, c = images.shape
        f.write(struct.pack(">IIII", 2051, n, r, c))
        f.write(images.astype(np.uint8).tobytes())
    with gzip.open(os.path.join(tmp, lbl_name), "wb") as f:
        f.write(struct.pack(">II", 2049, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


class TestFetchersRealFormats:
    def test_emnist_parses_real_idx_with_transpose(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 255, (20, 28, 28)).astype(np.uint8)
        labels = (rng.integers(1, 27, 20)).astype(np.uint8)  # letters: 1-based
        _write_idx(tmp_path, "emnist-letters-train-images-idx3-ubyte.gz",
                   "emnist-letters-train-labels-idx1-ubyte.gz", imgs, labels)
        from deeplearning4j_tpu.datasets.fetchers import load_emnist
        xs, ys = load_emnist("letters", train=True, allow_synthetic=False)
        assert xs.shape == (20, 28, 28, 1)
        # EMNIST images are stored transposed; loader un-transposes
        np.testing.assert_allclose(xs[0, :, :, 0], imgs[0].T / 255.0, atol=1e-6)
        assert ys.min() >= 0 and ys.max() <= 25  # 1-based → 0-based

    def test_svhn_parses_real_mat(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        import scipy.io
        rng = np.random.default_rng(1)
        X = rng.integers(0, 255, (32, 32, 3, 12)).astype(np.uint8)
        y = np.asarray([10, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 1], np.uint8)[:, None]
        scipy.io.savemat(os.path.join(tmp_path, "train_32x32.mat"), {"X": X, "y": y})
        from deeplearning4j_tpu.datasets.fetchers import load_svhn
        xs, ys = load_svhn(train=True, allow_synthetic=False)
        assert xs.shape == (12, 32, 32, 3)
        assert ys[0] == 0 and ys[10] == 0  # label '10' means digit 0
        np.testing.assert_allclose(xs[3], X[:, :, :, 3] / 255.0, atol=1e-6)

    def test_uci_parses_real_text(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        rng = np.random.default_rng(2)
        data = rng.normal(30, 5, (600, 60))
        np.savetxt(os.path.join(tmp_path, "synthetic_control.data"), data)
        from deeplearning4j_tpu.datasets.fetchers import load_uci_synthetic_control
        xtr, ytr = load_uci_synthetic_control(train=True, allow_synthetic=False)
        xte, yte = load_uci_synthetic_control(train=False, allow_synthetic=False)
        assert xtr.shape == (450, 60, 1) and xte.shape == (150, 60, 1)
        assert (np.bincount(ytr) == 75).all() and (np.bincount(yte) == 25).all()
        np.testing.assert_allclose(xtr[0, :, 0], data[0], rtol=1e-5)

    def test_tiny_imagenet_parses_directory(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        from PIL import Image
        rng = np.random.default_rng(3)
        for wnid in ("n001", "n002"):
            d = tmp_path / "tiny-imagenet-200" / "train" / wnid / "images"
            d.mkdir(parents=True)
            for i in range(3):
                arr = rng.integers(0, 255, (64, 64, 3)).astype(np.uint8)
                Image.fromarray(arr).save(str(d / f"{wnid}_{i}.JPEG"))
        from deeplearning4j_tpu.datasets.fetchers import load_tiny_imagenet
        xs, ys = load_tiny_imagenet(train=True, allow_synthetic=False)
        assert xs.shape == (6, 64, 64, 3)
        assert set(ys.tolist()) == {0, 1}

    def test_missing_files_raise_when_synthetic_disallowed(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
        from deeplearning4j_tpu.datasets import fetchers
        for fn in (lambda: fetchers.load_emnist("digits", allow_synthetic=False),
                   lambda: fetchers.load_svhn(allow_synthetic=False),
                   lambda: fetchers.load_tiny_imagenet(allow_synthetic=False),
                   lambda: fetchers.load_uci_synthetic_control(allow_synthetic=False)):
            with pytest.raises(FileNotFoundError):
                fn()


class TestRecordReaders:
    def test_csv_classification(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("# header\n1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n7.0,8.0,0\n")
        reader = CSVRecordReader(skip_lines=1).initialize(str(p))
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         num_classes=3)
        batches = list(it)
        assert len(batches) == 2
        np.testing.assert_allclose(batches[0].features, [[1, 2], [3, 4]])
        np.testing.assert_allclose(batches[0].labels, [[1, 0, 0], [0, 1, 0]])

    def test_csv_regression_label_range(self, tmp_path):
        p = tmp_path / "reg.csv"
        p.write_text("1,2,10,20\n3,4,30,40\n")
        reader = CSVRecordReader().initialize(str(p))
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         label_index_to=3, regression=True)
        b = list(it)[0]
        np.testing.assert_allclose(b.features, [[1, 2], [3, 4]])
        np.testing.assert_allclose(b.labels, [[10, 20], [30, 40]])

    def test_sequence_reader_pads_and_masks(self, tmp_path):
        f1 = tmp_path / "f1.csv"; f1.write_text("1,1\n2,2\n3,3\n")
        f2 = tmp_path / "f2.csv"; f2.write_text("5,5\n")
        l1 = tmp_path / "l1.csv"; l1.write_text("0\n1\n0\n")
        l2 = tmp_path / "l2.csv"; l2.write_text("1\n")
        fr = CSVSequenceRecordReader().initialize([str(f1), str(f2)])
        lr = CSVSequenceRecordReader().initialize([str(l1), str(l2)])
        it = SequenceRecordReaderDataSetIterator(fr, lr, batch_size=2, num_classes=2)
        b = list(it)[0]
        assert b.features.shape == (2, 3, 2)
        np.testing.assert_allclose(b.features_mask, [[1, 1, 1], [1, 0, 0]])
        np.testing.assert_allclose(b.labels[0, 1], [0, 1])
        np.testing.assert_allclose(b.labels_mask, [[1, 1, 1], [1, 0, 0]])

    def test_image_reader_labels_from_dirs(self, tmp_path):
        from PIL import Image
        rng = np.random.default_rng(0)
        for cls in ("cats", "dogs"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                arr = rng.integers(0, 255, (10, 12, 3)).astype(np.uint8)
                Image.fromarray(arr).save(str(d / f"{i}.png"))
        reader = ImageRecordReader(height=8, width=8).initialize(str(tmp_path))
        assert reader.labels == ["cats", "dogs"]
        it = ImageRecordReaderDataSetIterator(reader, batch_size=4)
        b = list(it)[0]
        assert b.features.shape == (4, 8, 8, 3)
        np.testing.assert_allclose(b.labels.sum(axis=0), [2, 2])


class TestNewZooModels:
    @pytest.mark.parametrize("which", ["googlenet", "inceptionresnetv1",
                                       "facenetnn4small2"])
    def test_forward_pass(self, which):
        from deeplearning4j_tpu.models import ZOO
        kw = {"num_classes": 7}
        if which == "inceptionresnetv1":
            kw.update(a_blocks=1, b_blocks=1, c_blocks=1, height=96, width=96)
        if which == "facenetnn4small2":
            kw.update(height=64, width=64)
        if which == "googlenet":
            kw.update(height=96, width=96)
        net = ZOO[which](**kw)
        net.init()
        h = {"googlenet": 96, "inceptionresnetv1": 96, "facenetnn4small2": 64}[which]
        x = np.random.default_rng(0).normal(size=(2, h, h, 3)).astype(np.float32)
        out = net.output(x)[0]
        assert out.shape == (2, 7)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)  # softmax head

    def test_facenet_embeddings_are_l2_normalized(self):
        from deeplearning4j_tpu.models import FaceNetNN4Small2
        net = FaceNetNN4Small2(height=64, width=64, num_classes=5)
        net.init()
        x = np.random.default_rng(1).normal(size=(3, 64, 64, 3)).astype(np.float32)
        # run the DAG up to the embeddings vertex via the public output of a
        # clone whose outputs point at "embeddings"
        import jax
        acts, _, _, _ = net._apply(net.params, net.state,
                                   {"in": jax.numpy.asarray(x)},
                                   train=False, rng=None)
        emb = np.asarray(acts["embeddings"])
        np.testing.assert_allclose(np.linalg.norm(emb, axis=1), 1.0, rtol=1e-4)

    def test_pretrained_roundtrip_with_checksum(self, tmp_path):
        from deeplearning4j_tpu.models import (
            LeNet, checksum, init_pretrained, install_weights,
        )
        net = LeNet(num_classes=4, height=28, width=28, channels=1)
        net.init()
        src = str(tmp_path / "lenet.zip")
        net.save(src)
        install_weights("lenet", src, cache_dir=str(tmp_path / "cache"))
        ck = checksum(src)
        loaded = init_pretrained("lenet", expected_checksum=ck,
                                 cache_dir=str(tmp_path / "cache"))
        x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)).astype(np.float32)
        np.testing.assert_allclose(loaded.output(x), net.output(x), rtol=1e-5)

    def test_pretrained_checksum_mismatch_evicts(self, tmp_path):
        from deeplearning4j_tpu.models import LeNet, init_pretrained, install_weights, cached_path
        net = LeNet(num_classes=2, height=28, width=28, channels=1)
        net.init()
        src = str(tmp_path / "m.zip")
        net.save(src)
        cache = str(tmp_path / "cache")
        install_weights("lenet", src, cache_dir=cache)
        with pytest.raises(IOError, match="checksum"):
            init_pretrained("lenet", expected_checksum=123, cache_dir=cache)
        assert not os.path.exists(cached_path("lenet", cache_dir=cache))

    def test_pretrained_missing_raises_clearly(self, tmp_path):
        from deeplearning4j_tpu.models import init_pretrained
        with pytest.raises(FileNotFoundError, match="zero-egress"):
            init_pretrained("vgg16", cache_dir=str(tmp_path))
