"""16/32/64-virtual-device 4-axis parallelism evidence (round-4, extended
round-5).

The conftest pins this process to 8 virtual CPU devices, so the ≥16-device
meshes run in a subprocess with its own XLA_FLAGS — the same mechanism the
driver's dryrun uses.  Covers what no 8-device mesh can: DP composed with
TP, SP and PP simultaneously (every axis ≥ 2, up to a 4-stage pipeline at
64 devices), plus elastic resize in BOTH directions (16→8 shrink, 8→16
grow) with params AND optimizer state migrated across meshes
(round-3 verdict Weak #5 / Next #6).
"""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from deeplearning4j_tpu.parallel import ShardedTransformerLM, build_mesh

devs = jax.devices()
assert len(devs) >= {total}, len(devs)
mesh = build_mesh({axes!r}, devices=devs[:{total}])
lm = ShardedTransformerLM(vocab_size=64, n_layers=4, d_model=32, n_heads=4,
                          mesh=mesh, max_len=16, seed=0)
rng = np.random.default_rng(0)
toks = rng.integers(0, 64, (2 * {axes!r}["data"], 16))
tgts = np.roll(toks, -1, axis=1)
l0 = float(lm.fit_batch(toks, tgts))
l1 = float(lm.fit_batch(toks, tgts))
assert np.isfinite(l0) and np.isfinite(l1), (l0, l1)
assert l1 < l0, (l0, l1)  # two steps on one batch must reduce the loss
print("OK", l0, l1)
"""

_RESIZE = """
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from deeplearning4j_tpu.parallel import ShardedTransformerLM, build_mesh

# elastic resize {src_n}->{dst_n} devices: train, checkpoint params AND
# optimizer state to host, rebuild on the new mesh, restore both, keep
# training downhill — the slice-reconfiguration story in both directions
devs = jax.devices()

def make(axes, n):
    mesh = build_mesh(axes, devices=devs[:n])
    return ShardedTransformerLM(vocab_size=64, n_layers=4, d_model=32,
                                n_heads=4, mesh=mesh, max_len=16, seed=0)

src = make({src_axes!r}, {src_n})
rng = np.random.default_rng(0)
toks = rng.integers(0, 64, (8, 16))
tgts = np.roll(toks, -1, axis=1)
losses = [float(src.fit_batch(toks, tgts)) for _ in range(3)]
host_params = jax.tree_util.tree_map(np.asarray, src.params)
host_opt = jax.tree_util.tree_map(np.asarray, src.opt_state)
dst = make({dst_axes!r}, {dst_n})
dst.params = jax.device_put(
    host_params, jax.tree_util.tree_map(lambda s: s.sharding, dst.params))
dst.opt_state = jax.device_put(
    host_opt, jax.tree_util.tree_map(lambda s: s.sharding, dst.opt_state))
dst.iteration = src.iteration
after = [float(dst.fit_batch(toks, tgts)) for _ in range(2)]
assert all(np.isfinite(v) for v in losses + after)
assert after[-1] < losses[0], (losses, after)  # training CONTINUED downhill
# restored Adam moments are live, not zeros
m0 = np.abs(np.asarray(
    jax.tree_util.tree_leaves(host_opt)[0], dtype=np.float32)).max()
assert m0 > 0, "source optimizer state was all zeros?"
print("OK", losses, after)
"""


def _run(code, n_devices, timeout=900):
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env.pop("JAX_PLATFORMS", None)
    p = subprocess.run([sys.executable, "-c", code], env=env, cwd=_REPO,
                       capture_output=True, text=True, timeout=timeout)
    assert p.returncode == 0, f"stdout:\n{p.stdout}\nstderr:\n{p.stderr[-3000:]}"
    assert "OK" in p.stdout


@pytest.mark.parametrize("total,axes", [
    (16, {"data": 2, "model": 2, "seq": 2, "pipe": 2}),
    # the 32/64-device configs are tier-2 (slow): each spawns a fresh
    # XLA backend + 4D compile in a subprocess, and the SAME meshes run
    # headlessly every round in the driver's dryrun (MULTICHIP_r*.json)
    pytest.param(32, {"data": 4, "model": 2, "seq": 2, "pipe": 2},
                 marks=pytest.mark.slow),
    pytest.param(64, {"data": 4, "model": 2, "seq": 2, "pipe": 4},
                 marks=pytest.mark.slow),
])
def test_transformer_lm_all_axes_geq_2(total, axes):
    _run(_SCRIPT.format(repo=_REPO, total=total, axes=axes), total)


_AXES_8 = {"data": 2, "model": 2, "seq": 2, "pipe": 1}
_AXES_16 = {"data": 2, "model": 2, "seq": 2, "pipe": 2}


@pytest.mark.slow  # the shrink direction re-runs headlessly every round
# in the driver's dryrun (_run_elastic_shrink → MULTICHIP_r*.json); grow
# is only covered here, so it stays tier-1
def test_elastic_shrink_16_to_8_continues_training():
    _run(_RESIZE.format(repo=_REPO, src_axes=_AXES_16, src_n=16,
                        dst_axes=_AXES_8, dst_n=8), 16)


def test_elastic_grow_8_to_16_continues_training():
    _run(_RESIZE.format(repo=_REPO, src_axes=_AXES_8, src_n=8,
                        dst_axes=_AXES_16, dst_n=16), 16)
