"""Traffic-driven model placement (serving/placement.py) + the
multi-model engine contracts it actuates and the registry inventory
views it consumes.

Fast CPU tests with duck-typed constant models (the response value IS
the model identity — version/tenant mixing is directly observable) and
injected clocks (GC201): the controller's widen/narrow/idle-evict/
demand-reload decisions are all driven deterministically here; the
end-to-end chaos proof lives in scripts/multitenant_soak.py.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.serving import (
    Engine, FleetRouter, ModelNotLoadedError, ModelRegistry,
    PlacementController,
)


class _Conf:
    input_type = InputType.feed_forward(3)


class _ConstModel:
    """Output value identifies the model — mixing is visible; the conf
    gives Engine.add_model its inferable per-example shape."""

    conf = _Conf()

    def __init__(self, val):
        self.val = float(val)

    def output(self, x):
        return np.full((x.shape[0], 1), self.val, np.float32)


def _registry():
    reg = ModelRegistry()
    for name, val in (("m1", 1.0), ("m2", 2.0), ("m3", 3.0)):
        v = reg.register(name, _ConstModel(val))
        reg.set_alias(name, "prod", v)
    return reg


def _engine(reg, default="m1", **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("slo_ms", 10_000)
    kw.setdefault("replicas", 1)
    eng = Engine.from_registry(reg, default, **kw)
    eng.load(input_shape=(3,))
    return eng


X = np.zeros((1, 3), np.float32)


class TestRegistryInventory:
    def test_list_aliases_is_the_deployable_view(self):
        reg = _registry()
        aliases = reg.list_aliases()
        assert set(aliases) == {"m1", "m2", "m3"}
        assert aliases["m1"] == {"prod": 1}
        reg.register("m4", _ConstModel(4.0))    # no alias -> omitted
        assert "m4" not in reg.list_aliases()

    def test_models_snapshot_inventory(self):
        reg = _registry()
        reg.register("m1", _ConstModel(1.5))    # v2; prod stays at v1
        snap = reg.models_snapshot()
        assert set(snap) == {"m1", "m2", "m3"}
        assert snap["m1"]["versions"] == [1, 2]
        assert snap["m1"]["pinned"] == 1
        assert snap["m1"]["aliases"] == {"prod": 1}
        assert snap["m2"]["last_access"] is None   # never resolved
        reg.resolve("m2", "prod")
        assert reg.models_snapshot()["m2"]["last_access"] is not None


class TestMultiModelEngine:
    def test_add_model_places_and_routes(self):
        reg = _registry()
        eng = _engine(reg)
        eng.add_model_from_registry(reg, "m2", input_shape=(3,))
        assert eng.has_model("m2") and eng.has_model("m1")
        assert set(eng.placed_models()) == {"m1", "m2"}
        assert eng.placed_models()["m2"] == "m2:v1"
        out1 = eng.output_async(X).result(timeout=10)
        out2 = eng.output_async(X, model="m2").result(timeout=10)
        assert float(out1[0, 0]) == 1.0 and float(out2[0, 0]) == 2.0
        assert eng.model_last_used("m2") is not None
        eng.shutdown()

    def test_add_model_rejects_duplicates_and_default(self):
        reg = _registry()
        eng = _engine(reg)
        eng.add_model("m2", _ConstModel(2.0), input_shape=(3,))
        with pytest.raises(ValueError, match="already placed"):
            eng.add_model("m2", _ConstModel(9.0), input_shape=(3,))
        with pytest.raises(ValueError, match="already placed"):
            eng.add_model("m1", _ConstModel(9.0), input_shape=(3,))
        eng.shutdown()

    def test_remove_model_evicts_but_never_the_default(self):
        reg = _registry()
        eng = _engine(reg)
        eng.add_model("m2", _ConstModel(2.0), input_shape=(3,))
        assert eng.remove_model("m2") is True
        assert not eng.has_model("m2")
        assert eng.remove_model("m2") is False      # already gone
        with pytest.raises(ModelNotLoadedError):
            eng.output_async(X, model="m2").result(timeout=10)
        with pytest.raises(ValueError, match="default model"):
            eng.remove_model("m1")
        eng.shutdown()


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestPlacementController:
    def _fleet(self, reg, n=2):
        router = FleetRouter(max_retries=2)
        engines = []
        for i in range(n):
            eng = _engine(reg)
            engines.append(eng)
            router.add_host(f"h{i}", engine=eng)
        return router, engines

    def test_widen_on_demand_then_idle_evict(self):
        reg = _registry()
        router, engines = self._fleet(reg)
        engines[0].add_model_from_registry(reg, "m2", input_shape=(3,))
        clk = _Clock()
        ctl = PlacementController(
            router, reg, models=["m2"], up_load=4.0, up_ticks=1,
            down_ticks=1000, cooldown_s=0.0, evict_idle_s=5.0,
            ewma_alpha=1.0, clock=clk)
        # hot: demand 20/tick over 1 holder >> up_load -> widen to h1
        for _ in range(20):
            router.output_async(X, model="m2").result(timeout=10)
        moves = ctl.tick()
        assert {"op": "add", "model": "m2", "host": "h1",
                "reason": "hot"} in moves
        assert sorted(ctl.placement()["m2"]) == ["h0", "h1"]
        assert engines[1].output_async(
            X, model="m2").result(timeout=10)[0, 0] == 2.0
        # idle: no traffic, last_used ages past evict_idle_s -> evicted
        # from EVERY holder (idle eviction bypasses min_hosts).  The
        # engines stamp last_used on THEIR clock (real monotonic), so
        # idle-age the controller clock past that.
        clk.t = time.monotonic() + 1000.0
        moves = ctl.tick()
        assert sorted(m["host"] for m in moves
                      if m["op"] == "evict") == ["h0", "h1"]
        assert ctl.placement()["m2"] == []
        router.shutdown(shutdown_hosts=True)

    def test_demand_reload_on_model_miss(self):
        """An evicted model's next request demand-reloads it through the
        router's miss hook — one latency bump, not an error."""
        reg = _registry()
        router, engines = self._fleet(reg)
        ctl = PlacementController(router, reg, models=["m3"],
                                  clock=_Clock())
        assert ctl.placement()["m3"] == []
        out = router.output_async(X, model="m3").result(timeout=10)
        assert float(out[0, 0]) == 3.0
        assert len(ctl.placement()["m3"]) == 1
        c = router.metrics_snapshot()["counters"]
        assert c.get("model_misses", 0) >= 1
        assert c.get("demand_loads", 0) == 1
        router.shutdown(shutdown_hosts=True)

    def test_unmanaged_model_miss_fails_typed(self):
        reg = _registry()
        router, _ = self._fleet(reg)
        PlacementController(router, reg, models=["m2"], clock=_Clock())
        with pytest.raises(ModelNotLoadedError):
            router.output_async(X, model="m3").result(timeout=10)
        router.shutdown(shutdown_hosts=True)

    def test_no_mixing_across_models_under_load(self):
        reg = _registry()
        router, engines = self._fleet(reg)
        engines[0].add_model_from_registry(reg, "m2", input_shape=(3,))
        engines[1].add_model_from_registry(reg, "m2", input_shape=(3,))
        futs = [(m, router.output_async(X, model=m if m != "m1" else None))
                for _ in range(50) for m in ("m1", "m2")]
        want = {"m1": 1.0, "m2": 2.0}
        for m, f in futs:
            assert float(f.result(timeout=30)[0, 0]) == want[m]
        router.shutdown(shutdown_hosts=True)

    def test_manage_and_snapshot(self):
        reg = _registry()
        router, _ = self._fleet(reg, n=1)
        ctl = PlacementController(router, reg, models=["m2"],
                                  clock=_Clock())
        assert ctl.managed_models() == ["m2"]
        ctl.manage("m3")
        assert "m3" in ctl.managed_models()
        snap = ctl.snapshot()
        assert set(snap) == {"placement", "demand_ewma", "recent_moves"}
        assert set(snap["placement"]) == {"m2", "m3"}
        router.shutdown(shutdown_hosts=True)
