"""SequenceVectors / ParagraphVectors / GloVe learning tests.

Same two-topic synthetic corpus strategy as test_nlp.py: semantic checks
(within-topic similarity beats across-topic; doc inference lands near the
right topic's documents), not just smoke tests.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    Glove, ParagraphVectors, SequenceVectors,
)

ANIMALS = ["cat", "dog", "pet", "fur", "paw", "tail", "meow", "bark"]
TECH = ["cpu", "ram", "disk", "code", "byte", "chip", "core", "cache"]


def topic_docs(n_docs=120, words_per_doc=20, seed=0):
    rng = np.random.default_rng(seed)
    docs, labels, topics = [], [], []
    for i in range(n_docs):
        t = int(rng.integers(0, 2))
        vocab = ANIMALS if t == 0 else TECH
        docs.append(" ".join(rng.choice(vocab, size=words_per_doc)))
        labels.append(f"DOC_{i}")
        topics.append(t)
    return docs, labels, topics


class TestSequenceVectors:
    def test_generic_elements(self):
        """SequenceVectors learns embeddings for arbitrary hashable
        elements — here integer ids, the DeepWalk use case."""
        rng = np.random.default_rng(3)
        # elements 0-7 and 10-17 co-occur within their own group only
        seqs = []
        for _ in range(400):
            base = 0 if rng.integers(0, 2) == 0 else 10
            seqs.append([int(base + x) for x in rng.integers(0, 8, size=8)])
        sv = SequenceVectors(layer_size=32, window=3, min_word_frequency=2,
                             epochs=12, batch_size=128, seed=1,
                             learning_rate=0.05)
        sv.fit_sequences(seqs)
        within = sv.similarity(0, 1)
        across = sv.similarity(0, 10)
        assert within > across + 0.2, f"within={within:.3f} across={across:.3f}"


class TestParagraphVectors:
    @pytest.mark.parametrize("dm", [True, False], ids=["dm", "dbow"])
    def test_doc_vectors_cluster_by_topic(self, dm):
        docs, labels, topics = topic_docs()
        pv = ParagraphVectors(dm=dm, layer_size=24, window=3, epochs=20,
                              batch_size=128, seed=1, learning_rate=0.05)
        pv.fit(docs, labels)
        vecs = np.stack([pv.doc_vector(lb) for lb in labels])
        vecs = vecs / np.maximum(np.linalg.norm(vecs, axis=1, keepdims=True), 1e-9)
        t = np.asarray(topics)
        same = (t[:, None] == t[None, :])
        sims = vecs @ vecs.T
        off = ~np.eye(len(t), dtype=bool)
        within = sims[same & off].mean()
        across = sims[~same].mean()
        assert within > across + 0.15, \
            f"dm={dm}: within={within:.3f} across={across:.3f}"

    @pytest.mark.parametrize("dm", [True, False], ids=["dm", "dbow"])
    def test_infer_unseen_doc(self, dm):
        docs, labels, topics = topic_docs()
        pv = ParagraphVectors(dm=dm, layer_size=24, window=3, epochs=20,
                              batch_size=128, seed=1, learning_rate=0.05)
        pv.fit(docs, labels)
        inferred = pv.infer("cat dog pet fur meow bark tail paw cat dog")
        assert inferred.shape == (24,)
        # nearest trained docs must be overwhelmingly animal-topic
        near = pv.nearest_labels(inferred, top_n=10)
        t_by_label = dict(zip(labels, topics))
        animal_hits = sum(1 for lb in near if t_by_label[lb] == 0)
        assert animal_hits >= 8, f"only {animal_hits}/10 animal docs: {near}"

    def test_infer_is_deterministic_given_seed(self):
        docs, labels, _ = topic_docs(40)
        pv = ParagraphVectors(dm=False, layer_size=16, epochs=3,
                              batch_size=128, seed=1)
        pv.fit(docs, labels)
        a = pv.infer_vector(["cat", "dog", "pet"], seed=5)
        b = pv.infer_vector(["cat", "dog", "pet"], seed=5)
        np.testing.assert_allclose(a, b)

    def test_requires_labels_match(self):
        pv = ParagraphVectors(layer_size=8)
        with pytest.raises(ValueError, match="labels"):
            pv.fit_sequences([["a", "b"]], labels=["x", "y"])

    def test_unsupported_combos_rejected(self):
        with pytest.raises(NotImplementedError, match="DM"):
            ParagraphVectors(dm=True, hierarchic_softmax=True)
        with pytest.raises(NotImplementedError, match="CBOW"):
            SequenceVectors(cbow=True, hierarchic_softmax=True)

    def test_dbow_with_hierarchical_softmax(self):
        docs, labels, topics = topic_docs()
        pv = ParagraphVectors(dm=False, hierarchic_softmax=True, layer_size=24,
                              window=3, epochs=20, batch_size=128, seed=1,
                              learning_rate=0.05)
        pv.fit(docs, labels)
        vecs = np.stack([pv.doc_vector(lb) for lb in labels])
        vecs = vecs / np.maximum(np.linalg.norm(vecs, axis=1, keepdims=True), 1e-9)
        t = np.asarray(topics)
        sims = vecs @ vecs.T
        off = ~np.eye(len(t), dtype=bool)
        within = sims[(t[:, None] == t[None, :]) & off].mean()
        across = sims[t[:, None] != t[None, :]].mean()
        assert within > across + 0.15, f"within={within:.3f} across={across:.3f}"

    def test_words_nearest_excludes_label_rows(self):
        docs, labels, _ = topic_docs(40)
        pv = ParagraphVectors(dm=False, layer_size=16, epochs=3,
                              batch_size=128, seed=1)
        pv.fit(docs, labels)
        near = pv.words_nearest("cat", top_n=10)  # must not crash on label rows
        assert all(isinstance(w, str) and not w.startswith("DOC_") for w in near)


class TestGlove:
    def test_cooccurrence_weighting(self):
        from deeplearning4j_tpu.nlp import CoOccurrences
        cooc = CoOccurrences(window=2, symmetric=True).count(
            [np.asarray([0, 1, 2], np.int32)])
        # adjacent pair weight 1.0, distance-2 pair weight 0.5, symmetric
        assert cooc[(0, 1)] == 1.0 and cooc[(1, 0)] == 1.0
        assert cooc[(0, 2)] == 0.5 and cooc[(2, 0)] == 0.5

    def test_topics_separate(self):
        rng = np.random.default_rng(0)
        sentences = []
        for _ in range(300):
            vocab = ANIMALS if rng.integers(0, 2) == 0 else TECH
            sentences.append(" ".join(rng.choice(vocab, size=10)))
        glove = Glove(layer_size=24, window=5, min_word_frequency=2,
                      epochs=30, learning_rate=0.05, seed=1)
        glove.fit(sentences)
        assert len(glove.vocab) == 16
        # training loss must drop
        assert glove.losses[-1] < glove.losses[0] * 0.5, glove.losses[::10]
        within = glove.similarity("cat", "dog")
        across = glove.similarity("cat", "cpu")
        assert within > across + 0.2, f"within={within:.3f} across={across:.3f}"
        nearest = glove.words_nearest("cat", top_n=7)
        assert len(set(nearest) & set(ANIMALS[1:])) >= 5, nearest

    def test_empty_corpus_raises(self):
        with pytest.raises(ValueError, match="vocabulary|co-occurrence"):
            Glove(min_word_frequency=100).fit(["one two three"])
