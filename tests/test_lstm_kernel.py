"""Fused LSTM cell kernel: pallas↔plain parity (forward + gradients) and
integration through the LSTM layer / gradient-check path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import lstm_kernel
from deeplearning4j_tpu.ops.lstm_kernel import _plain_cell, fused_lstm_cell


@pytest.fixture(autouse=True)
def enable_kernel(monkeypatch):
    """The kernel is opt-in (XLA epilogue fusion wins at common sizes);
    parity tests exercise the pallas path explicitly."""
    monkeypatch.setattr(lstm_kernel, "ENABLED", True)


def zc(mb=8, n=128, seed=0, dtype=jnp.float32):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    z = jax.random.normal(k1, (mb, 4 * n), dtype)
    c = jax.random.normal(k2, (mb, n), dtype)
    return z, c


class TestFusedCell:
    def test_forward_matches_plain(self):
        z, c = zc()
        h_f, c_f = fused_lstm_cell(z, c)
        h_p, c_p = _plain_cell(z, c)
        np.testing.assert_allclose(np.asarray(h_f), np.asarray(h_p),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(c_f), np.asarray(c_p),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_plain(self):
        z, c = zc(mb=4, n=128)

        def loss_fused(z_, c_):
            h, cn = fused_lstm_cell(z_, c_)
            return jnp.sum(h * h) + jnp.sum(jnp.tanh(cn))

        def loss_plain(z_, c_):
            h, cn = _plain_cell(z_, c_)
            return jnp.sum(h * h) + jnp.sum(jnp.tanh(cn))

        gf = jax.grad(loss_fused, argnums=(0, 1))(z, c)
        gp = jax.grad(loss_plain, argnums=(0, 1))(z, c)
        for a, b in zip(gf, gp):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_f64_falls_back_exactly(self):
        jax.config.update("jax_enable_x64", True)
        try:
            z, c = zc(n=32, dtype=jnp.float64)
            h, cn = fused_lstm_cell(z, c)
            hp, cp = _plain_cell(z, c)
            np.testing.assert_array_equal(np.asarray(h), np.asarray(hp))
            # f64 gradient vs central differences (the exactness the
            # gradient-check suite relies on)
            def loss(z_):
                hh, _ = fused_lstm_cell(z_, c)
                return jnp.sum(hh * hh)
            g = jax.grad(loss)(z)
            eps = 1e-6
            zp = z.at[0, 0].add(eps)
            zm = z.at[0, 0].add(-eps)
            num = (float(loss(zp)) - float(loss(zm))) / (2 * eps)
            np.testing.assert_allclose(float(g[0, 0]), num, rtol=1e-6)
        finally:
            jax.config.update("jax_enable_x64", False)

    def test_uneven_batch_tiles(self):
        z, c = zc(mb=7, n=128)  # 7 doesn't divide 256 → bm search kicks in
        h_f, c_f = fused_lstm_cell(z, c)
        h_p, c_p = _plain_cell(z, c)
        np.testing.assert_allclose(np.asarray(h_f), np.asarray(h_p),
                                   rtol=1e-5, atol=1e-6)

    def test_lstm_layer_uses_kernel_and_still_learns(self):
        """End-to-end: LSTM layer (sigmoid/tanh, no peephole) routes through
        the fused cell; a small next-step regression must still train."""
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.updaters import Adam

        rng = np.random.default_rng(0)
        phase = rng.uniform(0, 2 * np.pi, (32, 1))
        t = np.arange(13)[None, :]
        wave = np.sin(0.4 * t + phase)
        x = wave[:, :-1, None].astype(np.float32)
        y = wave[:, 1:, None].astype(np.float32)
        conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(lr=1e-2))
                .layer(LSTM(n_out=128))
                .layer(RnnOutputLayer(n_out=1, loss="mse", activation="identity"))
                .set_input_type(InputType.recurrent(1)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        losses = [net.fit_batch(DataSet(x, y)) for _ in range(25)]
        assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])
