"""Checkpoint-format regression goldens (round-4).

Parity target: the reference's regressiontest suite
(deeplearning4j-core/src/test/java/org/deeplearning4j/regressiontest/
RegressionTest080.java et al.) — fixed model files from an old version must
load forever.  The committed fixtures under tests/fixtures/ were written by
round-4 code (generate_goldens.py); these tests ONLY load them and check
pinned outputs.  If a format change breaks them, that is a compatibility
break with every existing user checkpoint: either restore compatibility or
regenerate the fixtures as a documented, deliberate format break.
"""

import os

import numpy as np

from deeplearning4j_tpu.datasets.normalizers import AbstractNormalizer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nlp.serializer import read_word_vectors

FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def _fixed_input(shape, seed):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestFormatGoldens:
    def test_mln_zip_loads_and_reproduces_output(self):
        net = MultiLayerNetwork.load(os.path.join(FIX, "mln_golden.zip"))
        got = net.output(_fixed_input((4, 8), 99))
        want = np.load(os.path.join(FIX, "mln_golden_output.npy"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)

    def test_mln_zip_carries_updater_state(self):
        net = MultiLayerNetwork.load(os.path.join(FIX, "mln_golden.zip"),
                                     load_updater=True)
        # Adam moments from the 5 generator steps must round-trip non-zero
        m = net.opt_state[0].get("m")
        assert m is not None
        assert float(np.abs(np.asarray(
            next(iter(m.values()) if isinstance(m, dict) else iter([m])))).max()) > 0

    def test_cg_zip_loads_and_reproduces_output(self):
        g = ComputationGraph.load(os.path.join(FIX, "cg_golden.zip"))
        got = g.output(_fixed_input((4, 5), 77), _fixed_input((4, 6), 78))[0]
        want = np.load(os.path.join(FIX, "cg_golden_output.npy"))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)

    def test_word2vec_c_text_format(self):
        vecs = read_word_vectors(os.path.join(FIX, "w2v_golden.txt"),
                                 binary=False)
        want = np.load(os.path.join(FIX, "w2v_golden_vectors.npy"))
        assert sorted(vecs) == [f"word{i}" for i in range(5)]
        got = np.stack([vecs[f"word{i}"] for i in range(5)])
        # text format rounds through decimal digits — not bit-exact
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_word2vec_c_binary_format(self):
        vecs = read_word_vectors(os.path.join(FIX, "w2v_golden.bin"),
                                 binary=True)
        want = np.load(os.path.join(FIX, "w2v_golden_vectors.npy"))
        got = np.stack([vecs[f"word{i}"] for i in range(5)])
        np.testing.assert_array_equal(got, want)  # binary IS bit-exact

    def test_normalizer_state(self):
        n = AbstractNormalizer.load(os.path.join(FIX, "normalizer_golden.npz"))
        got = n.transform(_fixed_input((4, 6), 12))
        want = np.load(os.path.join(FIX, "normalizer_golden_output.npy"))
        np.testing.assert_allclose(got, want, rtol=1e-6)
