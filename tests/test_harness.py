"""Training harness: listeners, early stopping, transfer learning."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    BestScoreEpochTerminationCondition, DataSetLossCalculator,
    EarlyStoppingConfiguration, EarlyStoppingTrainer, InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.transferlearning import TransferLearning, TransferLearningHelper
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.optimize import (
    CheckpointListener, CollectScoresIterationListener, PerformanceListener,
    ScoreIterationListener,
)


def blobs(n=256, f=10, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, f)) * 3
    ys = rng.integers(0, classes, size=n)
    xs = (centers[ys] + rng.normal(size=(n, f))).astype(np.float32)
    return xs, np.eye(classes, dtype=np.float32)[ys]


def mlp(f=10, classes=3, seed=1, lr=1e-2):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr=lr))
            .layer(Dense(n_out=32, activation="relu"))
            .layer(Dense(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(f)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestListeners:
    def test_score_and_collect(self):
        xs, ys = blobs(n=64)
        net = mlp()
        logged = []
        net.set_listeners(ScoreIterationListener(1, out=logged.append),
                          CollectScoresIterationListener())
        net.fit(ListDataSetIterator.from_arrays(xs, ys, 32), epochs=2)
        assert len(logged) == 4
        collect = net.listeners[1]
        assert [it for it, _ in collect.scores] == [1, 2, 3, 4]

    def test_performance_listener(self):
        xs, ys = blobs(n=128)
        net = mlp()
        perf = PerformanceListener(report_every=2, out=lambda s: None)
        perf.set_batch_size(32)
        net.set_listeners(perf)
        net.fit(ListDataSetIterator.from_arrays(xs, ys, 32), epochs=2)
        assert perf.history and perf.history[0][0] > 0

    def test_checkpoint_listener(self, tmp_path):
        xs, ys = blobs(n=64)
        net = mlp()
        ckpt = CheckpointListener(str(tmp_path), save_every_iterations=2, keep_last=2)
        net.set_listeners(ckpt)
        net.fit(ListDataSetIterator.from_arrays(xs, ys, 16), epochs=2)
        assert len(ckpt.saved) == 2  # rotation kept last 2
        assert all(os.path.exists(p) for p in ckpt.saved)
        restored = MultiLayerNetwork.load(ckpt.saved[-1])
        assert restored.num_params() == net.num_params()


class TestEarlyStopping:
    def test_max_epochs(self):
        xs, ys = blobs()
        net = mlp()
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[MaxEpochsTerminationCondition(3)])
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert result.total_epochs == 3
        assert result.termination_reason == "EpochTermination"
        assert len(result.score_vs_epoch) == 3
        # improving problem → best near the end
        assert result.best_model_epoch >= 2

    def test_score_improvement_patience(self):
        xs, ys = blobs(n=64)
        # tiny lr → no meaningful improvement → patience fires
        net = mlp(lr=1e-9)
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[
                MaxEpochsTerminationCondition(50),
                ScoreImprovementEpochTerminationCondition(2, min_improvement=1e-3)])
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert result.total_epochs < 50

    def test_max_score_abort(self):
        xs, ys = blobs(n=64)
        net = mlp(lr=1e3)  # absurd lr → exploding loss
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[MaxEpochsTerminationCondition(20)],
            iteration_terminations=[MaxScoreIterationTerminationCondition(50.0)])
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert result.termination_reason == "IterationTermination"

    def test_invalid_score_abort(self):
        # NaN guard (reference InvalidScoreIterationTerminationCondition):
        # a diverging run must stop at the first non-finite score, not
        # train to max epochs
        xs, ys = blobs(n=64)
        # identity+mse diverges to inf/NaN under an absurd lr (the stable
        # fused softmax-xent path saturates finite, so it can't NaN)
        conf_net = (NeuralNetConfiguration.builder().seed(1).updater(Adam(lr=1e9))
                    .layer(Dense(n_out=32, activation="relu"))
                    .layer(OutputLayer(n_out=3, activation="identity", loss="mse"))
                    .set_input_type(InputType.feed_forward(10)).build())
        net = MultiLayerNetwork(conf_net)
        net.init()
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[MaxEpochsTerminationCondition(20)],
            iteration_terminations=[InvalidScoreIterationTerminationCondition()])
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert result.termination_reason == "IterationTermination"
        assert result.total_epochs < 20

    def test_best_score_termination(self):
        xs, ys = blobs()
        net = mlp()
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[
                MaxEpochsTerminationCondition(100),
                BestScoreEpochTerminationCondition(0.9, minimize=True)])
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert result.termination_reason == "EpochTermination"
        assert result.total_epochs < 100
        assert result.score_vs_epoch[-1] <= 0.9

    def test_local_file_saver_restores_best(self, tmp_path):
        xs, ys = blobs()
        net = mlp()
        saver = LocalFileModelSaver(str(tmp_path))
        conf = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(DataSet(xs, ys)),
            epoch_terminations=[MaxEpochsTerminationCondition(2)],
            model_saver=saver)
        result = EarlyStoppingTrainer(conf, net, ListDataSetIterator.from_arrays(xs, ys, 64)).fit()
        assert os.path.exists(saver.best_path)
        best_score_again = DataSetLossCalculator(DataSet(xs, ys)).calculate_score(result.best_model)
        np.testing.assert_allclose(best_score_again, result.best_model_score, rtol=1e-4)


class TestTransferLearning:
    def test_freeze_and_replace_head(self):
        xs, ys = blobs(classes=3)
        src = mlp(classes=3)
        src.fit(ListDataSetIterator.from_arrays(xs, ys, 64), epochs=5)
        frozen_w = np.asarray(src.params[0]["W"])

        # new 4-class problem reusing the feature extractor
        xs2, ys2 = blobs(classes=4, seed=9)
        new_net = (TransferLearning(src)
                   .fine_tune_configuration(updater=Adam(lr=1e-2))
                   .set_feature_extractor(1)
                   .remove_output_layer()
                   .add_layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
                   .build())
        assert isinstance(new_net.conf.layers[0], FrozenLayer)
        new_net.fit(ListDataSetIterator.from_arrays(xs2, ys2, 64), epochs=8)
        # frozen weights unchanged after training
        np.testing.assert_array_equal(np.asarray(new_net.params[0]["W"]), frozen_w)
        assert new_net.evaluate(ListDataSetIterator.from_arrays(xs2, ys2, 64)).accuracy() > 0.7

    def test_nout_replace(self):
        src = mlp()
        new_net = (TransferLearning(src)
                   .n_out_replace(1, 24)
                   .build())
        assert new_net.conf.layers[1].n_out == 24
        assert new_net.params[1]["W"].shape == (32, 24)
        assert new_net.params[2]["W"].shape == (24, 3)
        # untouched layer keeps source params
        np.testing.assert_array_equal(np.asarray(new_net.params[0]["W"]),
                                      np.asarray(src.params[0]["W"]))

    def test_helper_featurize(self):
        xs, ys = blobs()
        src = mlp()
        helper = TransferLearningHelper(src, frozen_upto=0)
        feats = helper.featurize(DataSet(xs, ys))
        assert feats.features.shape == (256, 32)
        losses = helper.fit_featurized(feats, epochs=10)
        assert losses[-1] < losses[0]
        out = helper.output(xs)
        assert out.shape == (256, 3)
