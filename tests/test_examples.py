"""Every example script must run end-to-end on CPU (round-4 verdict
Next #6: the reference ships 8 runnable tutorials; these are the
equivalent user journeys, CI-tested).

Each runs in its own process (examples self-configure the platform via
DL4J_TPU_EXAMPLES_CPU; some pin device counts) and must print the final
"OK" its internal assertions guard."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EX = os.path.join(_REPO, "examples")

SCRIPTS = sorted(f for f in os.listdir(_EX)
                 if f.endswith(".py") and f[0].isdigit())


def test_all_tutorial_numbers_present():
    # the reference arc is 8 tutorials + the TPU flagship + decode serving
    nums = {s.split("_")[0] for s in SCRIPTS}
    assert nums == {"01", "02", "03", "04", "05", "06", "07", "08", "09",
                    "10"}


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["DL4J_TPU_EXAMPLES_CPU"] = "1"
    env.pop("JAX_PLATFORMS", None)
    # give example 09 a multi-device mesh to shard over
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    p = subprocess.run([sys.executable, os.path.join(_EX, script)],
                       env=env, cwd=_EX, capture_output=True, text=True,
                       timeout=600)
    assert p.returncode == 0, (
        f"{script} failed:\nstdout:\n{p.stdout[-2000:]}\n"
        f"stderr:\n{p.stderr[-3000:]}")
    assert "OK" in p.stdout
