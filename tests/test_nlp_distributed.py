"""DistributedWord2Vec: mesh-sharded skip-gram training on the 8-device
CPU mesh — semantic quality preserved, degenerate 1-device mesh exact."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp import DistributedWord2Vec, Word2Vec
from deeplearning4j_tpu.parallel import build_mesh


def topic_corpus(n=400, seed=0):
    rng = np.random.default_rng(seed)
    topics = [["cat", "dog", "pet", "fur", "paw", "tail", "meow", "bark"],
              ["cpu", "ram", "disk", "code", "byte", "chip", "core", "cache"]]
    return [" ".join(rng.choice(topics[int(rng.integers(0, 2))], size=8))
            for _ in range(n)]


class TestDistributedWord2Vec:
    def test_topics_separate_on_mesh(self):
        mesh = build_mesh({"data": 8})
        w2v = DistributedWord2Vec(mesh=mesh, layer_size=32, window=3,
                                  min_word_frequency=2, epochs=12,
                                  batch_size=128, seed=1, learning_rate=0.05,
                                  subsampling=0)
        w2v.fit(topic_corpus())
        within = w2v.similarity("cat", "dog")
        across = w2v.similarity("cat", "cpu")
        assert within > across + 0.2, f"within={within:.3f} across={across:.3f}"

    @pytest.mark.parametrize("dp", [1, 8])
    def test_mesh_matches_plain_word2vec_exactly(self, dp):
        """The psum'd raw-delta + global-count formulation reproduces the
        single-device occurrence averaging at ANY mesh size."""
        corpus = topic_corpus(100)
        kw = dict(layer_size=16, window=3, min_word_frequency=2, epochs=3,
                  batch_size=128, seed=5, learning_rate=0.05, subsampling=0)
        plain = Word2Vec(**kw)
        plain.fit(corpus)
        dist = DistributedWord2Vec(
            mesh=build_mesh({"data": dp}, devices=jax.devices()[:dp]), **kw)
        dist.fit(corpus)
        np.testing.assert_allclose(dist.syn0, plain.syn0, rtol=1e-4, atol=1e-5)

    def test_unsupported_modes_rejected(self):
        with pytest.raises(NotImplementedError, match="CBOW"):
            DistributedWord2Vec(cbow=True)
        with pytest.raises(ValueError, match="divisible"):
            DistributedWord2Vec(mesh=build_mesh({"data": 8}), batch_size=100)
        with pytest.raises(ValueError, match="axis"):
            DistributedWord2Vec(mesh=build_mesh({"model": 8}))
