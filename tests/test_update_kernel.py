"""Fused Adam/Nadam one-pass update: bit-identity vs the per-leaf plain
path (pallas-interpret, flat-jnp, and fallback modes) plus integration
through MultiLayerNetwork training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.updaters import (AMSGrad, Adam, AdaMax, Nadam,
                                            Updater)
from deeplearning4j_tpu.ops import update_kernel


@pytest.fixture(autouse=True)
def enable_kernel(monkeypatch):
    """The fused path is opt-in (DL4J_TPU_FUSED_UPDATE=1); tests exercise
    it explicitly."""
    monkeypatch.setattr(update_kernel, "ENABLED", True)


def tree(shapes, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return {f"l{i}": {"W": jnp.asarray(rng.normal(size=s), dtype),
                      "b": jnp.asarray(rng.normal(size=s[-1:]), dtype)}
            for i, s in enumerate(shapes)}


def nonzero_state(upd, params):
    """Adam state with NONZERO moments — zero moments hide FMA-ordering
    and beta-scaling differences in the m/v EMAs."""
    return {"m": jax.tree_util.tree_map(lambda p: p * 0.03, params),
            "v": jax.tree_util.tree_map(lambda p: p * p * 0.01, params)}


def _ulp_distance(x, y):
    """Elementwise distance in ulps via the monotone int mapping of the
    float bit patterns (works for f32/bf16/f64)."""
    ibits = {2: np.int16, 4: np.int32, 8: np.int64}[x.dtype.itemsize]
    xi = np.asarray(x).view(ibits).astype(np.int64)
    yi = np.asarray(y).view(ibits).astype(np.int64)
    # map sign-magnitude float ordering onto monotone integers
    xi = np.where(xi < 0, np.int64(-(2 ** 62)) - xi, xi)
    yi = np.where(yi < 0, np.int64(-(2 ** 62)) - yi, yi)
    return np.abs(xi - yi)


def assert_trees_bitwise(a, b, max_ulp=0):
    """max_ulp=0 -> strict bit identity.  max_ulp=1 tolerates XLA:CPU's
    layout-dependent FMA contraction (LLVM may or may not contract
    ``a*x + b*y`` depending on vector-lane boundaries, so the flat
    buffer and the per-leaf buffers can round one multiply-add
    differently); the math itself is the same chain either way."""
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype
        assert x.shape == y.shape
        x, y = np.asarray(x), np.asarray(y)
        if max_ulp == 0:
            np.testing.assert_array_equal(x, y)
        else:
            d = _ulp_distance(x, y)
            assert d.max() <= max_ulp, (
                f"max ulp diff {d.max()} at {int(d.argmax())} "
                f"({x.ravel()[d.argmax()]} vs {y.ravel()[d.argmax()]})")


def run_both(upd, kind, params, grads, state, it):
    """Plain per-leaf path and fused path, BOTH through jit (how they run
    inside a train step — the bit-comparability contract is jit-vs-jit;
    eager references differ by FMA contraction on sum-of-products)."""
    plain = jax.jit(lambda p, g, s, i: Updater.apply(upd, p, g, s, i))
    fused = jax.jit(
        lambda p, g, s, i: update_kernel.fused_apply(kind, upd, p, g, s, i))
    return plain(params, grads, state, it), fused(params, grads, state, it)


class TestBitIdentity:
    @pytest.mark.parametrize("upd,kind", [
        (Adam(lr=1e-3), "adam"),
        (Nadam(lr=2e-3), "nadam"),
    ])
    def test_pallas_matches_plain(self, upd, kind):
        # 37x61 = 2257 > one (8,128) tile -> pallas path, with padding
        params = tree([(37, 61), (61, 13)])
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = nonzero_state(upd, params)
        it = jnp.asarray(3.0, jnp.float32)
        (rp, rs), (fp, fs) = run_both(upd, kind, params, grads, state, it)
        assert_trees_bitwise(rp, fp)
        assert_trees_bitwise(rs, fs)

    def test_flat_jnp_matches_plain(self, monkeypatch):
        monkeypatch.setattr(update_kernel, "FORCE_JNP", True)
        upd = Adam(lr=1e-3)
        params = tree([(37, 61)])
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = nonzero_state(upd, params)
        it = jnp.asarray(0.0, jnp.float32)
        (rp, rs), (fp, fs) = run_both(upd, "adam", params, grads, state, it)
        assert_trees_bitwise(rp, fp)
        assert_trees_bitwise(rs, fs)

    def test_small_n_flat_jnp_matches_plain(self):
        # below one (8,128) tile the pallas path is skipped
        upd = Nadam(lr=1e-3)
        params = tree([(7, 11)])
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = nonzero_state(upd, params)
        it = jnp.asarray(5.0, jnp.float32)
        (rp, rs), (fp, fs) = run_both(upd, "nadam", params, grads, state, it)
        assert_trees_bitwise(rp, fp, max_ulp=1)
        assert_trees_bitwise(rs, fs, max_ulp=1)

    def test_bf16_moments_match_plain(self):
        upd = Adam(lr=1e-3, moment_dtype="bfloat16")
        params = tree([(37, 61)])
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = upd.init_state(params)
        assert jax.tree_util.tree_leaves(state["m"])[0].dtype == jnp.bfloat16
        it = jnp.asarray(2.0, jnp.float32)
        (rp, rs), (fp, fs) = run_both(upd, "adam", params, grads, state, it)
        assert_trees_bitwise(rp, fp)
        assert_trees_bitwise(rs, fs)
        assert jax.tree_util.tree_leaves(fs["m"])[0].dtype == jnp.bfloat16

    def test_bf16_params_match_plain(self):
        upd = Adam(lr=1e-3)
        params = tree([(37, 61)], dtype=jnp.bfloat16)
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = nonzero_state(upd, params)
        it = jnp.asarray(1.0, jnp.float32)
        (rp, rs), (fp, fs) = run_both(upd, "adam", params, grads, state, it)
        assert_trees_bitwise(rp, fp)
        assert jax.tree_util.tree_leaves(fp)[0].dtype == jnp.bfloat16


class TestFallbacks:
    def test_disabled_returns_none(self, monkeypatch):
        monkeypatch.setattr(update_kernel, "ENABLED", False)
        upd = Adam()
        params = tree([(8, 8)])
        state = upd.init_state(params)
        out = update_kernel.fused_apply("adam", upd, params, params, state,
                                        jnp.asarray(0.0, jnp.float32))
        assert out is None

    def test_f64_returns_none(self):
        jax.config.update("jax_enable_x64", True)
        try:
            upd = Adam()
            params = {"W": jnp.ones((8, 8), jnp.float64)}
            state = upd.init_state(params)
            out = update_kernel.fused_apply(
                "adam", upd, params, params, state,
                jnp.asarray(0.0, jnp.float32))
            assert out is None
            # ...and .apply still works via the plain path
            p2, s2 = upd.apply(params, params, state,
                               jnp.asarray(0.0, jnp.float32))
            assert p2["W"].dtype == jnp.float64
        finally:
            jax.config.update("jax_enable_x64", False)

    def test_empty_tree_returns_none(self):
        upd = Adam()
        out = update_kernel.fused_apply("adam", upd, {}, {}, {"m": {}, "v": {}},
                                        jnp.asarray(0.0, jnp.float32))
        assert out is None

    def test_kind_of_exact_types_only(self):
        assert update_kernel.kind_of(Adam()) == "adam"
        assert update_kernel.kind_of(Nadam()) == "nadam"
        # subclasses carry DIFFERENT math: must not take the Adam kernel
        assert update_kernel.kind_of(AdaMax()) is None
        assert update_kernel.kind_of(AMSGrad()) is None

    def test_amsgrad_apply_takes_plain_path(self):
        # AMSGrad inherits Adam.apply; kind_of(None) must route it to the
        # base per-leaf path without touching the kernel
        upd = AMSGrad(lr=1e-3)
        params = tree([(16, 16)])
        grads = jax.tree_util.tree_map(lambda p: p * 0.1, params)
        state = upd.init_state(params)
        it = jnp.asarray(0.0, jnp.float32)
        p2, s2 = upd.apply(params, grads, state, it)
        upds, s3 = upd.update(grads, state, it)
        ref = jax.tree_util.tree_map(
            lambda pp, uu: (pp.astype(jnp.float32) - uu).astype(pp.dtype),
            params, upds)
        assert_trees_bitwise(p2, ref)


class TestIntegration:
    def _fit(self, steps=3):
        from deeplearning4j_tpu.datasets import DataSet
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                                      NeuralNetConfiguration)

        rng = np.random.default_rng(7)
        x = rng.normal(size=(32, 20)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        conf = (NeuralNetConfiguration.builder().seed(0)
                .updater(Adam(lr=1e-2))
                .layer(Dense(n_out=48, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(20)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        ds = DataSet(x, y)
        for _ in range(steps):
            net.fit_batch(ds)
        return net.params

    def test_one_network_step_matches_plain(self, monkeypatch):
        # inside the full jitted train step the surrounding program
        # changes XLA:CPU's fusion/FMA choices -> 1-ulp tolerance per
        # application (per-step divergence compounds over iterations)
        p_fused = self._fit(steps=1)
        monkeypatch.setattr(update_kernel, "ENABLED", False)
        p_plain = self._fit(steps=1)
        assert_trees_bitwise(p_fused, p_plain, max_ulp=1)

    def test_network_training_matches_plain(self, monkeypatch):
        p_fused = self._fit(steps=5)
        monkeypatch.setattr(update_kernel, "ENABLED", False)
        p_plain = self._fit(steps=5)
        for a, b in zip(jax.tree_util.tree_leaves(p_fused),
                        jax.tree_util.tree_leaves(p_plain)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    def test_jit_apply_emits_train_update_span(self, tmp_path):
        from deeplearning4j_tpu.obs import trace as obs_trace

        upd = Adam(lr=1e-3)
        params = tree([(16, 16)])
        state = upd.init_state(params)
        run = update_kernel.jit_apply(upd)
        it = jnp.asarray(0.0, jnp.float32)
        path = str(tmp_path / "upd_trace.json")
        obs_trace.enable_tracing(path=path)
        try:
            p, s = run(params, params, state, it)
            jax.block_until_ready(jax.tree_util.tree_leaves(p))
            obs_trace.flush(path)
        finally:
            obs_trace.disable_tracing()
        import json
        with open(path) as f:
            ev = json.load(f)["traceEvents"]
        names = {e["name"] for e in ev if e.get("ph") == "X"}
        assert "train/update" in names
