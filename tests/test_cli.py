"""CLI: train → save → evaluate → predict → summary round trip."""

import json
import re
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.cli import main


@pytest.fixture()
def blob_npz(tmp_path):
    rng = np.random.default_rng(0)
    xs = np.concatenate([rng.normal(-2, 1, (96, 6)),
                         rng.normal(2, 1, (96, 6))]).astype(np.float32)
    ys = np.concatenate([np.zeros(96, np.int64), np.ones(96, np.int64)])
    path = str(tmp_path / "blobs.npz")
    np.savez(path, x=xs, y=ys)
    return path


@pytest.fixture()
def conf_json(tmp_path):
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(lr=0.02))
            .layer(Dense(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    path = str(tmp_path / "conf.json")
    with open(path, "w") as f:
        json.dump(conf.to_dict(), f)
    return path


class TestCli:
    def test_full_round_trip(self, tmp_path, blob_npz, conf_json, capsys):
        model = str(tmp_path / "model.zip")
        dash = str(tmp_path / "report.html")
        rc = main(["train", "--config", conf_json, "--data", blob_npz,
                   "--epochs", "8", "--batch-size", "64",
                   "--output", model, "--dashboard", dash])
        assert rc == 0 and os.path.exists(model) and os.path.exists(dash)
        out = capsys.readouterr().out
        assert "final loss" in out

        rc = main(["evaluate", "--model", model, "--data", blob_npz])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Accuracy" in out or "accuracy" in out

        preds = str(tmp_path / "preds.npz")
        rc = main(["predict", "--model", model, "--input", blob_npz,
                   "--output", preds])
        assert rc == 0
        p = np.load(preds)["predictions"]
        assert p.shape == (192, 2)

        rc = main(["summary", "--model", model, "--batch-size", "16"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "params" in out

    def test_zoo_training(self, tmp_path, capsys):
        rng = np.random.default_rng(1)
        xs = rng.normal(size=(64, 28, 28, 1)).astype(np.float32)
        ys = rng.integers(0, 10, 64).astype(np.int64)
        data = str(tmp_path / "imgs.npz")
        np.savez(data, x=xs, y=ys)
        rc = main(["train", "--zoo", "lenet",
                   "--zoo-args", '{"height": 28, "width": 28, "channels": 1,'
                   ' "num_classes": 10}',
                   "--data", data, "--epochs", "1", "--batch-size", "32"])
        assert rc == 0
        assert "final loss" in capsys.readouterr().out

    def test_unknown_zoo_rejected(self, blob_npz):
        with pytest.raises(SystemExit, match="unknown zoo"):
            main(["train", "--zoo", "nope", "--data", blob_npz])

    def test_module_entrypoint(self):
        r = subprocess.run([sys.executable, "-m", "deeplearning4j_tpu",
                            "--help"], capture_output=True, text=True,
                           cwd="/root/repo", timeout=120)
        assert r.returncode == 0 and "train" in r.stdout


class TestMeshTraining:
    """--mesh: CLI sharded training (the reference ParallelWrapperMain
    role, parallelism/main/ParallelWrapperMain.java)."""

    def test_train_over_mesh(self, tmp_path, blob_npz, conf_json, capsys):
        model = str(tmp_path / "mesh_model.zip")
        rc = main(["train", "--config", conf_json, "--data", blob_npz,
                   "--epochs", "2", "--batch-size", "32", "--seed", "7",
                   "--mesh", "data=8", "--output", model])
        assert rc == 0
        out = capsys.readouterr().out
        assert "mesh: {'data': 8}" in out
        assert os.path.exists(model)
        rc = main(["evaluate", "--model", model, "--data", blob_npz])
        assert rc == 0
        out = capsys.readouterr().out
        m = re.search(r"[Aa]ccuracy:?\s+([0-9.]+)", out)
        assert m, out
        assert float(m.group(1)) > 0.9

    def test_batch_not_divisible_rejected(self, blob_npz, conf_json):
        with pytest.raises(SystemExit, match="not divisible"):
            main(["train", "--config", conf_json, "--data", blob_npz,
                  "--batch-size", "30", "--mesh", "data=8"])

    def test_bad_mesh_spec_rejected(self, blob_npz, conf_json):
        for bad in ("whatever", "data=four", "data=", "data=0", "data=-2",
                    "model=0", "data=4,data=2"):
            with pytest.raises(SystemExit, match="bad --mesh"):
                main(["train", "--config", conf_json, "--data", blob_npz,
                      "--batch-size", "32", "--mesh", bad])

    def test_model_only_mesh_gets_data_axis(self, blob_npz, conf_json,
                                            capsys):
        """'model=2' must not crash ShardedTrainer: a data axis of size 1
        is implied (the batch sharding names it)."""
        rc = main(["train", "--config", conf_json, "--data", blob_npz,
                   "--epochs", "1", "--batch-size", "32",
                   "--mesh", "model=2"])
        assert rc == 0
        assert "'model': 2" in capsys.readouterr().out

    def test_infer_axis_resolved_before_divisibility_check(self, blob_npz,
                                                           conf_json):
        """-1 resolves against the device count (8 here) BEFORE the
        batch-divisibility preflight, so the mid-epoch shard error the
        check exists to prevent cannot slip through."""
        with pytest.raises(SystemExit, match="not divisible"):
            main(["train", "--config", conf_json, "--data", blob_npz,
                  "--batch-size", "30", "--mesh", "data=-1"])

    def test_tiny_dataset_clear_error(self, tmp_path, conf_json):
        xs = np.zeros((20, 6), np.float32)
        ys = np.zeros(20, np.int64)
        data = str(tmp_path / "tiny.npz")
        np.savez(data, x=xs, y=ys)
        with pytest.raises(SystemExit, match="no full batch"):
            main(["train", "--config", conf_json, "--data", data,
                  "--batch-size", "32", "--mesh", "data=8"])

    def test_epoch_done_fires_in_mesh_mode(self, blob_npz, conf_json,
                                           tmp_path):
        """Dashboard/epoch listeners must not silently disappear when
        training routes through ShardedTrainer."""
        dash = str(tmp_path / "mesh_dash.html")
        rc = main(["train", "--config", conf_json, "--data", blob_npz,
                   "--epochs", "2", "--batch-size", "32",
                   "--mesh", "data=8", "--dashboard", dash])
        assert rc == 0
        assert os.path.exists(dash)

    def test_ragged_tail_drop_is_announced(self, tmp_path, conf_json,
                                           capsys):
        xs = np.concatenate([np.full((50, 6), -2, np.float32),
                             np.full((50, 6), 2, np.float32)])
        ys = np.concatenate([np.zeros(50, np.int64), np.ones(50, np.int64)])
        data = str(tmp_path / "odd.npz")
        np.savez(data, x=xs, y=ys)
        rc = main(["train", "--config", conf_json, "--data", data,
                   "--epochs", "1", "--batch-size", "32", "--mesh",
                   "data=8"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "drops the ragged tail: 4 of 100 samples" in out


class TestServeTenantsAndModels:
    """serve --tenants tenants.json / --models NAME=PATH,... parsing
    (docs/SERVING.md "Multi-tenant serving")."""

    def _write(self, tmp_path, payload):
        path = str(tmp_path / "tenants.json")
        with open(path, "w") as f:
            json.dump(payload, f)
        return path

    def test_parse_tenants_list_and_wrapper(self, tmp_path):
        from deeplearning4j_tpu.cli import _parse_tenants

        rows = [{"tenant": "a", "weight": 2.0, "quota_qps": 10,
                 "slo_ms": 200},
                {"tenant": "b", "quota_concurrent": 4,
                 "admission": "block"}]
        for payload in (rows, {"tenants": rows}):
            table = _parse_tenants(self._write(tmp_path, payload))
            assert table.tenants() == ["a", "b"]
            assert table.weight("a") == 2.0
            assert table.admission_for("b") == "block"

    def test_parse_tenants_bad_specs_are_one_line_errors(self, tmp_path):
        from deeplearning4j_tpu.cli import _parse_tenants

        with pytest.raises(SystemExit, match="bad --tenants"):
            _parse_tenants(str(tmp_path / "missing.json"))
        path = str(tmp_path / "junk.json")
        with open(path, "w") as f:
            f.write("{not json")
        with pytest.raises(SystemExit, match="bad --tenants"):
            _parse_tenants(path)
        with pytest.raises(SystemExit, match="unknown tenant-spec keys"):
            _parse_tenants(self._write(
                tmp_path, [{"tenant": "a", "qps": 5}]))
        with pytest.raises(SystemExit, match="bad --tenants"):
            _parse_tenants(self._write(tmp_path, []))
        with pytest.raises(SystemExit, match="bad --tenants"):
            _parse_tenants(self._write(tmp_path, [{"weight": 1.0}]))

    def test_parse_models_specs(self):
        from deeplearning4j_tpu.cli import _parse_models

        assert _parse_models("a=/x/a.zip,b=/y/b.zip") == [
            ("a", "/x/a.zip"), ("b", "/y/b.zip")]
        # bare paths name themselves after the file stem
        assert _parse_models("/ckpt/fraud.zip") == [
            ("fraud", "/ckpt/fraud.zip")]
        with pytest.raises(SystemExit, match="duplicate model name"):
            _parse_models("a=/x/a.zip,a=/y/b.zip")
        with pytest.raises(SystemExit, match="bad --models"):
            _parse_models("")
        with pytest.raises(SystemExit, match="bad --models"):
            _parse_models("a=,b=/y/b.zip")

    def test_serve_flag_combinations_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="--model/--models"):
            main(["serve"])
        spec = self._write(tmp_path, [{"tenant": "a"}])
        with pytest.raises(SystemExit, match="--tenants configures"):
            main(["serve", "--fleet", "localhost:1,localhost:2",
                  "--tenants", spec])
