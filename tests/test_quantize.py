"""Int8 quantized serving: per-channel scale round-trips, degenerate
channels, calibration determinism, the zoo logit-divergence envelope, and
the engine's zero-serve-time-compiles contract under int8 warmup."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import quantize as qz


def mlp(seed=0, n_in=12, n_out=4, steps=20):
    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                                  NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.updaters import Adam

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(64, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 64)]
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(lr=1e-2))
            .layer(Dense(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in)).build())
    net = MultiLayerNetwork(conf)
    net.init()
    for _ in range(steps):
        net.fit_batch(DataSet(x, y))
    return net, x


class TestWeightQuantization:
    def test_per_channel_round_trip(self):
        rng = np.random.default_rng(0)
        # channels at wildly different magnitudes: per-channel scales
        # must bound the round-trip error per channel, not globally
        w = rng.normal(size=(64, 8)).astype(np.float32)
        w *= np.logspace(-3, 2, 8, dtype=np.float32)[None, :]
        q = qz.quantize_weight(jnp.asarray(w), act_amax=1.0)
        assert q.values.dtype == jnp.int8
        back = np.asarray(q.dequantize())
        amax = np.abs(w).max(axis=0)
        # symmetric int8: error <= scale/2 = amax/254 per channel
        assert (np.abs(back - w) <= amax / 254 + 1e-9).all()

    def test_all_zero_channel(self):
        w = np.zeros((16, 3), np.float32)
        w[:, 1] = np.linspace(-1, 1, 16)
        q = qz.quantize_weight(jnp.asarray(w), act_amax=1.0)
        back = np.asarray(q.dequantize())
        assert not back[:, 0].any() and not back[:, 2].any()
        assert np.abs(back[:, 1] - w[:, 1]).max() <= 1 / 254 + 1e-9

    def test_outlier_channel_does_not_poison_others(self):
        rng = np.random.default_rng(1)
        w = rng.normal(size=(32, 4)).astype(np.float32)
        w[0, 3] = 1e4                       # one huge outlier channel
        q = qz.quantize_weight(jnp.asarray(w), act_amax=1.0)
        back = np.asarray(q.dequantize())
        # the outlier only degrades ITS channel; per-tensor quantization
        # would flatten the small channels to zero
        assert np.abs(back[:, :3] - w[:, :3]).max() <= np.abs(
            w[:, :3]).max() / 127 + 1e-9

    def test_int8_matmul_int32_accumulation(self):
        # values big enough that an int8/int16 accumulator would overflow
        w = jnp.ones((256, 2), jnp.float32)
        q = qz.quantize_weight(w, act_amax=1.0)
        x = jnp.ones((1, 256), jnp.float32)
        y = np.asarray(x @ q)
        np.testing.assert_allclose(y, 256.0, rtol=0.02)

    def test_astype_is_identity(self):
        q = qz.quantize_weight(jnp.ones((8, 2)), act_amax=1.0)
        assert q.astype(jnp.bfloat16) is q
        assert q.shape == (8, 2) and q.ndim == 2


class TestCalibration:
    def test_deterministic_under_fixed_inputs(self):
        net, x = mlp(seed=3)
        s1 = qz.calibrate(net, x)
        s2 = qz.calibrate(net, x)
        assert s1 == s2 and len(s1) == 2

    def test_sweeps_take_running_max(self):
        net, x = mlp(seed=4)
        small = qz.calibrate(net, x * 0.1)
        both = qz.calibrate(net, [x * 0.1, x])
        assert all(both[k] >= small[k] for k in small)

    def test_unexercised_weight_stays_f32(self):
        net, x = mlp(seed=5)
        stats = qz.calibrate(net, x)
        missing = dict(list(stats.items())[:1])   # drop one layer's stats
        qp = qz.quantize_params(net.params, missing)
        kinds = [type(l) for l in jax.tree_util.tree_leaves(
            qp, is_leaf=lambda l: isinstance(l, qz.Int8Weight))
            if isinstance(l, qz.Int8Weight)]
        assert len(kinds) == 1

    def test_quantize_model_requires_candidates(self):
        class NoDense:
            params = {"foo": jnp.ones((3,))}
            state = {}

            def _apply_layers(self, params, state, x, **kw):
                return (x, state, None)

        with pytest.raises(ValueError, match="no 2-D 'W'"):
            qz.quantize_model(NoDense(), np.ones((4, 3), np.float32))


class TestLogitEnvelope:
    def test_mlp_envelope(self):
        net, x = mlp(seed=6)
        qm = qz.quantize_model(net, x)
        ref = np.asarray(net.output(x))
        got = qm.output(x)
        denom = max(np.abs(ref).max(), 1e-6)
        assert np.abs(ref - got).max() / denom < 0.05
        assert (ref.argmax(1) == got.argmax(1)).mean() >= 0.95

    def test_zoo_iris_mlp_envelope(self, tmp_path):
        from deeplearning4j_tpu.datasets.fetchers import load_iris
        from deeplearning4j_tpu.models import (PretrainedType,
                                               init_pretrained,
                                               init_pretrained_int8)

        xs, ys = load_iris()
        xs = xs.astype(np.float32)
        net = init_pretrained("iris_mlp", PretrainedType.IRIS,
                              cache_dir=str(tmp_path))
        qm = init_pretrained_int8("iris_mlp", PretrainedType.IRIS,
                                  calibration_inputs=xs,
                                  cache_dir=str(tmp_path))
        ref = np.asarray(net.output(xs))
        got = qm.output(xs)
        # the shipped artifact's accuracy must survive quantization
        assert (got.argmax(1) == ys).mean() > 0.97
        assert (ref.argmax(1) == got.argmax(1)).mean() >= 0.99

    def test_zoo_int8_requires_calibration_inputs(self):
        from deeplearning4j_tpu.models import init_pretrained_int8
        with pytest.raises(ValueError, match="calibration_inputs"):
            init_pretrained_int8("iris_mlp", "iris")


class TestEngineInt8:
    def _engine(self, net, **kw):
        from deeplearning4j_tpu.serving.engine import Engine
        return Engine(net, max_batch=8, slo_ms=200.0, bucket_sizes=(4, 8),
                      replicas=1, **kw)

    def test_zero_serve_time_compiles_with_int8_warmup(self):
        net, x = mlp(seed=7)
        eng = self._engine(net)
        try:
            eng.load(input_shape=(12,), quantize="int8",
                     calibration_inputs=x)
            n0 = eng.compile_cache_size()
            assert n0 is not None and n0 >= 2   # one per bucket
            for b in (3, 4, 8):
                out = eng.output(x[:b])
                assert out.shape == (b, 4)
            assert eng.compile_cache_size() == n0
        finally:
            eng.shutdown()

    def test_int8_serving_matches_direct_quantized_forward(self):
        net, x = mlp(seed=8)
        qm = qz.quantize_model(net, x)
        eng = self._engine(net)
        try:
            eng.load(input_shape=(12,), quantize="int8",
                     calibration_inputs=x)
            served = eng.output(x[:4])
            np.testing.assert_allclose(served, qm.output(x[:4]),
                                       rtol=1e-5, atol=1e-6)
        finally:
            eng.shutdown()

    def test_bad_mode_rejected(self):
        net, _ = mlp(seed=9, steps=1)
        eng = self._engine(net)
        try:
            with pytest.raises(ValueError, match="quantize"):
                eng.load(input_shape=(12,), quantize="int4")
        finally:
            eng.shutdown()
