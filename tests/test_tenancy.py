"""Multi-tenant admission (serving/tenancy.py + the batcher lanes).

The two halves of the isolation contract, tested at the layer that owns
each: TenantTable's atomic check-and-charge (quotas can never over-admit
under racing submits — the mirror of the PR-13 ContinuousBatcher race
tests) and the batcher's weighted-fair lanes (a bursting tenant's
backlog queues behind its own lane, never in front of a victim's).
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.serving import (
    DynamicBatcher, OverloadedError, TenantConfig, TenantOverloadedError,
    TenantTable,
)


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _table(rows, **kw):
    return TenantTable.from_specs(rows, **kw)


class TestTenantConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            TenantConfig("")
        with pytest.raises(ValueError):
            TenantConfig("a", weight=0)
        with pytest.raises(ValueError):
            TenantConfig("a", slo_ms=0)
        with pytest.raises(ValueError):
            TenantConfig("a", quota_qps=-1)
        with pytest.raises(ValueError):
            TenantConfig("a", quota_concurrent=0)
        with pytest.raises(ValueError):
            TenantConfig("a", admission="maybe")

    def test_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown tenant-spec keys"):
            TenantConfig.from_dict({"tenant": "a", "qps": 5})
        with pytest.raises(ValueError, match="needs a 'tenant' key"):
            TenantConfig.from_dict({"weight": 2.0})

    def test_burst_defaults_to_qps(self):
        assert TenantConfig("a", quota_qps=10).burst == 10.0
        assert TenantConfig("a", quota_qps=0.25).burst == 1.0   # floor 1
        assert TenantConfig("a", quota_qps=10, burst=40).burst == 40.0
        assert TenantConfig("a").burst is None

    def test_roundtrip(self):
        c = TenantConfig.from_dict(
            {"tenant": "a", "model": "m", "slo_ms": 100, "weight": 2,
             "quota_qps": 5, "quota_concurrent": 3, "admission": "block"})
        d = c.to_dict()
        assert d["tenant"] == "a" and d["model"] == "m"
        assert d["weight"] == 2.0 and d["admission"] == "block"


class TestTenantTable:
    def test_resolve_precedence(self):
        wide = TenantConfig("a", weight=1.0)
        scoped = TenantConfig("a", "m2", weight=3.0)
        dflt = TenantConfig("anyone", weight=7.0)
        t = TenantTable([wide, scoped], default=dflt)
        assert t.resolve("a", "m2") is scoped
        assert t.resolve("a", "m1") is wide
        assert t.resolve("a") is wide
        assert t.resolve("stranger", "m1") is dflt
        assert TenantTable([wide]).resolve("stranger") is None

    def test_untagged_traffic_is_never_limited(self):
        t = _table([{"tenant": "a", "quota_concurrent": 1}])
        for _ in range(10):
            assert t.try_admit("")
        assert t.concurrent("") == 0

    def test_concurrent_cap_charges_and_releases(self):
        t = _table([{"tenant": "a", "quota_concurrent": 2}])
        assert t.try_admit("a") and t.try_admit("a")
        assert not t.try_admit("a")          # cap reached, nothing charged
        assert t.concurrent("a") == 2
        t.release("a")
        assert t.try_admit("a")              # freed slot is admittable again
        assert t.snapshot()["a"]["admitted"] == 3

    def test_qps_token_bucket_with_injected_clock(self):
        clk = _FakeClock()
        t = _table([{"tenant": "a", "quota_qps": 2, "burst": 2}], clock=clk)
        assert t.try_admit("a") and t.try_admit("a")
        assert not t.try_admit("a")          # bucket empty at t=0
        clk.t = 0.5                          # 2 qps -> one token back
        assert t.try_admit("a")
        assert not t.try_admit("a")
        clk.t = 10.0                         # refill clamps at burst
        assert t.try_admit("a") and t.try_admit("a")
        assert not t.try_admit("a")

    def test_shed_builds_typed_error_and_counts(self):
        t = _table([{"tenant": "a", "quota_concurrent": 1}])
        err = t.shed("a", "m1", reason="quota_qps")
        assert isinstance(err, TenantOverloadedError)
        assert isinstance(err, OverloadedError)     # 429 path catches base
        assert err.tenant == "a" and err.shed_count == 1
        assert err.reason == "quota_qps"
        assert t.shed("a").shed_count == 2
        assert t.shed_count("a") == 2 and t.shed_count("b") == 0


class TestBatcherFairShare:
    def test_weighted_fair_drain_is_proportional(self):
        """Weight 2 vs 1: over a backlog drained in small batches the
        2.0 tenant gets ~2x the rows, and the anonymous lane still
        advances (weight 1.0)."""
        t = _table([{"tenant": "heavy", "weight": 2.0},
                    {"tenant": "light", "weight": 1.0}])
        b = DynamicBatcher(max_batch=1, slo_ms=10_000, max_queue=10_000,
                           max_wait_ms=0.0, tenants=t)
        x = np.zeros((1, 4), np.float32)
        for _ in range(30):
            b.submit(x, tenant="heavy")
            b.submit(x, tenant="light")
        order = []
        for _ in range(30):
            batch = b.next_batch()
            order.extend(r.tenant for r in batch)
        heavy = order.count("heavy")
        light = order.count("light")
        assert heavy + light == 30
        # stride scheduling: heavy ~ 2x light (exact interleave 2:1)
        assert 1.5 <= heavy / max(light, 1) <= 2.5
        b.close(fail_pending=True)

    def test_burst_backlog_does_not_delay_victim(self):
        """100 queued requests from the burster, then one victim
        arrival: the victim's request is served within the next
        scheduling round, not behind the whole burst backlog."""
        t = _table([{"tenant": "burst", "weight": 1.0},
                    {"tenant": "victim", "weight": 1.0}])
        b = DynamicBatcher(max_batch=2, slo_ms=10_000, max_queue=10_000,
                           max_wait_ms=0.0, tenants=t)
        x = np.zeros((1, 4), np.float32)
        for _ in range(100):
            b.submit(x, tenant="burst")
        b.submit(x, tenant="victim")
        served = []
        while len(served) < 6:
            served.extend(r.tenant for r in b.next_batch())
        assert "victim" in served[:4]
        b.close(fail_pending=True)


class TestQuotaRaces:
    def test_16_threads_racing_submit_admit_exactly_the_caps(self):
        """16 threads race ``submit`` across 3 tenants whose concurrent
        quotas are 5/3/7: the single-critical-section check-and-charge
        must admit EXACTLY each tenant's cap (never cap+1 from a
        check-then-act window) and shed the rest with the typed error
        carrying the right tenant — the tenancy mirror of the PR-13
        ContinuousBatcher queue-cap race test."""
        caps = {"t0": 5, "t1": 3, "t2": 7}
        t = _table([{"tenant": k, "quota_concurrent": v}
                    for k, v in caps.items()])
        b = DynamicBatcher(max_batch=4, slo_ms=10_000, max_queue=10_000,
                           tenants=t)
        x = np.zeros((1, 4), np.float32)
        n_threads, per_thread = 16, 9
        start = threading.Barrier(n_threads)
        admitted, shed, lock = [], [], threading.Lock()

        def pump(tid):
            tenant = f"t{tid % 3}"
            start.wait()
            for _ in range(per_thread):
                try:
                    fut = b.submit(x, tenant=tenant)
                except TenantOverloadedError as e:
                    with lock:
                        shed.append((tenant, e))
                else:
                    with lock:
                        admitted.append((tenant, fut))

        threads = [threading.Thread(target=pump, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        by_tenant = {k: [f for tt, f in admitted if tt == k] for k in caps}
        for k, cap in caps.items():
            assert len(by_tenant[k]) == cap, (k, len(by_tenant[k]))
            assert t.concurrent(k) == cap
        assert len(admitted) + len(shed) == n_threads * per_thread
        # every shed is typed with ITS tenant, and the table's counters
        # agree exactly with what the submitters saw
        for tenant, e in shed:
            assert e.tenant == tenant
        for k in caps:
            assert t.shed_count(k) == sum(1 for tt, _ in shed if tt == k)
        b.close(fail_pending=True)
        for _, fut in admitted:
            assert fut.done()

    def test_drained_tenants_queued_futures_resolve_typed(self):
        """begin_drain + close: every queued future of every tenant
        resolves with a typed error — nothing hangs, and post-drain
        submits shed synchronously."""
        t = _table([{"tenant": "a", "quota_concurrent": 8}])
        b = DynamicBatcher(max_batch=4, slo_ms=10_000, max_queue=100,
                           tenants=t)
        x = np.zeros((1, 4), np.float32)
        futs = [b.submit(x, tenant="a") for _ in range(6)]
        b.begin_drain()
        with pytest.raises(OverloadedError):
            b.submit(x, tenant="a")
        b.close(fail_pending=True)
        for f in futs:
            assert f.done()
            with pytest.raises(RuntimeError):
                f.result(timeout=1)
        # releases ran via done-callbacks: the tenant's budget is whole
        assert t.concurrent("a") == 0

    def test_release_is_exactly_once_via_done_callback(self):
        t = _table([{"tenant": "a", "quota_concurrent": 2}])
        b = DynamicBatcher(max_batch=4, slo_ms=10_000, tenants=t)
        x = np.zeros((1, 4), np.float32)
        f1 = b.submit(x, tenant="a")
        f2 = b.submit(x, tenant="a")
        with pytest.raises(TenantOverloadedError):
            b.submit(x, tenant="a")
        batch = b.next_batch()
        assert len(batch) == 2
        for r in batch:
            r.future.set_result(np.zeros((1, 1)))
        assert f1.done() and f2.done()
        assert t.concurrent("a") == 0
        assert b.submit(x, tenant="a") is not None
        b.close(fail_pending=True)
