"""Networked periphery — loopback integration tests (VERDICT round 2,
Missing #2/#3/#4): tensor pub-sub streaming, KNN REST server/client,
remote stats routing.  Everything runs on 127.0.0.1 with auto-assigned
ports; no external services."""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    NearestNeighborsClient,
    NearestNeighborsServer,
)
from deeplearning4j_tpu.streaming import (
    NDArrayConsumer,
    NDArrayPublisher,
    StreamingDataSetIterator,
    TensorBroker,
)
from deeplearning4j_tpu.ui import InMemoryStatsStorage, RemoteStatsRouter, UIServer


class TestTensorPubSub:
    def test_publish_consume_roundtrip(self):
        broker = TensorBroker().start()
        try:
            sub = NDArrayConsumer(broker.address, "t").connect()
            time.sleep(0.05)  # let the broker register the subscription
            pub = NDArrayPublisher(broker.address, "t").connect()
            rng = np.random.default_rng(0)
            sent = [rng.normal(size=(4, 3)).astype(np.float32) for _ in range(5)]
            for a in sent:
                pub.publish(a)
            got = [sub.next(timeout=5) for _ in range(5)]
            for a, b in zip(sent, got):
                np.testing.assert_allclose(a, b)
            pub.close()
            sub.close()
        finally:
            broker.stop()

    def test_fanout_to_multiple_subscribers(self):
        broker = TensorBroker().start()
        try:
            subs = [NDArrayConsumer(broker.address, "x").connect()
                    for _ in range(3)]
            time.sleep(0.05)
            pub = NDArrayPublisher(broker.address, "x").connect()
            arr = np.arange(6, dtype=np.float32).reshape(2, 3)
            pub.publish(arr)
            for s in subs:
                np.testing.assert_allclose(s.next(timeout=5), arr)
        finally:
            broker.stop()

    def test_topic_isolation(self):
        broker = TensorBroker().start()
        try:
            sub_a = NDArrayConsumer(broker.address, "a").connect()
            sub_b = NDArrayConsumer(broker.address, "b").connect()
            time.sleep(0.05)
            NDArrayPublisher(broker.address, "a").connect().publish(
                np.ones((2,), np.float32))
            np.testing.assert_allclose(sub_a.next(timeout=5), np.ones(2))
            with pytest.raises(Exception):  # queue.Empty
                sub_b._q.get(timeout=0.2)
        finally:
            broker.stop()

    def test_streaming_iterator_trains_a_model(self):
        """End-to-end: stream feature/label batches through the broker into
        MultiLayerNetwork.fit (the reference's Camel-route role)."""
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.updaters import Adam

        broker = TensorBroker().start()
        try:
            it = StreamingDataSetIterator(broker.address, max_batches=4,
                                          timeout=10)
            time.sleep(0.05)
            fpub = NDArrayPublisher(broker.address, "features").connect()
            lpub = NDArrayPublisher(broker.address, "labels").connect()
            rng = np.random.default_rng(0)
            for _ in range(4):
                labels = rng.integers(0, 2, 16)
                x = (labels[:, None] * 2.0 - 1.0
                     + rng.normal(scale=0.3, size=(16, 4))).astype(np.float32)
                fpub.publish(x)
                lpub.publish(np.eye(2, dtype=np.float32)[labels])
            conf = (NeuralNetConfiguration.builder()
                    .updater(Adam(lr=0.05))
                    .layer(Dense(n_out=8, activation="relu"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.feed_forward(4)).build())
            net = MultiLayerNetwork(conf)
            net.init()
            losses = net.fit(it)
            assert len(losses) == 4
            assert all(np.isfinite(float(l)) for l in losses)
        finally:
            broker.stop()


class TestKnnServer:
    @pytest.fixture()
    def server(self):
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(50, 8)).astype(np.float32)
        srv = NearestNeighborsServer(pts).start()
        yield srv, pts
        srv.stop()

    def test_knnnew_matches_local_index(self, server):
        srv, pts = server
        client = NearestNeighborsClient(srv.url)
        q = pts[7] + 0.01
        results = client.knn_new(q, k=3)
        assert len(results) == 3
        assert results[0]["index"] == 7
        d_local, i_local = srv.index.knn(q[None, :], 3)
        assert [r["index"] for r in results] == [int(x) for x in i_local[0]]
        np.testing.assert_allclose([r["distance"] for r in results],
                                   d_local[0], rtol=1e-5)

    def test_knn_by_id_excludes_self(self, server):
        srv, pts = server
        client = NearestNeighborsClient(srv.url)
        results = client.knn(index=3, k=4)
        assert len(results) == 4
        assert all(r["index"] != 3 for r in results)

    def test_bad_requests_are_400(self, server):
        srv, _ = server
        req = urllib.request.Request(
            srv.url + "/knn", data=json.dumps({"id": 999, "k": 2}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=5)
        assert e.value.code == 400


class TestRemoteStatsRouting:
    def test_router_posts_into_remote_storage(self):
        storage = InMemoryStatsStorage()
        ui = UIServer(port=0, enable_remote=True).attach(storage).start()
        try:
            router = RemoteStatsRouter(f"http://127.0.0.1:{ui.port}")
            router.put_update("sess-1", {"iteration": 1, "score": 0.5})
            router.put_update("sess-1", {"iteration": 2, "score": 0.4})
            assert storage.list_session_ids() == ["sess-1"]
            recs = storage.get_updates("sess-1")
            assert [r["iteration"] for r in recs] == [1, 2]
        finally:
            ui.stop()

    def test_remote_disabled_rejects(self):
        storage = InMemoryStatsStorage()
        ui = UIServer(port=0).attach(storage).start()  # remote NOT enabled
        try:
            router = RemoteStatsRouter(f"http://127.0.0.1:{ui.port}",
                                       max_retries=1, backoff=0.01)
            router.put_update("s", {"iteration": 1})
            assert storage.get_updates("s") == []
            assert len(router._pending) == 1  # buffered, not lost
        finally:
            ui.stop()

    def test_buffering_and_flush_after_outage(self):
        router = RemoteStatsRouter("http://127.0.0.1:1", max_retries=1,
                                   backoff=0.01, timeout=0.2)
        router.put_update("s", {"iteration": 1})
        assert len(router._pending) == 1  # dead endpoint → buffered
        storage = InMemoryStatsStorage()
        ui = UIServer(port=0, enable_remote=True).attach(storage).start()
        try:
            router.url = f"http://127.0.0.1:{ui.port}/remote"
            router.put_update("s", {"iteration": 2})
            recs = storage.get_updates("s")
            assert [r["iteration"] for r in recs] == [1, 2]
            assert router._pending == []
        finally:
            ui.stop()

    def test_statslistener_through_router_end_to_end(self):
        """Train in-process, stats appear in the 'remote' UIServer storage —
        the RemoteUIStatsStorageRouter.java:32 flow on loopback."""
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.ui import StatsListener

        storage = InMemoryStatsStorage()
        ui = UIServer(port=0, enable_remote=True).attach(storage).start()
        try:
            router = RemoteStatsRouter(f"http://127.0.0.1:{ui.port}")
            conf = (NeuralNetConfiguration.builder()
                    .layer(Dense(n_out=8, activation="relu"))
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss="mcxent"))
                    .set_input_type(InputType.feed_forward(4)).build())
            net = MultiLayerNetwork(conf)
            net.init()
            net.set_listeners(StatsListener(router, session_id="remote-run",
                                            update_frequency=1))
            rng = np.random.default_rng(0)
            from deeplearning4j_tpu.datasets import DataSet
            ds = DataSet(rng.normal(size=(16, 4)).astype(np.float32),
                         np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)])
            for _ in range(3):
                net.fit_batch(ds)
            recs = storage.get_updates("remote-run")
            assert len(recs) == 3
            assert all("score" in r for r in recs)
        finally:
            ui.stop()
