"""CJK tokenization + factory registry (VERDICT round 2, Missing #5 —
the capability behind deeplearning4j-nlp-chinese/japanese/korean):
segmentation modes, user-dictionary hook, mixed-script handling, and
Word2Vec training on an unspaced CJK corpus end-to-end."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    AggregatingSentenceIterator,
    CJKTokenizerFactory,
    CollectionSentenceIterator,
    Word2Vec,
    get_tokenizer_factory,
    register_tokenizer_factory,
)


class TestCJKTokenizer:
    def test_char_mode(self):
        tf = CJKTokenizerFactory(mode="char")
        assert tf.tokenize("我爱北京") == ["我", "爱", "北", "京"]

    def test_bigram_mode(self):
        tf = CJKTokenizerFactory(mode="bigram")
        assert tf.tokenize("我爱北京") == ["我爱", "爱北", "北京"]

    def test_single_char_run_is_unigram(self):
        tf = CJKTokenizerFactory(mode="bigram")
        assert tf.tokenize("我") == ["我"]

    def test_user_dictionary_longest_match(self):
        tf = CJKTokenizerFactory(user_dictionary=["北京", "北京大学"],
                                 mode="char")
        # longest dictionary word wins; leftovers fall back to chars
        assert tf.tokenize("我爱北京大学") == ["我", "爱", "北京大学"]

    def test_dictionary_with_bigram_fallback(self):
        tf = CJKTokenizerFactory(user_dictionary=["東京"], mode="bigram")
        toks = tf.tokenize("私は東京です")
        assert "東京" in toks
        assert all(len(t) <= 2 for t in toks)

    def test_mixed_script(self):
        tf = CJKTokenizerFactory(user_dictionary=["机器学习"], mode="char")
        toks = tf.tokenize("我用 JAX 做机器学习 v2!")
        assert "jax" in toks          # latin words lowercased/cleaned
        assert "机器学习" in toks      # dictionary hit
        assert "v2" in toks

    def test_hangul_and_kana_covered(self):
        tf = CJKTokenizerFactory(mode="char")
        assert tf.tokenize("한국") == ["한", "국"]
        assert tf.tokenize("カタカナ") != []

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            CJKTokenizerFactory(mode="word")


class TestRegistry:
    def test_builtin_names(self):
        for name in ("default", "cjk", "chinese", "japanese", "korean"):
            assert get_tokenizer_factory(name) is not None

    def test_kwargs_pass_through(self):
        tf = get_tokenizer_factory("chinese", user_dictionary=["北京"],
                                   mode="char")
        assert tf.tokenize("北京") == ["北京"]

    def test_unknown_name_lists_known(self):
        with pytest.raises(ValueError, match="default"):
            get_tokenizer_factory("klingon")

    def test_custom_registration(self):
        class Upper:
            def tokenize(self, s):
                return s.upper().split()

        register_tokenizer_factory("upper-test", Upper)
        assert get_tokenizer_factory("upper-test").tokenize("a b") == ["A", "B"]


class TestSentenceIterators:
    def test_aggregating_with_preprocessor(self):
        it = AggregatingSentenceIterator(
            CollectionSentenceIterator(["a b", "c"]),
            CollectionSentenceIterator(["d"]),
            preprocessor=str.upper)
        assert list(it) == ["A B", "C", "D"]


class TestWord2VecCJK:
    def test_word2vec_trains_on_unspaced_cjk_corpus(self):
        """End-to-end: unspaced CJK sentences → CJK tokenizer → Word2Vec;
        words from the same topic end up closer than across topics."""
        rng = np.random.default_rng(0)
        animals = ["猫咪", "狗狗", "宠物", "毛皮"]
        computers = ["电脑", "内存", "代码", "芯片"]
        sentences = []
        for _ in range(300):
            topic = animals if rng.integers(0, 2) == 0 else computers
            sentences.append("".join(rng.choice(topic, size=8)))
        w2v = Word2Vec(layer_size=32, window=3, min_word_frequency=2,
                       epochs=12, batch_size=128, learning_rate=0.05,
                       seed=1, subsampling=0,
                       tokenizer_factory=CJKTokenizerFactory(
                           user_dictionary=animals + computers, mode="char"))
        w2v.fit(sentences)
        assert w2v.has_word("猫咪") and w2v.has_word("电脑")
        within = w2v.similarity("猫咪", "狗狗")
        across = w2v.similarity("猫咪", "电脑")
        assert within > across + 0.2, f"within={within:.3f} across={across:.3f}"

    def test_string_factory_name(self):
        w2v = Word2Vec(tokenizer_factory="cjk")
        assert isinstance(w2v.tokenizer, CJKTokenizerFactory)


class TestLatticeSegmenter:
    """Round-4: dictionary-lattice (Viterbi) CJK segmentation — the
    kuromoji algorithm class (reference deeplearning4j-nlp-japanese
    vendored ViterbiBuilder)."""

    def test_lattice_beats_bigram_on_user_dictionary(self):
        """The VERDICT fixture: frequency-weighted lattice resolves the
        overlap 研究生命 → 研究|生命 where greedy longest-match (the
        bigram mode's dictionary pass) commits to 研究生|命."""
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        freqs = {"研究": 100, "研究生": 5, "生命": 80, "命": 10}
        lattice = CJKTokenizerFactory(user_dictionary=freqs, mode="lattice")
        greedy = CJKTokenizerFactory(user_dictionary=list(freqs),
                                     mode="bigram")
        text = "研究生命"
        assert lattice.tokenize(text) == ["研究", "生命"]
        assert greedy.tokenize(text) == ["研究生", "命"]  # the greedy trap

    def test_lattice_falls_back_per_char_off_dictionary(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        f = CJKTokenizerFactory(user_dictionary={"東京": 10}, mode="lattice")
        assert f.tokenize("東京都") == ["東京", "都"]
        assert f.tokenize("大阪") == ["大", "阪"]  # nothing matches

    def test_lattice_mixed_script(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        f = CJKTokenizerFactory(user_dictionary={"機械": 5, "学習": 5},
                                mode="lattice")
        assert f.tokenize("hello 機械学習 world") == \
            ["hello", "機械", "学習", "world"]

    def test_uniform_sequence_dictionary_prefers_longest(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        f = CJKTokenizerFactory(user_dictionary=["北京", "北京大学", "大学"],
                                mode="lattice")
        # one word (cost ~10) beats two words (cost ~20)
        assert f.tokenize("北京大学") == ["北京大学"]


class TestPosTagging:
    """Round-4: POS hook in the tokenizer-factory registry (reference
    deeplearning4j-nlp-uima PosUimaTokenizerFactory: tokens outside
    allowedPosTags are stripped)."""

    def test_rule_based_tagger(self):
        from deeplearning4j_tpu.nlp.tokenization import RuleBasedPosTagger
        tags = RuleBasedPosTagger().tag(
            ["the", "quick", "dog", "quickly", "jumped", "over", "3",
             "fences", "running"])
        assert tags == ["DT", "NN", "NN", "RB", "VBD", "IN", "CD", "NNS",
                        "VBG"]

    def test_pos_filter_factory_strips_disallowed(self):
        from deeplearning4j_tpu.nlp.tokenization import (
            PosFilterTokenizerFactory,
        )
        f = PosFilterTokenizerFactory(allowed_tags=["NN", "NNS", "NNP"])
        toks = f.tokenize("the fast dog jumped over the lazy cats")
        assert toks == ["fast", "dog", "lazy", "cats"]  # suffix tagger: NN*
        pairs = f.tokenize_with_tags("the dog jumped")
        assert pairs == [("the", "DT"), ("dog", "NN"), ("jumped", "VBD")]

    def test_registry_builds_pos_factory(self):
        from deeplearning4j_tpu.nlp.tokenization import get_tokenizer_factory
        f = get_tokenizer_factory("pos", allowed_tags=["NN"])
        assert f.tokenize("the dog jumped") == ["dog"]

    def test_pos_filtered_word2vec_vocabulary(self):
        """The VERDICT 'done' criterion: POS-filtered preprocessing works
        in a SequenceVectors/Word2Vec pipeline — the fitted vocabulary
        contains the nouns, not the determiners/verbs."""
        from deeplearning4j_tpu.nlp import Word2Vec
        from deeplearning4j_tpu.nlp.tokenization import (
            PosFilterTokenizerFactory,
        )
        f = PosFilterTokenizerFactory(allowed_tags=["NN", "NNS", "NNP"])
        corpus = ["the dog chased the cat over the fence"] * 30
        w2v = Word2Vec(layer_size=16, min_word_frequency=1, epochs=1,
                       window=2, tokenizer_factory=f)
        w2v.fit(corpus)
        vocab = w2v.vocab
        assert all(w in vocab for w in ("dog", "cat", "fence"))
        assert "the" not in vocab and "chased" not in vocab


class TestSentenceSegmentation:
    """Round-4: the UIMA SentenceAnnotator role (reference
    deeplearning4j-nlp-uima), dependency-free rules."""

    def test_basic_boundaries(self):
        from deeplearning4j_tpu.nlp.tokenization import SentenceSegmenter
        s = SentenceSegmenter()
        assert s.segment("Hello world. How are you? Fine!") == \
            ["Hello world.", "How are you?", "Fine!"]

    def test_abbreviations_protected(self):
        from deeplearning4j_tpu.nlp.tokenization import SentenceSegmenter
        s = SentenceSegmenter()
        got = s.segment("Dr. Smith arrived. He was late.")
        assert got == ["Dr. Smith arrived.", "He was late."]

    def test_cjk_terminators(self):
        from deeplearning4j_tpu.nlp.tokenization import SentenceSegmenter
        s = SentenceSegmenter()
        assert s.segment("这是第一句。这是第二句。") == ["这是第一句。", "这是第二句。"]

    def test_text_sentence_iterator_feeds_word2vec(self):
        from deeplearning4j_tpu.nlp.tokenization import TextSentenceIterator
        from deeplearning4j_tpu.nlp import Word2Vec
        docs = ["The dog barked. The cat slept." for _ in range(20)]
        sents = list(TextSentenceIterator(docs))
        assert len(sents) == 40
        w2v = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1)
        w2v.fit(sents)
        assert "dog" in w2v.vocab and "cat" in w2v.vocab


class TestCJKPosThroughLattice:
    """Round-5: dictionary entries carry a POS tag; the lattice emits
    (token, tag) pairs; PosFilterTokenizerFactory composes with the CJK
    factory as base AND tagger (reference kuromoji per-token POS,
    deeplearning4j-nlp-japanese)."""

    DICT = {"研究": (100, "名詞"), "生命": (80, "名詞"), "する": (200, "動詞"),
            "を": (500, "助詞"), "猫": (50, "名詞"), "犬": (50, "名詞"),
            "食べる": (40, "動詞"), "の": (600, "助詞")}

    def _factory(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        return CJKTokenizerFactory(user_dictionary=self.DICT, mode="lattice")

    def test_lattice_emits_token_tag_pairs(self):
        f = self._factory()
        got = f.tokenize_with_tags("研究を生命する")
        assert got == [("研究", "名詞"), ("を", "助詞"), ("生命", "名詞"),
                       ("する", "動詞")]

    def test_unknown_cjk_and_latin_tokens(self):
        from deeplearning4j_tpu.nlp.tokenization import (
            CasePreservingPreprocessor, CJKTokenizerFactory,
        )
        f = CJKTokenizerFactory(user_dictionary=self.DICT, mode="lattice",
                                preprocessor=CasePreservingPreprocessor())
        got = dict(f.tokenize_with_tags("猫が JAX"))
        assert got["猫"] == "名詞"
        assert got["が"] == f.UNKNOWN_TAG   # not in the dictionary
        assert got["JAX"] == "NNP"          # latin falls through to rules

    def test_plain_frequencies_still_work(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        f = CJKTokenizerFactory(user_dictionary={"研究": 100, "生命": 80},
                                mode="lattice")
        assert f.tokenize("研究生命") == ["研究", "生命"]
        assert f.tag(["研究"]) == [f.UNKNOWN_TAG]  # no POS column given

    def test_bad_entry_shape_rejected(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        with pytest.raises(ValueError, match="frequency"):
            CJKTokenizerFactory(
                user_dictionary={"研究": (1, "名詞", "研究", "extra")})

    def test_pos_filter_composes_with_cjk_factory(self):
        from deeplearning4j_tpu.nlp.tokenization import PosFilterTokenizerFactory
        cjk = self._factory()
        nouns_only = PosFilterTokenizerFactory(
            allowed_tags=["名詞"], base=cjk, tagger=cjk)
        assert nouns_only.tokenize("研究を生命する") == ["研究", "生命"]

    def test_pos_filtered_cjk_word2vec(self):
        """End-to-end: unspaced CJK corpus → lattice + POS filter → w2v
        vocabulary contains ONLY the allowed-tag (noun) tokens."""
        from deeplearning4j_tpu.nlp.tokenization import PosFilterTokenizerFactory
        rng = np.random.default_rng(0)
        nouns = ["研究", "生命", "猫", "犬"]
        fillers = ["を", "の", "する", "食べる"]
        sentences = []
        for _ in range(200):
            words = []
            for _ in range(6):
                words.append(str(rng.choice(nouns)))
                words.append(str(rng.choice(fillers)))
            sentences.append("".join(words))
        cjk = self._factory()
        w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=2,
                       epochs=2, batch_size=128, seed=1, subsampling=0,
                       tokenizer_factory=PosFilterTokenizerFactory(
                           allowed_tags=["名詞"], base=cjk, tagger=cjk))
        w2v.fit(sentences)
        assert {w.word for w in w2v.vocab.words} == set(nouns)


class TestBaseFormsThroughLattice:
    """Round-5: dictionary entries optionally carry a base form (lemma) —
    the second kuromoji per-token surface (Token.getBaseForm); conjugated
    surfaces reduce to their lemma for vectorization."""

    DICT = {"食べた": (30, "動詞", "食べる"), "食べる": (40, "動詞"),
            "猫": (50, "名詞"), "が": (500, "助詞"), "を": (500, "助詞"),
            "魚": (40, "名詞")}

    def _factory(self):
        from deeplearning4j_tpu.nlp.tokenization import CJKTokenizerFactory
        return CJKTokenizerFactory(user_dictionary=self.DICT, mode="lattice")

    def test_morphology_triples(self):
        f = self._factory()
        got = f.tokenize_with_morphology("猫が魚を食べた")
        assert got == [("猫", "名詞", "猫"), ("が", "助詞", "が"),
                       ("魚", "名詞", "魚"), ("を", "助詞", "を"),
                       ("食べた", "動詞", "食べる")]

    def test_base_form_factory_emits_lemmas(self):
        from deeplearning4j_tpu.nlp.tokenization import (
            BaseFormTokenizerFactory,
        )
        f = BaseFormTokenizerFactory(self._factory())
        assert f.tokenize("魚を食べた") == ["魚", "を", "食べる"]

    def test_base_form_factory_requires_capable_base(self):
        from deeplearning4j_tpu.nlp.tokenization import (
            BaseFormTokenizerFactory, DefaultTokenizerFactory,
        )
        with pytest.raises(ValueError, match="base_form"):
            BaseFormTokenizerFactory(DefaultTokenizerFactory())

    def test_registry_name(self):
        from deeplearning4j_tpu.nlp.tokenization import get_tokenizer_factory
        f = get_tokenizer_factory("baseform", base=self._factory())
        assert f.tokenize("食べた") == ["食べる"]

    def test_lemmatized_word2vec_merges_conjugations(self):
        """w2v trained through the base-form filter has ONE vocab entry
        for the lemma regardless of which conjugation appeared."""
        from deeplearning4j_tpu.nlp.tokenization import (
            BaseFormTokenizerFactory,
        )
        rng = np.random.default_rng(0)
        sentences = []
        for _ in range(100):
            verb = "食べた" if rng.integers(0, 2) else "食べる"
            sentences.append("猫が魚を" + verb)
        w2v = Word2Vec(layer_size=8, window=2, min_word_frequency=2,
                       epochs=1, seed=1, subsampling=0,
                       tokenizer_factory=BaseFormTokenizerFactory(
                           self._factory()))
        w2v.fit(sentences)
        assert w2v.has_word("食べる")
        assert not w2v.has_word("食べた")
