// Native data pipeline: shuffled minibatch assembly with background
// prefetch — the C++ analog of the reference's native ETL path (DataVec
// record readers + AsyncDataSetIterator's prefetch thread feeding device
// queues; reference datasets/iterator/AsyncDataSetIterator.java:30 and the
// device-affinity MagicQueue).
//
// Design: the full dataset (features+labels, float32) is registered once;
// a worker thread assembles shuffled minibatches into a small ring of
// slots ahead of the consumer.  Python (ctypes) pops slots and hands the
// buffers straight to jax.device_put — decode/shuffle/gather never touch
// the GIL.  Fisher–Yates with SplitMix64 keeps epoch shuffles reproducible
// from a seed, matching the Python iterator's semantics.
//
// Build: g++ -O3 -march=native -shared -fPIC data_loader.cpp -o libdl4jtpu_data.so -lpthread

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t seed) : state(seed) {}
  uint64_t next() {
    uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // unbiased bounded draw (Lemire)
  uint64_t bounded(uint64_t n) {
    __uint128_t m = (__uint128_t)next() * n;
    return (uint64_t)(m >> 64);
  }
};

struct Slot {
  std::vector<float> x;
  std::vector<float> y;
  int n_rows = 0;
  bool full = false;
};

struct Loader {
  const float* features = nullptr;  // [n, row_f] borrowed from numpy
  const float* labels = nullptr;    // [n, row_y] borrowed (may be null)
  int64_t n = 0, row_f = 0, row_y = 0;
  int batch = 0;
  bool drop_remainder = false;
  uint64_t seed = 0;

  std::vector<int64_t> perm;
  int64_t cursor = 0;       // next example index into perm
  int64_t epoch = 0;

  std::vector<Slot> ring;
  size_t head = 0, tail = 0;     // consumer pops head, producer fills tail
  size_t filled = 0;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::thread worker;
  std::atomic<bool> stop{false};
  std::atomic<bool> exhausted{false};

  void shuffle_epoch() {
    SplitMix64 rng(seed + 0x51ed2701ULL * (uint64_t)(epoch + 1));
    for (int64_t i = n - 1; i > 0; --i) {
      int64_t j = (int64_t)rng.bounded((uint64_t)(i + 1));
      std::swap(perm[i], perm[j]);
    }
  }

  // assemble one minibatch into slot; returns false when epoch exhausted
  bool fill(Slot& s) {
    int64_t remaining = n - cursor;
    if (remaining <= 0) return false;
    int64_t take = remaining < batch ? remaining : batch;
    if (take < batch && drop_remainder) return false;
    s.n_rows = (int)take;
    for (int64_t r = 0; r < take; ++r) {
      int64_t src = perm[cursor + r];
      std::memcpy(s.x.data() + r * row_f, features + src * row_f,
                  sizeof(float) * row_f);
      if (labels)
        std::memcpy(s.y.data() + r * row_y, labels + src * row_y,
                    sizeof(float) * row_y);
    }
    cursor += take;
    return true;
  }

  void run() {
    while (!stop.load()) {
      std::unique_lock<std::mutex> lk(mu);
      // fill happens under the lock: serializes with reset()'s cursor/perm
      // mutation; the prefetch win is vs Python/JAX work, not intra-loader
      cv_prod.wait(lk, [&] {
        return stop.load() || (filled < ring.size() && !exhausted.load());
      });
      if (stop.load()) return;
      Slot& s = ring[tail];
      if (!fill(s)) {
        exhausted.store(true);
        cv_cons.notify_all();
        continue;
      }
      s.full = true;
      tail = (tail + 1) % ring.size();
      ++filled;
      cv_cons.notify_all();
    }
  }
};

}  // namespace

extern "C" {

void* dl4j_loader_create(const float* features, const float* labels,
                         int64_t n, int64_t row_f, int64_t row_y,
                         int batch, int prefetch, uint64_t seed,
                         int drop_remainder) {
  auto* L = new Loader();
  L->features = features;
  L->labels = labels;
  L->n = n;
  L->row_f = row_f;
  L->row_y = row_y;
  L->batch = batch;
  L->seed = seed;
  L->drop_remainder = drop_remainder != 0;
  L->perm.resize(n);
  for (int64_t i = 0; i < n; ++i) L->perm[i] = i;
  L->shuffle_epoch();
  L->ring.resize(prefetch > 0 ? prefetch : 2);
  for (auto& s : L->ring) {
    s.x.resize((size_t)batch * row_f);
    s.y.resize(labels ? (size_t)batch * row_y : 0);
  }
  L->worker = std::thread([L] { L->run(); });
  return L;
}

// → rows copied into out buffers, 0 when the epoch is exhausted
int dl4j_loader_next(void* h, float* out_x, float* out_y) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv_cons.wait(lk, [&] { return L->filled > 0 || L->exhausted.load(); });
  if (L->filled == 0) return 0;  // exhausted
  Slot& s = L->ring[L->head];
  int rows = s.n_rows;
  std::memcpy(out_x, s.x.data(), sizeof(float) * (size_t)rows * L->row_f);
  if (L->labels && out_y)
    std::memcpy(out_y, s.y.data(), sizeof(float) * (size_t)rows * L->row_y);
  s.full = false;
  L->head = (L->head + 1) % L->ring.size();
  --L->filled;
  L->cv_prod.notify_all();
  return rows;
}

void dl4j_loader_reset(void* h) {
  auto* L = static_cast<Loader*>(h);
  std::unique_lock<std::mutex> lk(L->mu);
  // drop buffered slots, rewind, reshuffle with a new epoch tweak
  for (auto& s : L->ring) s.full = false;
  L->head = L->tail = 0;
  L->filled = 0;
  L->cursor = 0;
  L->epoch += 1;
  L->shuffle_epoch();
  L->exhausted.store(false);
  L->cv_prod.notify_all();
}

void dl4j_loader_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stop.store(true);
    L->cv_prod.notify_all();
    L->cv_cons.notify_all();
  }
  L->worker.join();
  delete L;
}

}  // extern "C"
