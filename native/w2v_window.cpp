// Skip-gram window/pair generation — the Word2Vec host pipeline's hot loop.
//
// Role parity: the reference walks sentences token-by-token per Hogwild
// thread (models/embeddings/learning/impl/elements/SkipGram.java:224,
// iterateSample pair emission).  The TPU inversion batches pairs for the
// device; this C++ pass produces the identical position-major pair stream
// (per-center dynamic window b ~ U{1..W}, sentence-bounded) that
// sequencevectors.py's vectorized numpy pipeline emits, at ~10x the
// throughput and GIL-free (SURVEY §2.2 "native ETL" seam, same build
// scheme as data_loader.cpp).
//
// Determinism: one splitmix64 stream seeded by the caller, consumed one
// draw per center in position order — stable across runs and block splits
// are the caller's concern (it passes a per-block seed).

#include <cstdint>
#include <cstddef>

namespace {

inline uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9e3779b97f4a7c15ull);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
  return z ^ (z >> 31);
}

}  // namespace

extern "C" {

// tokens[n], sids[n] (sentence id per token).  Emits pairs into
// centers/targets/pos (caller allocates capacity n * 2 * window).
// Returns the pair count.  pos[k] = the center's index within this block
// (drives word-granular LR on the Python side).
int64_t dl4j_sg_windows(const int32_t* tokens, const int32_t* sids,
                        int64_t n, int32_t window, uint64_t seed,
                        int32_t* centers, int32_t* targets, int64_t* pos) {
  if (window < 1) return 0;  // modulo-by-zero below would SIGFPE
  uint64_t state = seed;
  int64_t k = 0;
  for (int64_t i = 0; i < n; ++i) {
    // b ~ U{1..window} — one draw per center, position order
    const int32_t b =
        static_cast<int32_t>(splitmix64(state) % static_cast<uint64_t>(window)) + 1;
    const int32_t c = tokens[i];
    const int32_t sid = sids[i];
    const int64_t lo = i - b < 0 ? 0 : i - b;
    const int64_t hi = i + b >= n ? n - 1 : i + b;
    for (int64_t j = lo; j <= hi; ++j) {
      if (j == i || sids[j] != sid) continue;
      centers[k] = c;
      targets[k] = tokens[j];
      pos[k] = i;
      ++k;
    }
  }
  return k;
}

}  // extern "C"
