"""One metrics registry: typed counters / gauges / fixed-bucket histograms.

Every subsystem used to carry its own counter dict with its own schema
(`serving/metrics.py`, ElasticTrainer's recovery attributes, prefetch
stall stats, launcher membership stats) — none of them composable into
one "what is this process doing" answer.  :class:`MetricsRegistry` is
that answer:

- **Typed instruments.**  ``counter()`` (monotonic, float-friendly),
  ``gauge()`` (set/callback), ``histogram()`` (fixed boundaries — O(k)
  record, tiny lock hold, mergeable across processes; the same design
  the serving latency histograms already proved out).  All instruments
  take optional labels (``c.inc(1, replica=0)``) rendered as
  ``name{replica=0}`` series keys in the snapshot.
- **Collectors.**  Components that already own structured state
  (a `ServingMetrics`, the live prefetch iterators, a PodLauncher)
  register a zero-arg callable; its dict is embedded under
  ``snapshot()["collected"][name]``.  Bound methods are held via
  weakref so a dropped engine unregisters itself.
- **One snapshot schema.**  ``{"counters": {series: value}, "gauges":
  {...}, "histograms": {series: {...}}, "collected": {...}}`` — what
  ``UIServer /metrics`` serves and what :func:`merge_snapshots`
  aggregates into the launcher's pod-level view (counters sum,
  histogram buckets add, gauges keep min/mean/max across workers).

A process-global default registry (:func:`get_registry`) is the shared
surface; tests needing isolation construct their own instances.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _series(name: str, key: _LabelKey) -> str:
    if not key:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class Counter:
    """Monotonic counter (per label-set series)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._values: Dict[_LabelKey, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {_series(self.name, k): v
                    for k, v in sorted(self._values.items())} \
                or {self.name: 0}


class Gauge:
    """Point-in-time value: ``set()`` it, or ``set_fn()`` a callback read
    at snapshot time (how launcher epoch / queue depths export)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._values: Dict[_LabelKey, float] = {}
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            if self._fn is not None and not labels:
                try:
                    return float(self._fn())
                except Exception:
                    return None
            return self._values.get(_label_key(labels))

    def snapshot(self) -> Dict[str, Optional[float]]:
        with self._lock:
            vals = dict(self._values)
            fn = self._fn
        out = {_series(self.name, k): v for k, v in sorted(vals.items())}
        if fn is not None:
            try:
                out[self.name] = float(fn())
            except Exception:
                out[self.name] = None
        return out or {self.name: None}


# 0.1ms .. 10s in exponential steps — the serving default, reused
# anywhere latencies are recorded; +inf overflow bucket is implicit
DEFAULT_LATENCY_BUCKETS_MS: Sequence[float] = (
    0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
    200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0,
)


class Histogram:
    """Fixed-boundary histogram (thread-safe, one series per label-set).

    Fixed buckets, not reservoirs: O(#buckets) record, tiny lock-held
    time, and snapshots merge across engines/processes by adding
    counts — the properties a hot path and a pod aggregator both need.
    Percentiles interpolate linearly inside the winning bucket, so p99
    on ~17 buckets is approximate by design; exact needs read ``count``
    / ``sum`` or time externally.
    """

    class _Series:
        __slots__ = ("counts", "count", "total", "max_value")

        def __init__(self, n_buckets: int):
            self.counts = [0] * (n_buckets + 1)   # +1 = overflow
            self.count = 0
            self.total = 0.0
            self.max_value = 0.0

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        self.name = name
        self.bounds = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._series: Dict[_LabelKey, Histogram._Series] = {}

    def _get(self, key: _LabelKey) -> "Histogram._Series":
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = Histogram._Series(len(self.bounds))
        return s

    def record(self, value: float, **labels) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if value <= b:
                break
        else:
            i = len(self.bounds)
        key = _label_key(labels)
        with self._lock:
            s = self._get(key)
            s.counts[i] += 1
            s.count += 1
            s.total += value
            if value > s.max_value:
                s.max_value = value

    def percentile(self, p: float, **labels) -> Optional[float]:
        """Approximate p-th percentile (0 < p <= 100); None when empty.
        Overflow hits report the max seen (no boundary to interpolate
        against)."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or not s.count:
                return None
            counts = list(s.counts)
            count, mx = s.count, s.max_value
        rank = p / 100.0 * count
        seen = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if seen + c >= rank:
                if i >= len(self.bounds):
                    return mx
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * ((rank - seen) / c)
            seen += c
        return mx

    def _series_snapshot(self, s: "Histogram._Series",
                         key: _LabelKey) -> dict:
        out = {"count": s.count, "sum": round(s.total, 3),
               "max": round(s.max_value, 3),
               "mean": round(s.total / s.count, 3) if s.count else None,
               "buckets": list(self.bounds), "counts": list(s.counts)}
        return out

    def series_snapshot(self) -> Dict[str, dict]:
        """{series key: stats} — the registry-facing schema (subclasses
        may override ``snapshot()`` with a legacy shape; the registry
        always reads this one)."""
        with self._lock:
            items = list(self._series.items())
        out = {}
        for key, s in sorted(items, key=lambda kv: kv[0]):
            snap = self._series_snapshot(s, key)
            for p in (50, 90, 99):
                v = self.percentile(p, **dict(key))
                snap[f"p{p}"] = round(v, 3) if v is not None else None
            out[_series(self.name, key)] = snap
        return out

    def snapshot(self) -> Dict[str, dict]:
        return self.series_snapshot()


class MetricsRegistry:
    """Named instruments + collectors with one snapshot schema."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._collectors: Dict[str, object] = {}
        self._seq = 0

    # -- instruments (get-or-create, idempotent) ---------------------------

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                self._check_free(name, self._counters)
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._check_free(name, self._gauges)
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                self._check_free(name, self._histograms)
                h = self._histograms[name] = Histogram(name, buckets)
            elif tuple(sorted(buckets)) != h.bounds:
                raise ValueError(
                    f"histogram {name!r} already registered with buckets "
                    f"{h.bounds}")
            return h

    def register(self, instrument):
        """Adopt an already-constructed instrument (e.g. a subclassed
        histogram) under its own name; returns it."""
        if isinstance(instrument, Counter):
            d = self._counters
        elif isinstance(instrument, Gauge):
            d = self._gauges
        elif isinstance(instrument, Histogram):
            d = self._histograms
        else:
            raise TypeError(f"not an instrument: {type(instrument).__name__}")
        with self._lock:
            self._check_free(instrument.name, d)
            if instrument.name in d:
                raise ValueError(f"{instrument.name!r} already registered")
            d[instrument.name] = instrument
        return instrument

    def _check_free(self, name: str, own: dict) -> None:
        for kind, d in (("counter", self._counters),
                        ("gauge", self._gauges),
                        ("histogram", self._histograms)):
            if d is not own and name in d:
                raise ValueError(f"{name!r} already registered as a {kind}")

    # -- collectors --------------------------------------------------------

    def register_collector(self, name: str, fn: Callable[[], object],
                           unique: bool = False) -> str:
        """Embed ``fn()``'s JSON-able result under
        ``snapshot()["collected"][name]``.  Bound methods are held via
        ``weakref.WeakMethod`` — when the owner dies the collector
        disappears (no unregister bookkeeping on engine teardown).
        ``unique=True`` suffixes the name with a registry-wide sequence
        number (per-instance collectors like serving engines).  Returns
        the registered name."""
        ref: object
        try:
            ref = weakref.WeakMethod(fn)       # bound method
        except TypeError:
            ref = fn                           # plain function: strong ref
        with self._lock:
            if unique:
                name = f"{name}#{self._seq}"
                self._seq += 1
            self._collectors[name] = ref
        return name

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histos = list(self._histograms.values())
            collectors = list(self._collectors.items())
        out = {"counters": {}, "gauges": {}, "histograms": {},
               "collected": {}}
        for c in counters:
            out["counters"].update(c.snapshot())
        for g in gauges:
            out["gauges"].update(g.snapshot())
        for h in histos:
            out["histograms"].update(h.series_snapshot())
        dead = []
        for name, ref in collectors:
            fn = ref() if isinstance(ref, weakref.WeakMethod) else ref
            if fn is None:
                dead.append(name)
                continue
            try:
                out["collected"][name] = fn()
            except Exception as e:   # a broken collector must not take
                out["collected"][name] = {"error": repr(e)}  # /metrics down
        if dead:
            with self._lock:
                for name in dead:
                    self._collectors.pop(name, None)
        return out

    def reset(self) -> None:
        """Drop every instrument and collector (test isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._collectors.clear()


def merge_snapshots(snaps: List[dict]) -> dict:
    """Aggregate N ``MetricsRegistry.snapshot()`` dicts (one per worker)
    into a pod-level view: counters sum, histogram bucket counts add
    (series with matching boundaries), gauges keep min/mean/max across
    the workers that exported them.  ``collected`` blocks are kept
    per-source (they are component-shaped, not mergeable)."""
    out: dict = {"sources": len(snaps), "counters": {}, "gauges": {},
                 "histograms": {}, "collected": []}
    gauge_vals: Dict[str, List[float]] = {}
    for snap in snaps:
        for k, v in (snap.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + (v or 0)
        for k, v in (snap.get("gauges") or {}).items():
            if isinstance(v, (int, float)):
                gauge_vals.setdefault(k, []).append(float(v))
        for k, h in (snap.get("histograms") or {}).items():
            if not isinstance(h, dict):
                continue
            agg = out["histograms"].get(k)
            if agg is None:
                agg = out["histograms"][k] = {
                    "count": 0, "sum": 0.0, "max": 0.0,
                    "buckets": list(h.get("buckets", [])),
                    "counts": [0] * len(h.get("counts", []))}
            if agg["buckets"] != list(h.get("buckets", [])):
                continue   # foreign boundaries — cannot add counts
            agg["count"] += h.get("count", 0)
            agg["sum"] = round(agg["sum"] + (h.get("sum") or 0.0), 3)
            agg["max"] = max(agg["max"], h.get("max") or 0.0)
            counts = h.get("counts", [])
            if len(counts) == len(agg["counts"]):
                agg["counts"] = [a + b for a, b in zip(agg["counts"], counts)]
        if snap.get("collected"):
            out["collected"].append(snap["collected"])
    for k, vals in gauge_vals.items():
        out["gauges"][k] = {"min": min(vals), "max": max(vals),
                            "mean": round(sum(vals) / len(vals), 6),
                            "n": len(vals)}
    for h in out["histograms"].values():
        h["mean"] = round(h["sum"] / h["count"], 3) if h["count"] else None
    return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global default registry — the one ``UIServer
    /metrics`` serves and the launcher's per-worker exports snapshot."""
    return _REGISTRY
