"""Span tracing: bounded ring buffer -> Chrome trace events -> pod merge.

One :class:`TraceRecorder` per process records *complete spans* (name +
start + duration), *instant events* (faults, recoveries, canary
decisions, membership epochs), and nothing else — the two event shapes
Chrome's trace-event format needs to render a timeline.  Design
constraints, in order:

1. **Low overhead when off.**  Tracing is opt-in (``enable_tracing`` /
   CLI ``--trace``).  The module-level ``span()``/``instant()`` helpers
   the hot paths call do ONE global read when disabled and return a
   shared no-op context manager — no allocation, no lock, no clock
   read.  Instrumented code is bit-identical with tracing off; the
   ``telemetry_overhead`` bench config gates both properties.
2. **Low overhead when on.**  Recording is two monotonic clock reads
   plus one dict build plus one deque append under a lock; the ring
   buffer is bounded (oldest events evicted, eviction counted) so a
   week-long run cannot OOM the host.
3. **Mergeable across processes.**  Events are stamped on a wall-clock
   base (``time.time()`` anchor + monotonic deltas), each process gets
   its own Chrome ``pid`` track (the launcher's worker index where
   available), and :func:`merge_traces` stitches N per-worker files —
   including multiple incarnations of a relaunched worker — into one
   pod timeline that shows a ``proc_kill`` instant on one track
   followed by the relaunched incarnation's resume/recovery spans.

Export is the Chrome trace-event JSON object format
(``{"traceEvents": [...]}``): load it in chrome://tracing or
https://ui.perfetto.dev.  ``validate_chrome_trace`` is the schema check
tests and the A/B gate run against the export.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from functools import wraps
from typing import Any, Callable, Dict, Iterable, List, Optional

# the launcher's per-worker env contract (parallel/distributed.py defines
# the same literals; obs must stay import-free of jax-adjacent modules)
_ENV_PROCESS_ID = "DL4J_TPU_PROCESS_ID"
_ENV_INCARNATION = "DL4J_TPU_INCARNATION"

DEFAULT_CAPACITY = 65536


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled —
    also what ``span()`` hands back so callers can unconditionally call
    ``.set(...)``."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> "_NullSpan":
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """An open span: records a complete ("X") event when the context
    exits.  ``set(**args)`` attaches arguments discovered mid-span."""

    __slots__ = ("_rec", "name", "cat", "args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str,
                 args: Optional[dict]):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args) -> "_Span":
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)
        return self

    def __enter__(self):
        self._t0 = self._rec.clock()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.set(error=exc_type.__name__)
        self._rec.complete_at(self.name, self._t0, self._rec.clock(),
                              cat=self.cat,
                              **(self.args or {}))
        return False


class TraceRecorder:
    """Thread-safe bounded ring buffer of Chrome trace events.

    ``clock`` is the monotonic span clock (``time.monotonic`` — the same
    clock the serving engine/batcher stamp requests with, so their
    timestamps can be replayed into post-hoc spans via
    :meth:`complete_at`).  Exported timestamps ride a wall-clock anchor
    captured at construction, so traces from different processes share a
    time base and merge without negotiation.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 path: Optional[str] = None,
                 process_id: Optional[int] = None,
                 process_name: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.capacity = max(1, int(capacity))
        self.path = path
        self.clock = clock
        self._t0_mono = clock()
        # graftcheck: disable=GC201 (wall-anchor BY DESIGN: the one wall read that lets per-process monotonic timelines merge; docs/OBSERVABILITY.md)
        self._t0_wall = time.time()
        if process_id is None:
            process_id = _env_int(_ENV_PROCESS_ID, 0)
        self.process_id = int(process_id)
        inc = _env_int(_ENV_INCARNATION, 0)
        self.process_name = process_name or (
            f"worker{self.process_id}.inc{inc} (pid {os.getpid()})")
        self._events: deque = deque(maxlen=self.capacity)
        self._threads: Dict[int, str] = {}
        self._lock = threading.Lock()
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def _abs_us(self, t_mono: float) -> float:
        """Monotonic instant -> wall-clock microseconds (the merge base)."""
        return (self._t0_wall + (t_mono - self._t0_mono)) * 1e6

    def _record(self, ev: dict) -> None:
        tid = threading.get_ident()
        ev["pid"] = self.process_id
        ev["tid"] = tid
        with self._lock:
            if tid not in self._threads:
                self._threads[tid] = threading.current_thread().name
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(ev)

    def complete_at(self, name: str, t_start: float, t_end: float,
                    cat: str = "", **args) -> None:
        """Record a complete span from two instants of ``self.clock`` —
        the post-hoc path (e.g. a request's queue wait, stamped at
        submit time on another thread)."""
        ev = {"name": name, "ph": "X", "cat": cat or "span",
              "ts": round(self._abs_us(t_start), 1),
              "dur": round(max(0.0, t_end - t_start) * 1e6, 1)}
        if args:
            ev["args"] = args
        self._record(ev)

    def instant(self, name: str, cat: str = "", **args) -> None:
        """Record an instant event (fault, recovery, canary decision,
        membership epoch...)."""
        ev = {"name": name, "ph": "i", "s": "p", "cat": cat or "instant",
              "ts": round(self._abs_us(self.clock()), 1)}
        if args:
            ev["args"] = args
        self._record(ev)

    def span(self, name: str, cat: str = "", **args) -> _Span:
        return _Span(self, name, cat, args or None)

    # -- export ------------------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def export(self) -> dict:
        """The Chrome trace-event JSON object (perfetto-loadable), plus a
        ``metadata`` block the merge tool and tests read."""
        with self._lock:
            events = list(self._events)
            threads = dict(self._threads)
            dropped = self.dropped
        meta: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": self.process_id,
             "tid": 0, "args": {"name": self.process_name}},
        ]
        for tid, tname in sorted(threads.items()):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self.process_id, "tid": tid,
                         "args": {"name": tname}})
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {
                "process_id": self.process_id,
                "process_name": self.process_name,
                "os_pid": os.getpid(),
                "t0_wall": self._t0_wall,
                "events": len(events),
                "dropped": dropped,
            },
        }

    def save(self, path: Optional[str] = None) -> str:
        """Write the export atomically; returns the path written."""
        path = path or self.path
        if not path:
            raise ValueError("no trace path: pass save(path=...) or "
                             "enable_tracing(path=...)")
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.export(), f)
        os.replace(tmp, path)
        return path


# -- module-level fast path (what instrumented code calls) -----------------

_recorder: Optional[TraceRecorder] = None


def enable_tracing(path: Optional[str] = None,
                   capacity: int = DEFAULT_CAPACITY,
                   process_id: Optional[int] = None,
                   process_name: Optional[str] = None) -> TraceRecorder:
    """Install (and return) the process-global recorder.  ``path`` is
    where ``flush()`` writes the Chrome trace."""
    global _recorder
    _recorder = TraceRecorder(capacity=capacity, path=path,
                              process_id=process_id,
                              process_name=process_name)
    return _recorder


def disable_tracing() -> None:
    global _recorder
    _recorder = None


def set_recorder(rec: Optional[TraceRecorder]) -> Optional[TraceRecorder]:
    """Install a pre-built recorder (or None to disable) — lets an A/B
    harness toggle ONE accumulating recorder across interleaved arms."""
    global _recorder
    _recorder = rec
    return rec


def get_recorder() -> Optional[TraceRecorder]:
    return _recorder


def tracing_enabled() -> bool:
    return _recorder is not None


def span(name: str, cat: str = "", **args):
    """``with span("train/step", iteration=i): ...`` — a no-op when
    tracing is disabled (one global read, shared null object)."""
    r = _recorder
    if r is None:
        return _NULL_SPAN
    return r.span(name, cat, **args)


def instant(name: str, cat: str = "", **args) -> None:
    r = _recorder
    if r is not None:
        r.instant(name, cat, **args)


def complete_at(name: str, t_start: float, t_end: float,
                cat: str = "", **args) -> None:
    """Post-hoc complete span from two ``time.monotonic`` instants."""
    r = _recorder
    if r is not None:
        r.complete_at(name, t_start, t_end, cat, **args)


def traced(name: Optional[str] = None, cat: str = ""):
    """Decorator form: ``@traced("serve/warmup")``."""
    def deco(fn):
        span_name = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*a, **kw):
            r = _recorder
            if r is None:
                return fn(*a, **kw)
            with r.span(span_name, cat):
                return fn(*a, **kw)
        return wrapper
    return deco


def flush(path: Optional[str] = None) -> Optional[str]:
    """Write the global recorder's trace to ``path`` (or its configured
    path); None when tracing is disabled or no path is known.  Safe to
    call right before a chaos SIGKILL — the write is atomic."""
    r = _recorder
    if r is None:
        return None
    if path is None and r.path is None:
        return None
    try:
        return r.save(path)
    except OSError:
        return None


# -- merge + schema --------------------------------------------------------

def merge_traces(paths: Iterable[str], out_path: Optional[str] = None) -> dict:
    """Stitch N per-process trace files into ONE pod timeline.

    Events already share a wall-clock base (every recorder anchors its
    monotonic clock to ``time.time()`` at construction), so merging is
    concatenation plus pid disambiguation: two files claiming the same
    Chrome pid (a relaunched worker's incarnations, or a foreign file)
    are offset into distinct tracks, and each incarnation keeps its own
    ``process_name`` metadata row.  Returns the merged trace object;
    writes it to ``out_path`` when given.
    """
    merged: List[dict] = []
    meta: List[dict] = []
    used_pids: Dict[int, int] = {}   # requested pid -> next free remap
    sources = []
    for path in sorted(paths):
        with open(path) as f:
            obj = json.load(f)
        events = obj.get("traceEvents", [])
        pids = sorted({int(e.get("pid", 0)) for e in events})
        remap: Dict[int, int] = {}
        for pid in pids:
            new = pid
            while new in used_pids:
                new += 1000          # distinct track, stable ordering
            used_pids[new] = pid
            remap[pid] = new
        for e in events:
            e = dict(e)
            e["pid"] = remap.get(int(e.get("pid", 0)), e.get("pid", 0))
            (meta if e.get("ph") == "M" else merged).append(e)
        sources.append({"path": os.path.basename(path),
                        "pids": {str(k): v for k, v in remap.items()},
                        "metadata": obj.get("metadata", {})})
    merged.sort(key=lambda e: e.get("ts", 0.0))
    out = {"traceEvents": meta + merged, "displayTimeUnit": "ms",
           "metadata": {"merged_from": sources, "events": len(merged)}}
    if out_path:
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, out_path)
    return out


_REQUIRED_BY_PHASE = {"X": ("name", "ts", "dur", "pid", "tid"),
                      "i": ("name", "ts", "pid", "tid"),
                      "M": ("name", "pid")}


def validate_chrome_trace(obj: Any) -> List[str]:
    """Check ``obj`` against the Chrome trace-event JSON object format
    (the subset this module emits: X / i / M phases).  Returns a list of
    human-readable problems — empty means the trace is loadable."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"trace must be a JSON object, got {type(obj).__name__}"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = e.get("ph")
        if ph not in _REQUIRED_BY_PHASE:
            problems.append(f"event {i} ({e.get('name')!r}) has "
                            f"unsupported phase {ph!r}")
            continue
        for field in _REQUIRED_BY_PHASE[ph]:
            if field not in e:
                problems.append(f"event {i} ({e.get('name')!r}, ph={ph}) "
                                f"missing {field!r}")
        for num in ("ts", "dur"):
            if num in e and not isinstance(e[num], (int, float)):
                problems.append(f"event {i} {num} not numeric")
        if "dur" in e and isinstance(e["dur"], (int, float)) and e["dur"] < 0:
            problems.append(f"event {i} has negative dur")
        if "args" in e and not isinstance(e["args"], dict):
            problems.append(f"event {i} args not an object")
        if len(problems) > 20:
            problems.append("... (truncated)")
            break
    return problems


def span_tree(obj_or_events) -> List[dict]:
    """Complete-span forest by (pid, tid) timestamp containment: each
    node is ``{"name", "event", "children": [...]}`` — what the golden
    span-tree tests and the A/B gate walk."""
    if isinstance(obj_or_events, dict):
        events = obj_or_events.get("traceEvents", [])
    else:
        events = list(obj_or_events)
    spans = [e for e in events if e.get("ph") == "X"]
    by_track: Dict[tuple, List[dict]] = {}
    for e in spans:
        by_track.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    roots: List[dict] = []
    for track in sorted(by_track, key=str):
        evs = sorted(by_track[track],
                     key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: List[dict] = []
        for e in evs:
            node = {"name": e["name"], "event": e, "children": []}
            end = e["ts"] + e.get("dur", 0.0)
            while stack and e["ts"] >= (stack[-1]["event"]["ts"]
                                        + stack[-1]["event"].get("dur", 0.0)):
                stack.pop()
            if stack:
                stack[-1]["children"].append(node)
            else:
                roots.append(node)
            if end > e["ts"]:   # a child could still start inside us
                stack.append(node)
    return roots


def find_spans(tree: List[dict], name: str) -> List[dict]:
    """All nodes named ``name`` anywhere in a :func:`span_tree` forest."""
    out: List[dict] = []

    def walk(nodes):
        for n in nodes:
            if n["name"] == name:
                out.append(n)
            walk(n["children"])

    walk(tree)
    return out
