"""Unified observability: span tracing + one metrics registry.

Two small, dependency-free primitives every subsystem shares
(docs/OBSERVABILITY.md):

- :mod:`~.trace` — a low-overhead, thread-safe span recorder (bounded
  ring buffer, monotonic clocks) with a Chrome-trace-event JSON export
  (perfetto/chrome://tracing-loadable) and a merge tool that stitches
  the launcher's N per-worker trace files into one pod timeline.
- :mod:`~.metrics` — a typed MetricsRegistry (counters / gauges /
  fixed-bucket histograms, labeled) with one snapshot schema; the
  serving counters, elastic recovery counters, prefetch stall stats,
  and launcher membership stats all surface through it, so one
  ``/metrics`` response answers "what is this process doing".

The TensorFlow precedent (arxiv 1605.08695) ships step-span tracing and
a unified metrics surface as core infrastructure; the TPU-supercomputer
retrospective (arxiv 2606.15870) makes production debuggability the
gating concern at pod scale.  Tracing is OFF by default and the
disabled path is a few dict lookups — the ``telemetry_overhead`` bench
config hard-gates the enabled path at <= 3% step overhead and the
disabled path at bit-identical behavior.
"""

from .metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    merge_snapshots,
)
from .trace import (
    TraceRecorder, disable_tracing, enable_tracing, get_recorder, instant,
    merge_traces, span, span_tree, tracing_enabled, traced,
    validate_chrome_trace,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "TraceRecorder",
    "disable_tracing", "enable_tracing", "get_recorder", "get_registry",
    "instant", "merge_snapshots", "merge_traces", "span", "span_tree",
    "traced", "tracing_enabled", "validate_chrome_trace",
]
