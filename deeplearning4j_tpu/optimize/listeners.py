"""Training listeners — iteration/epoch callbacks.

Parity targets (reference optimize/listeners/): ScoreIterationListener,
PerformanceListener (samples/batches per sec — PerformanceListener.java:
19-58), CollectScoresIterationListener, TimeIterationListener,
EvaluativeListener; checkpoint saving mirrors the early-stopping savers
(earlystopping/saver/LocalFileModelSaver.java).

Contract: ``iteration_done(model, iteration, score)`` after every optimizer
step (called from MultiLayerNetwork.fit_batch / ComputationGraph.fit_batch),
``epoch_done(model, epoch)`` after each epoch.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """Base no-op listener (reference IterationListener/TrainingListener).

    ``score`` is a float-like LazyScore — reading it (format, compare,
    ``float()``) syncs the device; listeners that only log every N
    iterations therefore only sync every N iterations.

    ``requires_model_state``: set True on listeners whose callback acts on
    the model's *current* params (checkpointing, evaluation).  Fused
    multi-step paths (TBPTT scan) fall back to stepping one chunk per
    dispatch when such a listener is attached, so the callback sees each
    iteration's params rather than end-of-batch params."""

    requires_model_state = False

    def iteration_done(self, model, iteration: int, score: float) -> None:
        pass

    def epoch_done(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference ScoreIterationListener)."""

    def __init__(self, print_every: int = 10, out: Optional[Callable[[str], None]] = None):
        self.print_every = max(print_every, 1)
        self._out = out or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration, score):
        if iteration % self.print_every == 0:
            self._out(f"Score at iteration {iteration} is {score:.6f}")


class PerformanceListener(TrainingListener):
    """Throughput tracking: samples/sec + batches/sec per reporting window
    (reference PerformanceListener.java:22-58)."""

    def __init__(self, report_every: int = 10, batch_size_fn: Optional[Callable] = None,
                 out: Optional[Callable[[str], None]] = None):
        self.report_every = max(report_every, 1)
        self._out = out or (lambda s: logger.info(s))
        self._last_time: Optional[float] = None
        self._last_iter = 0
        self._batch_size = 0
        self.history: List[Tuple[float, float]] = []  # (samples/sec, batches/sec)
        self._batch_size_fn = batch_size_fn

    def set_batch_size(self, n: int) -> None:
        self._batch_size = n

    def iteration_done(self, model, iteration, score):
        now = time.perf_counter()
        if self._last_time is None:
            self._last_time, self._last_iter = now, iteration
            return
        if iteration - self._last_iter >= self.report_every:
            elapsed = now - self._last_time
            batches = iteration - self._last_iter
            bps = batches / elapsed
            sps = bps * (self._batch_size or 0)
            self.history.append((sps, bps))
            self._out(f"iteration {iteration}: {bps:.1f} batches/sec"
                      + (f", {sps:.1f} samples/sec" if self._batch_size else ""))
            self._last_time, self._last_iter = now, iteration


class CollectScoresIterationListener(TrainingListener):
    """Accumulate (iteration, score) pairs (reference CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(frequency, 1)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, score))


class TimeIterationListener(TrainingListener):
    """ETA logging from measured iteration rate (reference TimeIterationListener)."""

    def __init__(self, total_iterations: int, out: Optional[Callable[[str], None]] = None):
        self.total = total_iterations
        self._start: Optional[float] = None
        self._out = out or (lambda s: logger.info(s))

    def iteration_done(self, model, iteration, score):
        if self._start is None:
            self._start = time.perf_counter()
            return
        elapsed = time.perf_counter() - self._start
        rate = iteration / max(elapsed, 1e-9)
        remaining = max(self.total - iteration, 0) / max(rate, 1e-9)
        self._out(f"iteration {iteration}/{self.total}, ETA {remaining:.0f}s")


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (reference EvaluativeListener)."""

    requires_model_state = True

    def __init__(self, data, frequency: int = 100, evaluation_factory=None,
                 out: Optional[Callable[[str], None]] = None):
        self.data = data
        self.frequency = max(frequency, 1)
        self._factory = evaluation_factory
        self._out = out or (lambda s: logger.info(s))
        self.evaluations: List[Tuple[int, object]] = []

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency != 0:
            return
        ev = model.evaluate(self.data, self._factory() if self._factory else None)
        self.evaluations.append((iteration, ev))
        self._out(f"evaluation at iteration {iteration}: accuracy={ev.accuracy():.4f}")


class CheckpointListener(TrainingListener):
    """Periodic checkpointing to a directory, keeping the last N
    (reference CheckpointListener semantics; format = utils.serializer zip)."""

    requires_model_state = True

    def __init__(self, directory: str, save_every_iterations: Optional[int] = None,
                 save_every_epochs: Optional[int] = None, keep_last: int = 3):
        self.dir = directory
        self.every_iter = save_every_iterations
        self.every_epoch = save_every_epochs
        self.keep_last = keep_last
        self.saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str) -> None:
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def iteration_done(self, model, iteration, score):
        if self.every_iter and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def epoch_done(self, model, epoch):
        if self.every_epoch and epoch % self.every_epoch == 0:
            self._save(model, f"epoch_{epoch}")


class ComposableListener(TrainingListener):
    """Fan-out to several listeners (reference ComposableIterationListener)."""

    def __init__(self, *listeners: TrainingListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration, score):
        for l in self.listeners:
            l.iteration_done(model, iteration, score)

    def epoch_done(self, model, epoch):
        for l in self.listeners:
            l.epoch_done(model, epoch)
