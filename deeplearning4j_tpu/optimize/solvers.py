"""Second-order / line-search solvers.

Parity targets: reference optimize/solvers/BackTrackLineSearch.java
(Armijo backtracking with the Bertsekas conditions), LBFGS.java (two-loop
recursion, m=4 history default), ConjugateGradient.java (Polak-Ribière),
LineGradientDescent.java — the alternatives to the default
StochasticGradientDescent the reference selects by OptimizationAlgorithm.

TPU formulation: parameters are raveled to one flat vector
(jax.flatten_util), the loss/gradient closure is jit-compiled ONCE, and
the solver's control flow (history, line search) runs on host — direction
algebra is O(params) vector math that XLA executes on device; only
step-size decisions bounce back, exactly the part that must be dynamic.

Use standalone via ``minimize``, or on a model via ``fit_solver`` (the
reference's Solver.optimize() entry).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

Array = jax.Array


@dataclasses.dataclass
class SolverResult:
    params: object            # same pytree structure as the input
    loss: float
    losses: List[float]
    iterations: int
    converged: bool


def backtrack_line_search(f: Callable[[Array], Array], x: Array, fx: float,
                          g: Array, direction: Array,
                          initial_step: float = 1.0,
                          c1: float = 1e-4, rho: float = 0.5,
                          max_steps: int = 20) -> Tuple[float, float]:
    """Armijo backtracking (reference BackTrackLineSearch.optimize): shrink
    ``step`` until f(x + step·d) ≤ f(x) + c1·step·gᵀd.  Returns
    (step, f_new); step=0.0 when no decrease was found."""
    gd = float(g @ direction)
    if gd >= 0:  # not a descent direction — caller should reset
        return 0.0, fx
    step = initial_step
    for i in range(max_steps):
        f_new = float(f(x + step * direction))
        if np.isfinite(f_new) and f_new <= fx + c1 * step * gd:
            if i == 0:
                # the initial step already satisfies Armijo — expand while
                # the objective keeps dropping (reference BackTrackLineSearch
                # stpmax forward phase), so a badly scaled direction can't
                # trap the solver in micro-steps
                for _ in range(10):
                    f_try = float(f(x + 2.0 * step * direction))
                    if np.isfinite(f_try) and f_try < f_new:
                        step *= 2.0
                        f_new = f_try
                    else:
                        break
            return step, f_new
        step *= rho
    return 0.0, fx


def minimize(loss_fn: Callable, params, method: str = "lbfgs",
             max_iterations: int = 100, tol: float = 1e-6,
             history: int = 4) -> SolverResult:
    """Full-batch minimization of ``loss_fn(params)`` (a scalar-returning
    function of a pytree).  method ∈ {"lbfgs", "cg", "line_gd"}.

    ``history`` is the L-BFGS memory (reference LBFGS.java m=4)."""
    if method not in ("lbfgs", "cg", "line_gd"):
        raise ValueError(f"unknown method '{method}' — use lbfgs | cg | line_gd")
    x0, unravel = ravel_pytree(params)
    x0 = x0.astype(jnp.float32)

    vg = jax.jit(jax.value_and_grad(lambda flat: loss_fn(unravel(flat))))
    f_only = jax.jit(lambda flat: loss_fn(unravel(flat)))

    x = x0
    fx, g = vg(x)
    fx = float(fx)
    losses = [fx]
    converged = False

    # L-BFGS history
    s_hist: List[Array] = []
    y_hist: List[Array] = []
    prev_g: Optional[Array] = None
    prev_d: Optional[Array] = None

    it = 0
    for it in range(1, max_iterations + 1):
        if method == "line_gd":
            d = -g
        elif method == "cg":
            if prev_g is None:
                d = -g
            else:
                # Polak-Ribière with automatic reset (reference
                # ConjugateGradient.java beta max(0, ...))
                beta = float(jnp.dot(g, g - prev_g) / jnp.maximum(
                    jnp.dot(prev_g, prev_g), 1e-20))
                beta = max(0.0, beta)
                d = -g + beta * prev_d
        else:  # lbfgs two-loop recursion (LBFGS.java)
            q = g
            alphas = []
            for s, y in zip(reversed(s_hist), reversed(y_hist)):
                rho_i = 1.0 / float(jnp.dot(y, s))
                a = rho_i * float(jnp.dot(s, q))
                alphas.append((a, rho_i, s, y))
                q = q - a * y
            if y_hist:
                s_l, y_l = s_hist[-1], y_hist[-1]
                gamma = float(jnp.dot(s_l, y_l) / jnp.maximum(jnp.dot(y_l, y_l), 1e-20))
                q = q * gamma
            for a, rho_i, s, y in reversed(alphas):
                b = rho_i * float(jnp.dot(y, q))
                q = q + (a - b) * s
            d = -q

        step, f_new = backtrack_line_search(f_only, x, fx, g, d)
        if step == 0.0:
            # line search failed: reset to steepest descent once, else stop
            if method != "line_gd" and (prev_g is not None or s_hist):
                s_hist, y_hist, prev_g, prev_d = [], [], None, None
                step, f_new = backtrack_line_search(f_only, x, fx, g, -g)
                d = -g
            if step == 0.0:
                break
        x_new = x + step * d
        _, g_new = vg(x_new)
        if method == "lbfgs":
            s_vec = x_new - x
            y_vec = g_new - g
            if float(jnp.dot(s_vec, y_vec)) > 1e-10:  # curvature condition
                s_hist.append(s_vec)
                y_hist.append(y_vec)
                if len(s_hist) > history:
                    s_hist.pop(0)
                    y_hist.pop(0)
        prev_g, prev_d = g, d
        rel = abs(fx - f_new) / max(abs(fx), 1e-12)
        x, fx, g = x_new, f_new, g_new
        losses.append(fx)
        if rel < tol:
            converged = True
            break

    return SolverResult(unravel(x), fx, losses, it, converged)


def fit_solver(net, ds, method: str = "lbfgs", max_iterations: int = 100,
               tol: float = 1e-6) -> SolverResult:
    """Full-batch solver training for a MultiLayerNetwork (reference
    Solver.optimize with OptimizationAlgorithm.LBFGS / CONJUGATE_GRADIENT /
    LINE_GRADIENT_DESCENT).  Updates ``net.params`` in place."""
    x = jnp.asarray(ds.features)
    y = None if ds.labels is None else jax.tree_util.tree_map(jnp.asarray, ds.labels)
    m = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

    def loss_fn(params):
        loss, _ = net._loss(params, net.state, x, y, train=False, rng=None,
                            mask=m, label_mask=lm)
        return loss

    result = minimize(loss_fn, net.params, method=method,
                      max_iterations=max_iterations, tol=tol)
    net.params = result.params
    return result
