from .listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    TimeIterationListener,
    EvaluativeListener,
    CheckpointListener,
    ComposableListener,
)
from .score import LazyScore
from .solvers import SolverResult, backtrack_line_search, fit_solver, minimize
