from .listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    CollectScoresIterationListener,
    TimeIterationListener,
    EvaluativeListener,
    CheckpointListener,
    ComposableListener,
)
