"""LazyScore — a device-resident loss scalar with float semantics.

Why this exists: the reference's ``MultiLayerNetwork.fit`` returns ``score``
as a Java double, which on GPU forces a device→host readback every iteration
(reference nn/multilayer/MultiLayerNetwork.java:1165 → ``score()``).  On TPU
— especially a remote (axon-tunnelled) TPU where a round trip costs ~100ms,
2× the step's actual compute — a per-step readback serializes dispatch and
caps training throughput far below what the chip can do.

So ``fit_batch`` returns the loss as a *future*: a 0-d ``jax.Array`` still
on device, wrapped so it behaves like a ``float`` the moment anyone actually
reads it (printing, comparing, ``round``-ing, numpy-converting).  A training
loop that just chains ``fit_batch`` calls never blocks; XLA keeps the device
busy while Python races ahead enqueueing the next steps.  The first numeric
use materializes (and caches) the host value.

This is the TPU-native analog of the reference's async gradient machinery
(``EncodedGradientsAccumulator``): don't make the host a per-step barrier.
"""

from __future__ import annotations

from typing import Optional


def materialize_scores(scores) -> None:
    """Batch-materialize every un-read LazyScore in ``scores`` with ONE
    device transfer (``jax.device_get`` of all pending 0-d buffers), then
    cache the floats.  Per-score ``float()`` would pay one host round trip
    each — on a remote TPU that's ~100ms × steps; this is one."""
    import jax

    from ..obs import trace as obs_trace
    lazy = [s for s in scores
            if isinstance(s, LazyScore) and not s.materialized]
    if not lazy:
        return
    # the batched device barrier (one transfer for the whole epoch) —
    # the other place step device time surfaces on the host timeline
    with obs_trace.span("train/device_sync", cat="train", n_scores=len(lazy)):
        vals = jax.device_get([s._dev for s in lazy])
    for s, v in zip(lazy, vals):
        s._val = float(v)
        s._dev = None


class LazyScore:
    """Float-like view of a device scalar; blocks only on first read.

    ``float(score)``, ``f"{score:.4f}"``, comparisons, arithmetic, ``round``
    and ``np.asarray`` all materialize the value (cached after the first
    read).  ``score.device_value()`` hands back the un-materialized
    ``jax.Array`` for callers that want to keep computation on device
    (e.g. accumulating an epoch-mean loss without syncing).
    """

    __slots__ = ("_dev", "_val")

    def __init__(self, device_scalar, value: Optional[float] = None):
        self._dev = device_scalar
        self._val = value

    # -- materialization ---------------------------------------------------

    def value(self) -> float:
        if self._val is None:
            from ..obs import trace as obs_trace
            # the host<->device barrier of the step — the only blocking
            # read in a chained fit_batch loop (docs/OBSERVABILITY.md)
            with obs_trace.span("train/device_sync", cat="train"):
                self._val = float(self._dev)
            self._dev = None  # drop the device buffer once read
        return self._val

    def device_value(self):
        """The underlying 0-d jax.Array (or the cached float if already
        materialized) — for device-side accumulation without a sync."""
        return self._dev if self._dev is not None else self._val

    @property
    def materialized(self) -> bool:
        return self._val is not None

    # -- float protocol ----------------------------------------------------

    def __float__(self) -> float:
        return self.value()

    def __int__(self) -> int:
        return int(self.value())

    def __bool__(self) -> bool:
        return bool(self.value())

    def __round__(self, ndigits=None):
        return round(self.value(), ndigits)

    def __format__(self, spec: str) -> str:
        return format(self.value(), spec)

    def __repr__(self) -> str:
        return repr(self.value())

    def __str__(self) -> str:
        return str(self.value())

    def __hash__(self) -> int:
        return hash(self.value())

    def __array__(self, dtype=None, copy=None):
        import numpy as np
        return np.asarray(self.value(), dtype=dtype)

    # -- comparisons -------------------------------------------------------

    @staticmethod
    def _coerce(other):
        return other.value() if isinstance(other, LazyScore) else other

    def __eq__(self, other):
        return self.value() == self._coerce(other)

    def __ne__(self, other):
        return self.value() != self._coerce(other)

    def __lt__(self, other):
        return self.value() < self._coerce(other)

    def __le__(self, other):
        return self.value() <= self._coerce(other)

    def __gt__(self, other):
        return self.value() > self._coerce(other)

    def __ge__(self, other):
        return self.value() >= self._coerce(other)

    # -- arithmetic (materializes; use device_value() to stay on device) ---

    def __add__(self, other):
        return self.value() + self._coerce(other)

    def __radd__(self, other):
        return self._coerce(other) + self.value()

    def __sub__(self, other):
        return self.value() - self._coerce(other)

    def __rsub__(self, other):
        return self._coerce(other) - self.value()

    def __mul__(self, other):
        return self.value() * self._coerce(other)

    def __rmul__(self, other):
        return self._coerce(other) * self.value()

    def __truediv__(self, other):
        return self.value() / self._coerce(other)

    def __rtruediv__(self, other):
        return self._coerce(other) / self.value()

    def __neg__(self):
        return -self.value()

    def __abs__(self):
        return abs(self.value())
