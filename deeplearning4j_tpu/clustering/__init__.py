"""Clustering + nearest neighbors (replaces
deeplearning4j-nearestneighbors-parent, SURVEY.md §2.4).

TPU inversion: the reference's pointer-chasing spatial trees (VPTree,
KDTree, SPTree) are replaced by brute-force tiled distance matmuls — the
‖a−b‖² = ‖a‖² + ‖b‖² − 2a·b expansion turns neighbor search into one MXU
matmul + top-k, which beats tree traversal on TPU for any dataset that fits
in HBM (the reference itself falls back to brute force on GPU for the same
reason).
"""

from .kmeans import KMeansClustering
from .knn import NearestNeighbors, pairwise_distances
from .knn_server import NearestNeighborsClient, NearestNeighborsServer

__all__ = ["KMeansClustering", "NearestNeighbors", "pairwise_distances",
           "NearestNeighborsClient", "NearestNeighborsServer"]
