"""Brute-force nearest neighbors on the MXU.

Parity target: reference nearestneighbor-core VPTree.java (vantage-point
tree search) + NearestNeighbor.java server ops.  The tree is replaced by
tiled distance matmuls + jax.lax.top_k — O(N·Q·D) FLOPs that the MXU eats,
with query tiling to bound HBM (SURVEY's "brute-force-on-TPU" note).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@partial(jax.jit, static_argnums=(2,))
def _dist_block(queries: Array, points: Array, metric: str = "euclidean") -> Array:
    """[Q,D] × [N,D] → [Q,N] distances via the matmul expansion."""
    if metric == "cosine":
        qn = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        pn = points / jnp.maximum(jnp.linalg.norm(points, axis=1, keepdims=True), 1e-12)
        return 1.0 - qn @ pn.T
    if metric == "manhattan":
        return jnp.sum(jnp.abs(queries[:, None, :] - points[None, :, :]), axis=-1)
    # euclidean²: ‖q‖² + ‖p‖² − 2q·p  (one MXU matmul)
    q2 = jnp.sum(queries * queries, axis=1, keepdims=True)   # [Q,1]
    p2 = jnp.sum(points * points, axis=1)                    # [N]
    d2 = q2 + p2[None, :] - 2.0 * (queries @ points.T)
    return jnp.maximum(d2, 0.0)


def pairwise_distances(a, b=None, metric: str = "euclidean") -> np.ndarray:
    """All-pairs distance matrix (euclidean returns TRUE distances)."""
    a = jnp.asarray(a, jnp.float32)
    b = a if b is None else jnp.asarray(b, jnp.float32)
    d = _dist_block(a, b, metric)
    if metric == "euclidean":
        d = jnp.sqrt(d)
    return np.asarray(d)


@partial(jax.jit, static_argnums=(2, 3))
def _topk_block(queries: Array, points: Array, k: int, metric: str) -> Tuple[Array, Array]:
    d = _dist_block(queries, points, metric)
    neg_d, idx = jax.lax.top_k(-d, k)
    return -neg_d, idx


class NearestNeighbors:
    """KNN index (reference VPTree surface: knn(point, k) → ids+distances).

    ``query_block`` tiles large query sets so the [Q,N] distance block
    stays within HBM.
    """

    def __init__(self, points, metric: str = "euclidean",
                 query_block: int = 4096):
        self.points = jnp.asarray(np.asarray(points, np.float32))
        if self.points.ndim != 2:
            raise ValueError(f"points must be [N,D], got {self.points.shape}")
        self.metric = metric
        self.query_block = query_block

    def knn(self, queries, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """→ (distances [Q,k], indices [Q,k]), nearest first.  Euclidean
        distances are true (sqrt'd) distances."""
        q = np.asarray(queries, np.float32)
        squeeze = q.ndim == 1
        if squeeze:
            q = q[None, :]
        k = min(k, self.points.shape[0])
        outs_d, outs_i = [], []
        for s in range(0, q.shape[0], self.query_block):
            d, i = _topk_block(jnp.asarray(q[s:s + self.query_block]),
                               self.points, k, self.metric)
            outs_d.append(np.asarray(d))
            outs_i.append(np.asarray(i))
        d = np.concatenate(outs_d)
        i = np.concatenate(outs_i)
        if self.metric == "euclidean":
            d = np.sqrt(d)
        return (d[0], i[0]) if squeeze else (d, i)
