"""K-nearest-neighbors REST server + client.

Parity target: reference
deeplearning4j-nearestneighbors-parent/deeplearning4j-nearestneighbor-server/
.../NearestNeighborsServer.java:42 (Play REST server over a VPTree index:
POST /knn — neighbors of an already-indexed point by id; POST /knnnew —
neighbors of a posted vector) and the sibling client module
(NearestNeighborsClient).

TPU inversion: the index is the MXU brute-force ``NearestNeighbors``
(clustering/knn.py) instead of a VPTree — one [Q,N] distance matmul block
beats pointer-chasing on this hardware — served by the same stdlib
``ThreadingHTTPServer`` pattern as ui/server.py.  Wire format is JSON
(ids + distances), matching the reference's NearestNeighborsResults shape.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import numpy as np

from .knn import NearestNeighbors


class NearestNeighborsServer:
    """``NearestNeighborsServer(points).start()`` → POST /knn, /knnnew.

    /knn     {"id": int, "k": int}        → neighbors of indexed point
    /knnnew  {"vector": [...], "k": int}  → neighbors of a new vector
    Responses: {"results": [{"index": i, "distance": d}, ...]}
    """

    def __init__(self, points, metric: str = "euclidean",
                 host: str = "127.0.0.1", port: int = 0):
        self.index = NearestNeighbors(points, metric=metric)
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NearestNeighborsServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    k = int(req.get("k", 5))
                    if self.path == "/knn":
                        idx = int(req["id"])
                        pts = np.asarray(server.index.points)
                        if not (0 <= idx < len(pts)):
                            return self._reply(400, {"error": f"id {idx} out of "
                                                     f"range [0,{len(pts)})"})
                        # k+1 then drop the query point itself (reference
                        # /knn semantics: neighbors of an indexed point)
                        d, i = server.index.knn(pts[idx][None, :], k + 1)
                        pairs = [(int(ii), float(dd))
                                 for dd, ii in zip(d[0], i[0]) if ii != idx][:k]
                    elif self.path == "/knnnew":
                        vec = np.asarray(req["vector"], np.float32)
                        d, i = server.index.knn(vec[None, :], k)
                        pairs = [(int(ii), float(dd)) for dd, ii in zip(d[0], i[0])]
                    else:
                        return self._reply(404, {"error": f"no route {self.path}"})
                    self._reply(200, {"results": [
                        {"index": ii, "distance": dd} for ii, dd in pairs]})
                except (KeyError, ValueError, TypeError) as e:
                    self._reply(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


class NearestNeighborsClient:
    """HTTP client for NearestNeighborsServer (reference
    deeplearning4j-nearestneighbors-client's NearestNeighborsClient)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _post(self, route: str, payload: dict) -> dict:
        req = urllib.request.Request(
            self.url + route, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def knn(self, index: int, k: int) -> List[dict]:
        """Neighbors of an indexed point: [{"index", "distance"}, ...]."""
        return self._post("/knn", {"id": index, "k": k})["results"]

    def knn_new(self, vector, k: int) -> List[dict]:
        """Neighbors of a new vector."""
        return self._post("/knnnew", {"vector": np.asarray(vector).tolist(),
                                      "k": k})["results"]
