"""K-Means (Lloyd) as jit-compiled distance matmuls + segment sums.

Parity target: reference clustering/kmeans/KMeansClustering.java +
algorithm/BaseClusteringAlgorithm.java (iterationCount /
distanceConvergence strategies, varianceDistance option).

TPU inversion: each Lloyd iteration is ONE XLA program — assignment via
the ‖x−c‖² matmul expansion, centroid update via one-hot matmul (a dense
[N,K]ᵀ[N,D] product the MXU handles) — instead of the reference's
per-point loops over cluster objects.  k-means++ seeding matches the
reference's ClusterUtils.initClusters probabilistic spread.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _assign(x: Array, centroids: Array) -> Tuple[Array, Array]:
    """Distance block + argmin: (assignments [N], d2 [N,K]).  Shares the
    matmul-expansion kernel with knn._dist_block."""
    from .knn import _dist_block

    d2 = _dist_block(x, centroids, "euclidean")           # [N,K], clamped ≥ 0
    return jnp.argmin(d2, axis=1), d2


@jax.jit
def _assign_inertia(x: Array, centroids: Array) -> Tuple[Array, Array]:
    assign, d2 = _assign(x, centroids)
    return assign, jnp.sum(jnp.min(d2, axis=1))


@jax.jit
def _lloyd_step(x: Array, centroids: Array) -> Tuple[Array, Array, Array]:
    """One Lloyd iteration: assign + recompute.  x [N,D], centroids [K,D].
    Returns (new_centroids, assignments, inertia) — assignments/inertia are
    relative to the INPUT centroids (the caller re-assigns at the end)."""
    assign, d2 = _assign(x, centroids)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    onehot = jax.nn.one_hot(assign, centroids.shape[0], dtype=x.dtype)  # [N,K]
    sums = onehot.T @ x                                   # [K,D] — MXU matmul
    counts = jnp.sum(onehot, axis=0)                      # [K]
    new_c = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0),
                      centroids)                          # empty cluster keeps old
    return new_c, assign, inertia


class KMeansClustering:
    """setup(k, max_iterations | convergence) + apply_to(points)
    (reference KMeansClustering.setup variants)."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 init: str = "kmeans++", seed: int = 12345):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.init = init
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None
        self.inertia_: Optional[float] = None
        self.n_iter_: int = 0

    @staticmethod
    def setup(k: int, max_iterations: int = 100, **kw) -> "KMeansClustering":
        return KMeansClustering(k, max_iterations, **kw)

    def _init_centroids(self, x: np.ndarray, rng) -> np.ndarray:
        if self.init == "random":
            idx = rng.choice(x.shape[0], self.k, replace=False)
            return x[idx].copy()
        # k-means++ (Arthur & Vassilvitskii 2007)
        centroids = [x[rng.integers(0, x.shape[0])]]
        d2 = np.full(x.shape[0], np.inf)
        for _ in range(1, self.k):
            last = centroids[-1]
            d2 = np.minimum(d2, np.sum((x - last) ** 2, axis=1))
            p = d2 / d2.sum() if d2.sum() > 0 else None
            centroids.append(x[rng.choice(x.shape[0], p=p)])
        return np.stack(centroids)

    def apply_to(self, points) -> np.ndarray:
        """Cluster; returns assignments [N]."""
        x = np.asarray(points, np.float32)
        if x.ndim != 2 or x.shape[0] < self.k:
            raise ValueError(f"need [N>=k,D] points, got {x.shape} with k={self.k}")
        rng = np.random.default_rng(self.seed)
        c = jnp.asarray(self._init_centroids(x, rng))
        xj = jnp.asarray(x)
        prev_inertia = np.inf
        for it in range(self.max_iterations):
            c, _, inertia = _lloyd_step(xj, c)
            inertia = float(inertia)
            self.n_iter_ = it + 1
            if np.isfinite(prev_inertia) and \
                    prev_inertia - inertia <= self.tol * max(abs(prev_inertia), 1.0):
                break
            prev_inertia = inertia
        # final assignment/inertia against the FINAL centroids, so
        # fit_predict(x) == predict(x) and inertia_ matches self.centroids
        assign, inertia = _assign_inertia(xj, c)
        self.centroids = np.asarray(c)
        self.inertia_ = float(inertia)
        return np.asarray(assign)

    fit_predict = apply_to

    def predict(self, points) -> np.ndarray:
        if self.centroids is None:
            raise ValueError("apply_to before predict")
        x = jnp.asarray(np.asarray(points, np.float32))
        return np.asarray(_assign_inertia(x, jnp.asarray(self.centroids))[0])
