from .serializer import save_model, load_model
from .gradient_check import check_gradients
