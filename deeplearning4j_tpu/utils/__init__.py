from .serializer import save_model, load_model
from .gradient_check import check_gradients


def device_iteration(net, advance: int):
    """Device-resident iteration counter shared by MultiLayerNetwork and
    ComputationGraph: a fresh host-scalar upload per step costs ~10ms of
    serialized latency on a tunnelled TPU, so the counter lives on device
    and advances with an (async) eager add.  Falls back to an upload
    whenever python-side ``net.iteration`` was changed externally
    (checkpoint restore, manual reset)."""
    import jax.numpy as jnp
    if net._it_dev is None or net._it_dev_val != net.iteration:
        net._it_dev = jnp.asarray(net.iteration, jnp.int32)
    it = net._it_dev
    net._it_dev = it + advance
    net._it_dev_val = net.iteration + advance
    return it
