"""Shared build-on-first-use loader for the C++ native pieces
(native/*.cpp — the SURVEY §2.2 native seam).

One hardened implementation for every binding module: mtime-based
rebuild, atomic temp+rename (concurrent builders never expose a
half-linked .so), and warn-and-fallback on ANY failure including a
corrupt cached library (dlopen errors), so callers degrade to their
pure-Python paths instead of crashing mid-training.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional, Sequence

logger = logging.getLogger("deeplearning4j_tpu")


def build_and_load(src: str, so_name: str,
                   extra_flags: Sequence[str] = ()) -> Optional[ctypes.CDLL]:
    """Compile ``src`` (if stale) into native/build/``so_name`` and dlopen
    it; None on any failure (callers fall back to Python)."""
    build = os.path.join(os.path.dirname(src), "build")
    os.makedirs(build, exist_ok=True)
    so = os.path.join(build, so_name)
    try:
        if not os.path.exists(so) \
                or os.path.getmtime(so) < os.path.getmtime(src):
            tmp = f"{so}.{os.getpid()}.tmp"
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src,
                 "-o", tmp, *extra_flags],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        return ctypes.CDLL(so)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired, OSError) as e:
        logger.warning("native library %s unavailable (%s); using Python "
                       "fallback", so_name, e)
        return None
