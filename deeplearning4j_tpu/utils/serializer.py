"""Model serialization — the checkpoint format.

Parity target: reference util/ModelSerializer.java:37 — a single zip
containing config JSON + flat params + updater state (``writeModel():52``,
``restoreMultiLayerNetwork():137-296``, ``saveUpdater`` flag).  Here the zip
holds:

    configuration.json   — MultiLayerConfiguration.to_dict() JSON
    meta.json            — {format_version, iteration, epoch, model_class}
    params.npz           — entries "<layer_idx>/<param_name>"
    state.npz            — non-trainable state (BN running stats, centers)
    updater.npz          — optimizer state, "<layer_idx>/<slot>/<param_name>"

Unlike the reference's single flat coefficient buffer, params stay named —
robust to layout changes and directly shardable on restore.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Dict, List

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

# 1: original format (f32/f64 leaves only).
# 2: bf16 leaves are stored as their raw bits viewed uint16, under the
#    entry name "<path>#bfloat16" (np.savez cannot round-trip bf16) —
#    version-1 readers would surface them as missing keys, so the format
#    version records the suffix scheme.  Loading v1 zips stays supported.
# 3: optional "grad_residual.npz" — the error-feedback residual of the
#    compressed DCN gradient exchange (parallel/trainer.py
#    grad_compression=; params-tree structure, each leaf carries a leading
#    dcn-slice axis).  Dropping it would silently lose in-flight
#    compression error on restore, so writers bump the version; v1/v2
#    readers reject v3 zips instead of resuming with a truncated state.
#    Loading v1/v2 zips stays supported (no residual → trainers re-init
#    zeros).
# 4: meta.json carries "integrity": {entry_name: sha256 hex} over every
#    other zip entry's raw bytes.  Zip's own per-entry CRC32 only protects
#    the deflate stream — a bit flip in the central directory, a torn
#    write, or an entry swapped between checkpoints can still hand the
#    loader plausible-looking garbage.  The digest is verified on load
#    (CheckpointIntegrityError on mismatch) so restore can fall back to an
#    older intact checkpoint instead of resuming from corrupt state
#    (parallel/elastic.py CheckpointManager.restore_latest).  v1-v3 zips
#    (no "integrity" key) still load, unverified.
FORMAT_VERSION = 4
SUPPORTED_VERSIONS = (1, 2, 3, 4)


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint entry's bytes do not match the digest recorded in
    meta.json — the file was truncated, bit-flipped, or otherwise
    corrupted after it was written.  RuntimeError (not ValueError) so the
    elastic FailureDetector classifies it as a recoverable storage
    failure, not a programming error."""


def _flatten_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_tree(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_tree(v, f"{prefix}{i}/"))
    elif tree is None:
        pass
    else:
        a = np.asarray(tree)
        if a.dtype == ml_dtypes.bfloat16:
            # np.savez round-trips bf16 as an opaque void dtype — store
            # the raw bits as uint16 with the dtype in the entry name
            # (Adam moment_dtype state, reduced-precision checkpoints)
            out[prefix[:-1] + "#bfloat16"] = a.view(np.uint16)
        else:
            out[prefix[:-1]] = a
    return out


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _load_npz(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data)) as z:
        return {k: z[k] for k in z.files}


def _unflatten_into(template, flat: Dict[str, np.ndarray], prefix=""):
    """Rebuild a pytree with the template's structure from name→array."""
    if isinstance(template, dict):
        return {k: _unflatten_into(v, flat, f"{prefix}{k}/") for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        seq = [_unflatten_into(v, flat, f"{prefix}{i}/") for i, v in enumerate(template)]
        return type(template)(seq) if isinstance(template, tuple) else seq
    if template is None:
        return None
    key = prefix[:-1]
    if key in flat:
        return jnp.asarray(flat[key])
    if key + "#bfloat16" in flat:
        return jnp.asarray(flat[key + "#bfloat16"].view(ml_dtypes.bfloat16))
    raise KeyError(
        f"checkpoint missing parameter '{key}' (format v{FORMAT_VERSION} "
        f"stores bf16 leaves uint16-viewed under '<name>#bfloat16' — a "
        f"checkpoint written by a newer format or a mismatched config?)")


def _digest(data: bytes) -> str:
    import hashlib

    return hashlib.sha256(data).hexdigest()


def save_model(net, path: str, save_updater: bool = True,
               compression: int = zipfile.ZIP_DEFLATED) -> None:
    """``compression`` picks the zip entry codec: the default
    ``ZIP_DEFLATED`` for routine checkpoints, ``ZIP_STORED`` for the
    preemption grace-window emergency path (parallel/preemption.py) —
    skipping deflate trades disk bytes for write latency when the host
    is seconds from going away.  Readers don't care: the zip headers
    carry the codec per entry, and the v4 integrity digests are over the
    UNCOMPRESSED entry bytes, so verification is codec-independent."""
    entries = {"configuration.json":
               json.dumps(net.conf.to_dict(), indent=1).encode(),
               "params.npz": _npz_bytes(_flatten_tree(net.params)),
               "state.npz": _npz_bytes(_flatten_tree(net.state))}
    if save_updater:
        entries["updater.npz"] = _npz_bytes(_flatten_tree(net.opt_state))
    residual = getattr(net, "grad_residual", None)
    if residual is not None:
        entries["grad_residual.npz"] = _npz_bytes(_flatten_tree(residual))
    meta = {
        "format_version": FORMAT_VERSION,
        "iteration": net.iteration,
        "epoch": net.epoch,
        "model_class": getattr(net, "_model_class", type(net).__name__),
        # end-to-end digests over the entry bytes (v4): meta.json is tiny
        # and parsed (json errors surface on their own), everything else
        # is verified against these on load
        "integrity": {name: _digest(data) for name, data in entries.items()},
    }
    with zipfile.ZipFile(path, "w", compression) as zf:
        zf.writestr("meta.json", json.dumps(meta))
        for name, data in entries.items():
            zf.writestr(name, data)


def _read_verified(zf: "zipfile.ZipFile", name: str, integrity, path) -> bytes:
    data = zf.read(name)
    want = (integrity or {}).get(name)
    if want is not None and _digest(data) != want:
        raise CheckpointIntegrityError(
            f"checkpoint entry {name!r} in {path!r} fails its sha256 digest "
            "— the file is corrupt (torn write / bit flip); restore from an "
            "older checkpoint")
    return data


def load_model(path: str, load_updater: bool = True):
    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read("meta.json"))
        ver = meta.get("format_version", 1)
        if ver not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"checkpoint format v{ver} not supported (reader knows "
                f"{SUPPORTED_VERSIONS}); re-save with a matching framework "
                "version")
        integrity = meta.get("integrity")  # absent in v1-v3: load unverified
        conf_d = json.loads(_read_verified(zf, "configuration.json",
                                           integrity, path))
        params_flat = _load_npz(_read_verified(zf, "params.npz", integrity,
                                               path))
        state_flat = _load_npz(_read_verified(zf, "state.npz", integrity,
                                              path))
        names = zf.namelist()
        upd_flat = _load_npz(_read_verified(
            zf, "updater.npz", integrity, path)) if (
            load_updater and "updater.npz" in names) else None
        resid_flat = _load_npz(_read_verified(
            zf, "grad_residual.npz", integrity, path)) if (
            "grad_residual.npz" in names) else None

    if conf_d.get("type") == "ComputationGraphConfiguration":
        from ..nn.graph import ComputationGraph, ComputationGraphConfiguration
        conf = ComputationGraphConfiguration.from_dict(conf_d)
        net = ComputationGraph(conf)
    else:
        from ..nn.multilayer import MultiLayerConfiguration, MultiLayerNetwork
        conf = MultiLayerConfiguration.from_dict(conf_d)
        net = MultiLayerNetwork(conf)
    net.init()  # builds templates with correct structure
    net.params = _unflatten_into(net.params, params_flat)
    net.state = _unflatten_into(net.state, state_flat)
    if upd_flat is not None:
        net.opt_state = _unflatten_into(net.opt_state, upd_flat)
    if resid_flat is not None:
        # params tree is only the structural template here — residual
        # leaves carry their own (slice-leading) shapes from the npz
        net.grad_residual = _unflatten_into(net.params, resid_flat)
    net.iteration = meta.get("iteration", 0)
    net.epoch = meta.get("epoch", 0)
    return net
