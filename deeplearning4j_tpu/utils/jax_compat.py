"""jax version portability for the scale-out stack.

The framework targets the modern jax surface (``jax.shard_map``,
``jax.typeof(...).vma``, ``jax.sharding.set_mesh``) but must also run on
the jax 0.4.x line some environments bake in, where shard_map still lives
in ``jax.experimental`` (with ``check_rep`` instead of ``check_vma``),
varying-across-mesh typing does not exist, and there is no ambient-mesh
setter.  Every parallel/ module routes through these shims instead of
touching the moving names directly; on a current jax they are zero-cost
pass-throughs.

Semantics notes for the 0.4.x path:
  - ``check_vma=False`` maps to ``check_rep=False``; the default (vma
    checking ON) also maps to ``check_rep=False`` — 0.4.x's replication
    checker predates several collective transpose rules the pipeline and
    ring layers rely on, while the *math* is unaffected (grad parity is
    pinned by tests/test_parallelism_4d.py and tests/test_parallel.py).
  - vma typing degrades to "unknown": ``vma_of`` returns an empty
    frozenset and ``vary_over`` is the identity, which is exactly what a
    backend without the typing discipline expects.
  - ``set_mesh`` enters the plain ``Mesh`` context manager — enough for
    the NamedSharding-carrying jit calls the trainers make.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["axis_size", "enable_x64", "shard_map", "set_mesh", "vma_of",
           "vary_over"]

_HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")
_HAS_TYPEOF = hasattr(jax, "typeof")


if _HAS_NEW_SHARD_MAP:
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
else:
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
        return _shard_map_04x(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False)

shard_map.__doc__ = """``jax.shard_map`` across jax versions.

Keyword-only, mirroring the modern signature; ``check_vma=None`` means
"library default".  See the module docstring for the 0.4.x mapping."""


if hasattr(jax.sharding, "set_mesh"):
    set_mesh = jax.sharding.set_mesh
else:
    @contextlib.contextmanager
    def set_mesh(mesh):
        """Ambient-mesh context for jax without ``jax.sharding.set_mesh``."""
        with mesh:
            yield mesh


if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:
    from jax.experimental import enable_x64  # noqa: F401  (0.4.x home)


def axis_size(name):
    """``jax.lax.axis_size`` (0.4.x spells it ``psum(1, name)`` — the
    classic static-size idiom; the literal 1 folds to the axis size)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def vma_of(x):
    """The varying-across-mesh axis set of ``x`` (empty frozenset outside
    shard_map or on jax without vma typing)."""
    if not _HAS_TYPEOF:
        return frozenset()
    return getattr(jax.typeof(x), "vma", frozenset())


def vary_over(x, axes):
    """Mark ``x`` as device-varying over ``axes`` it isn't already varying
    on (shard_map vma typing for zero-init scan carries).  Uses
    ``jax.lax.pcast`` where available (pvary is deprecated in jax ≥0.9);
    identity on jax without vma typing."""
    if not _HAS_TYPEOF:
        return x
    have = vma_of(x)
    need = tuple(a for a in axes if a not in have)
    if not need:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, need, to="varying")
    return jax.lax.pvary(x, need)
