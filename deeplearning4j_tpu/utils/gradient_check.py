"""Numeric-vs-analytic gradient validation.

Parity target: reference gradientcheck/GradientCheckUtil.java:57
(``checkGradients():112``: central difference at eps, max relative error
threshold, per-parameter reporting).  This is the correctness backbone of
the reference's test suite (13 gradient-check suites, SURVEY.md §4.1) and
of ours: jax.grad's analytic gradients are compared against central
differences of the network score.

Run under float64 (``jax.experimental.enable_x64`` in tests) for the
reference's 1e-4/1e-5 tolerances to be meaningful.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def check_gradients(
    net,
    ds,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    max_params_per_array: int = 16,
    seed: int = 0,
    verbose: bool = False,
) -> bool:
    """Central-difference check of d(score)/d(params) for a network.

    Mirrors GradientCheckUtil.checkGradients: relative error
    |a - n| / max(|a|, |n|) must be < max_rel_error unless |a - n| <
    min_abs_error.  ``max_params_per_array`` subsamples large tensors
    (checking every element of a conv kernel is wasteful — the reference
    checks all, we sample deterministically).
    """
    x = jnp.asarray(ds.features)
    y = None if ds.labels is None else jnp.asarray(ds.labels)
    m = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)

    @jax.jit
    def score_fn(params):
        loss, _ = net._loss(params, net.state, x, y, train=False, rng=None,
                            mask=m, label_mask=lm)
        return loss

    analytic = jax.jit(jax.grad(score_fn))(net.params)
    flat_params, treedef = jax.tree_util.tree_flatten(net.params)
    flat_grads = treedef.flatten_up_to(analytic)
    # Use numpy copies for perturbation
    host_params = [np.array(p, dtype=np.float64) if jnp.issubdtype(p.dtype, jnp.floating)
                   else np.array(p) for p in flat_params]

    rng = np.random.default_rng(seed)
    total_checked, failures = 0, []
    for ai, (p, g) in enumerate(zip(host_params, flat_grads)):
        if not np.issubdtype(p.dtype, np.floating):
            continue
        size = p.size
        idxs = np.arange(size) if size <= max_params_per_array else \
            rng.choice(size, size=max_params_per_array, replace=False)
        for flat_idx in idxs:
            orig = p.flat[flat_idx]
            p.flat[flat_idx] = orig + epsilon
            plus = float(score_fn(treedef.unflatten(
                [jnp.asarray(q, flat_params[i].dtype) for i, q in enumerate(host_params)])))
            p.flat[flat_idx] = orig - epsilon
            minus = float(score_fn(treedef.unflatten(
                [jnp.asarray(q, flat_params[i].dtype) for i, q in enumerate(host_params)])))
            p.flat[flat_idx] = orig
            numeric = (plus - minus) / (2 * epsilon)
            a = float(np.asarray(g).flat[flat_idx])
            abs_err = abs(a - numeric)
            denom = max(abs(a), abs(numeric))
            rel_err = abs_err / denom if denom > 0 else 0.0
            total_checked += 1
            if rel_err > max_rel_error and abs_err > min_abs_error:
                failures.append((ai, int(flat_idx), a, numeric, rel_err))
                if verbose:
                    print(f"FAIL array {ai} idx {flat_idx}: analytic={a:.6e} "
                          f"numeric={numeric:.6e} rel={rel_err:.3e}")

    if verbose:
        print(f"checked {total_checked} params, {len(failures)} failures")
    return len(failures) == 0
