"""Early stopping — config-driven training driver.

Parity target: reference earlystopping/ (EarlyStoppingConfiguration,
trainer/EarlyStoppingTrainer, 8 termination conditions, scorecalc/
DataSetLossCalculator, saver/LocalFileModelSaver|InMemoryModelSaver;
SURVEY.md §2.1 "Early stopping").  Epoch terminations stop between epochs;
iteration terminations can stop mid-epoch (checked every
``evaluate_every_n_epochs`` per the reference's semantics).
"""

from __future__ import annotations

import copy
import dataclasses
import os
import time
from typing import Any, Callable, List, Optional

import jax


# ---------------------------------------------------------------------------
# score calculators (reference scorecalc/)
# ---------------------------------------------------------------------------


class DataSetLossCalculator:
    """Validation loss (reference DataSetLossCalculator).  minimize=True."""

    minimize_score = True

    def __init__(self, data):
        self.data = data

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        for ds in model._as_iterator(self.data):
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / max(n, 1)


class AccuracyScoreCalculator:
    """Validation accuracy (maximize)."""

    minimize_score = False

    def __init__(self, data):
        self.data = data

    def calculate_score(self, model) -> float:
        return model.evaluate(self.data).accuracy()


# ---------------------------------------------------------------------------
# termination conditions (reference termination/)
# ---------------------------------------------------------------------------


class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        return epoch >= self.max_epochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without (minimal) improvement (reference
    ScoreImprovementEpochTerminationCondition)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._epochs_since_best = 0

    def on_epoch(self, improved: bool) -> None:
        self._epochs_since_best = 0 if improved else self._epochs_since_best + 1

    def terminate(self, epoch, score, best_score) -> bool:
        return self._epochs_since_best > self.patience


class MaxScoreIterationTerminationCondition:
    """Abort when the training score explodes past a bound (reference
    MaxScoreIterationTerminationCondition)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate_iteration(self, score: float) -> bool:
        import math
        return (not math.isfinite(score)) or score > self.max_score


class MaxTimeIterationTerminationCondition:
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start: Optional[float] = None

    def terminate_iteration(self, score: float) -> bool:
        if self._start is None:
            self._start = time.monotonic()
            return False
        return (time.monotonic() - self._start) > self.max_seconds


class InvalidScoreIterationTerminationCondition:
    """Abort immediately on NaN/Inf training score — the divergence guard
    (reference termination/InvalidScoreIterationTerminationCondition)."""

    def terminate_iteration(self, score: float) -> bool:
        import math
        return not math.isfinite(score)


class BestScoreEpochTerminationCondition:
    """Stop once the validation score is at least as good as a target
    (reference termination/BestScoreEpochTerminationCondition)."""

    def __init__(self, best_expected_score: float,
                 minimize: Optional[bool] = None):
        # minimize=None inherits the direction from the score calculator at
        # fit time, so a maximizing calculator (accuracy) can't silently be
        # paired with a minimizing threshold
        self.best_expected_score = best_expected_score
        self.minimize = minimize

    def terminate(self, epoch, score, best_score) -> bool:
        minimize = True if self.minimize is None else self.minimize
        if minimize:
            return score <= self.best_expected_score
        return score >= self.best_expected_score


# ---------------------------------------------------------------------------
# model savers (reference saver/)
# ---------------------------------------------------------------------------


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(model):
        return (jax.tree_util.tree_map(lambda a: a, model.params),
                jax.tree_util.tree_map(lambda a: a, model.state),
                jax.tree_util.tree_map(lambda a: a, model.opt_state))

    def save_best(self, model) -> None:
        self._best = self._snapshot(model)

    def save_latest(self, model) -> None:
        self._latest = self._snapshot(model)

    def restore_best(self, model) -> None:
        if self._best is not None:
            model.params, model.state, model.opt_state = self._best


class LocalFileModelSaver:
    """Best/latest zips in a directory (reference LocalFileModelSaver)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.best_path = os.path.join(directory, "bestModel.zip")
        self.latest_path = os.path.join(directory, "latestModel.zip")

    def save_best(self, model) -> None:
        model.save(self.best_path)

    def save_latest(self, model) -> None:
        model.save(self.latest_path)

    def restore_best(self, model) -> None:
        if os.path.exists(self.best_path):
            restored = type(model).load(self.best_path)
            model.params, model.state, model.opt_state = (
                restored.params, restored.state, restored.opt_state)


# ---------------------------------------------------------------------------
# configuration + trainer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    epoch_terminations: List[Any] = dataclasses.field(default_factory=list)
    iteration_terminations: List[Any] = dataclasses.field(default_factory=list)
    model_saver: Any = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: List[float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class EarlyStoppingTrainer:
    """Drives fit-epoch / score / save / terminate (reference
    trainer/EarlyStoppingTrainer + EarlyStoppingGraphTrainer — one class
    here since MLN and CG share the fit surface)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_data):
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        minimize = getattr(cfg.score_calculator, "minimize_score", True)
        for t in cfg.epoch_terminations:
            if getattr(t, "minimize", False) is None:
                t.minimize = minimize
        best_score = float("inf") if minimize else float("-inf")
        best_epoch = -1
        scores: List[float] = []
        epoch = 0
        reason, details = "MaxEpochs", ""

        while True:
            # -- one epoch with iteration terminations ----------------------
            aborted = False
            for ds in self.model._as_iterator(self.train_data):
                loss = self.model.fit_batch(ds)
                for t in cfg.iteration_terminations:
                    if t.terminate_iteration(loss):
                        reason = "IterationTermination"
                        details = f"{type(t).__name__} at loss {loss}"
                        aborted = True
                        break
                if aborted:
                    break
            self.model.epoch += 1
            epoch += 1
            if aborted:
                break

            # -- score + save best ------------------------------------------
            improved = False
            if cfg.score_calculator is not None and epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
                scores.append(score)
                improved = score < best_score if minimize else score > best_score
                if improved:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best(self.model)
            if cfg.save_last_model:
                cfg.model_saver.save_latest(self.model)

            # -- epoch terminations -----------------------------------------
            stop = False
            for t in cfg.epoch_terminations:
                if hasattr(t, "on_epoch"):
                    t.on_epoch(improved)
                if t.terminate(epoch, scores[-1] if scores else float("nan"), best_score):
                    reason = "EpochTermination"
                    details = type(t).__name__
                    stop = True
                    break
            if stop:
                break

        cfg.model_saver.restore_best(self.model)
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=scores,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch,
            best_model=self.model,
        )
