from .earlystopping import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    DataSetLossCalculator,
    AccuracyScoreCalculator,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    InMemoryModelSaver,
    LocalFileModelSaver,
)
