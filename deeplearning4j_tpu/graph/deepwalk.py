"""DeepWalk — node embeddings from random walks (Perozzi et al. 2014).

Parity target: reference graph/models/deepwalk/DeepWalk.java (Builder:
vectorSize, windowSize, learningRate; fit(GraphWalkIterator) trains
skip-gram with hierarchical softmax over a degree-based Huffman tree).

TPU inversion: walks become integer "sentences" for the shared
SequenceVectors engine (nlp/sequencevectors.py) — one corpus interface for
words, documents, and graph vertices, exactly the layering the reference
uses (DeepWalk extends the SequenceVectors stack).  Both hierarchical
softmax (the reference's choice) and negative sampling are available.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..nlp.sequencevectors import SequenceVectors
from .graph import Graph
from .walks import RandomWalkIterator


class DeepWalk:
    """Builder-parity surface: vector_size, window_size, walk_length,
    walks_per_vertex, learning_rate (reference DeepWalk.Builder)."""

    def __init__(self,
                 vector_size: int = 100,
                 window_size: int = 5,
                 walk_length: int = 40,
                 walks_per_vertex: int = 10,
                 learning_rate: float = 0.025,
                 epochs: int = 1,
                 hierarchic_softmax: bool = True,
                 negative: int = 5,
                 batch_size: int = 2048,
                 seed: int = 12345):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.hs = hierarchic_softmax
        self.negative = negative
        self.batch_size = batch_size
        self.seed = seed
        self._sv: Optional[SequenceVectors] = None
        self._graph: Optional[Graph] = None

    def fit(self, graph: Graph, walks=None) -> "DeepWalk":
        """Generate walks (or take a provided iterator) and train."""
        self._graph = graph
        if walks is None:
            walks = RandomWalkIterator(graph, self.walk_length,
                                       self.walks_per_vertex, self.seed)
        corpus: List[List[int]] = [list(w) for w in walks]
        self._sv = SequenceVectors(
            layer_size=self.vector_size,
            window=self.window_size,
            min_word_frequency=1,
            negative=self.negative,
            hierarchic_softmax=self.hs,
            learning_rate=self.learning_rate,
            epochs=self.epochs,
            batch_size=self.batch_size,
            seed=self.seed)
        self._sv.fit_sequences(corpus)
        return self

    # ------------------------------------------------------------------
    # lookup (reference GraphVectors interface)
    # ------------------------------------------------------------------

    def vertex_vector(self, v: int) -> np.ndarray:
        return self._sv.word_vector(v)

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(a, b)

    def verticies_nearest(self, v: int, top_n: int = 10) -> List[int]:
        # (sic) reference spells it verticesNearest; keep a sane alias too
        return self._sv.words_nearest(v, top_n)

    vertices_nearest = verticies_nearest

    @property
    def vectors(self) -> np.ndarray:
        """[n_vertices, vector_size] table indexed by vocab order — use
        ``vertex_vector`` for id-addressed lookup."""
        return self._sv.syn0
