"""Random-walk generators (reference deeplearning4j-graph
iterator/RandomWalkIterator.java + WeightedWalkIterator.java).

Walks are plain integer sequences consumed by SequenceVectors — the same
corpus interface word2vec uses, per the reference's
GraphWalkIteratorProvider → SequenceVectors bridge.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from .graph import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex
    (reference RandomWalkIterator: NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED)."""

    def __init__(self, graph: Graph, walk_length: int,
                 walks_per_vertex: int = 1, seed: int = 12345):
        self.graph = graph
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed

    def _choose_next(self, rng, cur: int, nbrs: List[int]) -> int:
        """Next-hop policy hook — uniform here, weighted in the subclass."""
        return int(nbrs[rng.integers(0, len(nbrs))])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        order = np.arange(self.graph.n)
        for _ in range(self.walks_per_vertex):
            rng.shuffle(order)
            for start in order:
                walk = [int(start)]
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    nbrs = self.graph.neighbors(cur)
                    if not nbrs:  # disconnected → self loop
                        walk.append(cur)
                        continue
                    cur = self._choose_next(rng, cur, nbrs)
                    walk.append(cur)
                yield walk


class WeightedWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional walks (reference WeightedWalkIterator)."""

    def _choose_next(self, rng, cur: int, nbrs: List[int]) -> int:
        w = np.asarray(self.graph.edge_weights(cur), np.float64)
        return int(nbrs[rng.choice(len(nbrs), p=w / w.sum())])
