"""Graph embeddings (replaces deeplearning4j-graph, SURVEY.md §2.4)."""

from .graph import Graph
from .walks import RandomWalkIterator, WeightedWalkIterator
from .deepwalk import DeepWalk

__all__ = ["Graph", "RandomWalkIterator", "WeightedWalkIterator", "DeepWalk"]
