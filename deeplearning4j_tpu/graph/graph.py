"""In-memory graph (reference deeplearning4j-graph
api/graph/Graph.java + impl/Graph.java: vertices with adjacency lists,
directed or undirected, optional edge weights)."""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List, Optional, Tuple

import numpy as np


class Graph:
    """Adjacency-list graph over integer vertex ids [0, n).

    ``add_edge(a, b, weight)``; undirected graphs mirror automatically
    (reference Graph.addEdge with undirected=true).
    """

    def __init__(self, num_vertices: int, undirected: bool = True):
        if num_vertices <= 0:
            raise ValueError("num_vertices must be positive")
        self.n = num_vertices
        self.undirected = undirected
        self._adj: List[List[int]] = [[] for _ in range(num_vertices)]
        self._w: List[List[float]] = [[] for _ in range(num_vertices)]

    def add_edge(self, a: int, b: int, weight: float = 1.0) -> None:
        if not (0 <= a < self.n and 0 <= b < self.n):
            raise ValueError(f"edge ({a},{b}) out of range [0,{self.n})")
        self._adj[a].append(b)
        self._w[a].append(weight)
        if self.undirected:
            self._adj[b].append(a)
            self._w[b].append(weight)

    def add_edges(self, edges: Iterable[Tuple[int, int]]) -> None:
        for e in edges:
            self.add_edge(e[0], e[1], e[2] if len(e) > 2 else 1.0)

    def num_vertices(self) -> int:
        return self.n

    def degree(self, v: int) -> int:
        return len(self._adj[v])

    def neighbors(self, v: int) -> List[int]:
        return self._adj[v]

    def edge_weights(self, v: int) -> List[float]:
        return self._w[v]
