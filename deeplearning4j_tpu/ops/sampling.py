"""Seeded counter-based token sampling — ONE source of truth.

The decode engine's host-driven samplers (serving/decode.py
``_make_samplers``) and the fused multi-step decode programs
(``DecodeProgram.step_multi`` — models/transformer.py,
parallel/transformer.py) must draw bitwise-identical tokens for the
same (logits, sampling spec, seed, token_index): the fused-decode A/B
gate (bench ``fused_step_ab``) compares them token for token, and the
crash-retry path regenerates sequences by replaying the same counters.
Keeping the math here makes that identity structural — both callers
trace the SAME function, so there is no second implementation to
drift.

The key schedule is ``fold_in(PRNGKey(seed), step)`` with ``step`` the
absolute generated-token index (0 = the token sampled from the prefill
logits), which is what makes horizon fusion exact: step j of a fused
horizon uses the identical key the plain engine would have used j
dispatches later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_token(lg, t, k, p, seed, step, vocab_size: int):
    """Sample one token from a logits row ``lg`` [V].

    temperature ``t`` <= 0 is greedy; ``k`` == 0 and ``p`` >= 1 disable
    the top-k / top-p filters.  Returns ``(token int32, finite bool)``
    — ``finite`` is the all-finite poison flag the engine's isolation
    path reads.  Deterministic: the PRNG key is
    ``fold_in(PRNGKey(seed), step)``, so the same (seed, step) always
    produces the same draw regardless of which executable traced it.
    """
    finite = jnp.all(jnp.isfinite(lg))
    greedy = jnp.argmax(lg).astype(jnp.int32)
    scaled = lg / jnp.maximum(t, 1e-6)
    srt = jnp.sort(scaled)[::-1]
    kk = jnp.clip(jnp.where(k > 0, k, vocab_size), 1, vocab_size)
    thr_k = srt[kk - 1]
    probs = jax.nn.softmax(srt)
    cum_excl = jnp.cumsum(probs) - probs   # mass BEFORE each entry
    keep = cum_excl < jnp.clip(p, 1e-6, 1.0)  # top-1 always kept
    thr_p = jnp.min(jnp.where(keep, srt, jnp.inf))
    thr = jnp.maximum(thr_k, thr_p)
    masked = jnp.where(scaled >= thr, scaled, -jnp.inf)
    g = jax.random.gumbel(
        jax.random.fold_in(jax.random.PRNGKey(seed), step), lg.shape)
    sampled = jnp.argmax(masked + g).astype(jnp.int32)
    return jnp.where(t <= 0.0, greedy, sampled), finite
