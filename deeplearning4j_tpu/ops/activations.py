"""Activation functions — parity surface for ND4J's ``IActivation`` registry.

The reference selects activations by enum/string on each layer config
(reference nn/conf/layers via `Activation.fromString`; impls live in ND4J
``org.nd4j.linalg.activations.impl``).  Here each activation is a pure
jax.numpy function; the backward pass comes from autodiff instead of the
hand-written ``backprop(in, epsilon)`` each ND4J activation implements.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

Array = jax.Array
Activation = Callable[[Array], Array]


def identity(x: Array) -> Array:
    return x


def relu(x: Array) -> Array:
    return jnp.maximum(x, 0)


def relu6(x: Array) -> Array:
    return jnp.clip(x, 0, 6)


def leakyrelu(x: Array, alpha: float = 0.01) -> Array:
    return jnp.where(x >= 0, x, alpha * x)


def elu(x: Array, alpha: float = 1.0) -> Array:
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x: Array) -> Array:
    return jax.nn.selu(x)


def gelu(x: Array) -> Array:
    return jax.nn.gelu(x)


def sigmoid(x: Array) -> Array:
    return jax.nn.sigmoid(x)


def hardsigmoid(x: Array) -> Array:
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x: Array) -> Array:
    return jnp.tanh(x)


def hardtanh(x: Array) -> Array:
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x: Array) -> Array:
    # ND4J RationalTanh: 1.7159 * tanh_approx(2x/3) via Pade-like rational
    # approximation f(x) = clip(x*(36x^2+49)/(x^2(12x^2+49)+49)) scaled.
    a = x * (2.0 / 3.0)
    tanh_a = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a * a + 1.41645 * a * a * a * a))
    return 1.7159 * tanh_a


def rectifiedtanh(x: Array) -> Array:
    return jnp.maximum(0.0, jnp.tanh(x))


def softplus(x: Array) -> Array:
    return jax.nn.softplus(x)


def softsign(x: Array) -> Array:
    return jax.nn.soft_sign(x)


def softmax(x: Array) -> Array:
    return jax.nn.softmax(x, axis=-1)


def logsoftmax(x: Array) -> Array:
    return jax.nn.log_softmax(x, axis=-1)


def cube(x: Array) -> Array:
    return x * x * x


def swish(x: Array) -> Array:
    return x * jax.nn.sigmoid(x)


def mish(x: Array) -> Array:
    return x * jnp.tanh(jax.nn.softplus(x))


_REGISTRY: Dict[str, Activation] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softplus": softplus,
    "softsign": softsign,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "cube": cube,
    "swish": swish,
    "mish": mish,
}


#: activations accepting a scalar parameter via "name(value)" syntax —
#: the string form keeps layer configs JSON round-trippable (the reference
#: carries the scalar on the impl object, e.g. ActivationLReLU(alpha))
_PARAMETRIC = {"leakyrelu", "elu"}


def get_activation(name: str | Activation) -> Activation:
    """Resolve an activation by name (case-insensitive, DL4J enum style).
    Parametric forms: "leakyrelu(0.3)", "elu(0.5)"."""
    if callable(name):
        return name
    key = name.lower()
    if key.endswith(")") and "(" in key:
        base, _, arg = key.partition("(")
        if base in _PARAMETRIC:
            try:
                # graftcheck: disable=GC101 (parses a STATIC activation-name string at trace time — never a traced value)
                alpha = float(arg[:-1])
            except ValueError:
                raise ValueError(
                    f"Bad parametric activation '{name}': expected "
                    f"'{base}(<number>)', e.g. '{base}(0.3)'") from None
            fn = _REGISTRY[base]
            return lambda x: fn(x, alpha)
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def activation_names() -> list[str]:
    return sorted(_REGISTRY)
