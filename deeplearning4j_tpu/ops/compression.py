"""Gradient compression kernels — the wire format of the DCN exchange tier.

Parity target: the reference's distributed trainer compresses gradients
before they touch the (slow) wire — ``EncodingHandler`` behind
``SharedTrainingMaster`` picks between ``thresholdEncode`` (sparse: one
signed int32 index per transmitted element, sign of the int = sign of the
update, magnitude = the threshold) and ``bitmapEncode`` (dense: 2 bits per
element) and keeps what it did NOT transmit in a residual accumulator that
is re-applied next step (error feedback — compression error never
disappears, it is deferred).

Here the slow wire is the DCN between TPU slices (ICI within a slice is
orders of magnitude faster — "Exploring the limits of Concurrency in ML
Training on Google TPUs"), so these kernels implement the cross-slice tier
of a two-tier exchange: dense psum over the ICI axis, then
``compressed_pmean`` over the ``dcn`` axis.  Everything is jit-able jnp
code; the exchange all_gathers the ENCODED buffers, so the collective
genuinely moves only the compressed bytes.

Two encodings, mirroring the reference's pair:

  threshold  — top-k-by-magnitude sparse encoding with a fixed capacity of
               ``n/16`` elements (the reference's threshold→bitmap
               switchover density).  Fixed ``threshold`` reproduces the
               reference exactly (transmit sign·threshold); the default
               adaptive mode (``threshold=None``) transmits sign·scale
               with scale = mean |selected| — a per-bucket, per-step
               live threshold that needs no tuning.
  bitmap     — 2 bits/element packed 16-to-a-uint32 ({0, +scale, -scale});
               adaptive scale = mean |g|.  Wire cost is shape-static
               (n/16 words), the right choice when gradients are dense.

Both are ~16x below f32 on the wire by construction, independent of the
gradient's actual sparsity — the property the bench gate asserts.
"""

from __future__ import annotations

import functools
import math
import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_compat import axis_size

#: opt-in one-pass fixed-threshold encode (sort-free select+pack; see
#: the "one-pass threshold encode" section).  Read once at import, like
#: ops/update_kernel.ENABLED — checked at TRACE time.
FUSED_ENCODE = os.environ.get("DL4J_TPU_FUSED_ENCODE", "0") == "1"
#: route the one-pass encode through the pallas kernel instead of the
#: fused-jnp streaming pass (the kernel is the TPU seam; streaming jnp
#: is the arm the CPU A/B measures)
FUSED_ENCODE_PALLAS = os.environ.get(
    "DL4J_TPU_FUSED_ENCODE_PALLAS", "0") == "1"

try:
    from jax.experimental import pallas as pl

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

METHODS = ("threshold", "bitmap")
#: reference EncodingHandler default threshold (fixed-threshold mode)
DEFAULT_THRESHOLD = 1e-3
#: capacity of the threshold encoding: at most n/16 elements per message
#: (the reference switches to bitmapEncode above this density — beyond it
#: the sparse format is no longer smaller)
THRESHOLD_DENSITY_CAP = 1.0 / 16.0
#: 2-bit codes, 16 to a uint32 word
BITMAP_LANES = 16
#: bucket granularity of the exchange (see GradBucketer)
DEFAULT_BUCKET_BYTES = 4 << 20


def default_k_max(n: int) -> int:
    """Threshold-encoding message capacity for an n-element bucket."""
    # graftcheck: disable=GC101 (n is a STATIC bucket size known at trace time, not a traced value)
    return 0 if n == 0 else max(1, int(n * THRESHOLD_DENSITY_CAP))


# ---------------------------------------------------------------------------
# one-pass threshold encode (sort-free select + signed-index pack)
# ---------------------------------------------------------------------------
#
# Fixed-threshold mode does not need top_k's O(n log n) sort at all: the
# selection predicate (|g| >= t) is local, so each selected element's
# output slot is just the running count of selected elements before it —
# a cumsum — and the pack is one scatter.  The encoded SET is identical
# to the top_k path whenever at most k elements clear the threshold;
# entry ORDER differs (index-ascending vs magnitude-descending), which
# threshold_decode's scatter-add never observes — decode round-trips are
# bit-identical (every dense index receives the same +-scale entries,
# and partial sums of m·t are exact for integral m).  Overflow (> k
# selected) lax.cond's into the exact top_k path, keeping its
# largest-first selection.  Adaptive mode (threshold=None) genuinely
# needs the k-th order statistic and always uses top_k.

_ENC_LANES = 128
#: pallas variant: single-block kernel, so cap the VMEM footprint
_ENC_PALLAS_MAX_BYTES = 8 << 20


def _topk_pack(g, mag, k: int, threshold):
    """The reference-exact fixed-mode pack: top_k over the masked
    magnitudes (largest-first selection under overflow)."""
    vals, idx = jax.lax.top_k(jnp.where(mag >= threshold, mag, 0.0), k)
    valid = vals > 0.0
    sign = jnp.where(g[idx] >= 0, 1, -1).astype(jnp.int32)
    return jnp.where(valid, sign * (idx + 1), 0).astype(jnp.int32)


def _streaming_pack(g, mag, k: int, threshold: float, n: int):
    """One fused pass: slot = exclusive running count of selections.
    Precondition (caller's lax.cond): at most k elements clear t."""
    sel = mag >= threshold
    pos = jnp.cumsum(sel.astype(jnp.int32)) - 1
    payload = (jnp.where(g >= 0, 1, -1).astype(jnp.int32)
               * (jnp.arange(n, dtype=jnp.int32) + 1))
    slot = jnp.where(sel & (pos < k), pos, k)
    return jnp.zeros((k,), jnp.int32).at[slot].set(payload, mode="drop")


def _encode_kernel(g_ref, o_ref, *, k: int, k_pad: int, threshold: float,
                   n: int):
    g = g_ref[...].reshape(-1)          # row-major == original order
    mag = jnp.abs(g)
    idx = jax.lax.iota(jnp.int32, g.shape[0])
    sel = (mag >= threshold) & (idx < n)   # zero padding never selects
    pos = jnp.cumsum(sel.astype(jnp.int32)) - 1
    payload = jnp.where(g >= 0, 1, -1).astype(jnp.int32) * (idx + 1)
    slot = jnp.where(sel & (pos < k), pos, k_pad)
    out = jnp.zeros((k_pad,), jnp.int32).at[slot].set(payload, mode="drop")
    o_ref[...] = out.reshape(-1, _ENC_LANES)


def _pallas_pack(g, k: int, threshold: float, n: int):
    """Select+pack as ONE pallas pass over the whole (VMEM-resident)
    bucket; interpret-mode on CPU.  Caller guarantees the size gate."""
    pad = (-n) % (8 * _ENC_LANES)
    rows = (n + pad) // _ENC_LANES
    k_pad = k + ((-k) % _ENC_LANES)
    out = pl.pallas_call(
        functools.partial(_encode_kernel, k=k, k_pad=k_pad,
                          threshold=threshold, n=n),
        out_shape=jax.ShapeDtypeStruct((k_pad // _ENC_LANES, _ENC_LANES),
                                       jnp.int32),
        interpret=(jax.default_backend() == "cpu"),
    )(jnp.pad(g, (0, pad)).reshape(rows, _ENC_LANES))
    return out.reshape(-1)[:k]


def _pallas_encode_ok(n: int) -> bool:
    return (_HAS_PALLAS
            and jax.default_backend() in ("tpu", "cpu")
            and n >= 8 * _ENC_LANES
            and 4 * n <= _ENC_PALLAS_MAX_BYTES)


def _one_pass_threshold_encode(g, mag, k: int, threshold: float, n: int):
    """enc int32[k] via the sort-free path, falling back to the exact
    top_k pack inside lax.cond when more than k elements clear t."""
    count = jnp.sum((mag >= threshold).astype(jnp.int32))

    def fits(_):
        if FUSED_ENCODE_PALLAS and _pallas_encode_ok(n):
            return _pallas_pack(g, k, threshold, n)
        return _streaming_pack(g, mag, k, threshold, n)

    def overflow(_):
        return _topk_pack(g, mag, k, threshold)

    return jax.lax.cond(count <= k, fits, overflow, None)


# ---------------------------------------------------------------------------
# threshold encoding (reference thresholdEncode analog)
# ---------------------------------------------------------------------------

def threshold_encode(g, k_max: int, threshold: Optional[float] = None):
    """Encode a 1-D gradient into ``(enc int32[k], scale f32[])``.

    ``enc`` entries are ``sign(g)·(index+1)`` for the selected elements and
    0 for unused capacity — the reference's signed-index wire format, which
    carries sign and position in one int32.  The decoded value of every
    transmitted element is ``sign·scale``:

      threshold=None  (adaptive) — select the k_max largest |g|; scale =
        mean of the selected magnitudes (zero-magnitude elements are never
        selected, so an all-zero gradient encodes to an empty message)
      threshold=t     (reference-exact) — select only |g| >= t (capacity
        permitting, largest first); scale = t
    """
    n = 0 if g.ndim == 0 else g.shape[0]
    k = min(k_max, n)
    if n == 0 or k <= 0:
        return jnp.zeros((max(k_max, 0),), jnp.int32), jnp.zeros((), jnp.float32)
    g = g.astype(jnp.float32)
    mag = jnp.abs(g)
    if threshold is None:
        vals, idx = jax.lax.top_k(mag, k)
        valid = vals > 0.0
        scale = (jnp.sum(jnp.where(valid, vals, 0.0))
                 / jnp.maximum(jnp.sum(valid), 1))
    else:
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        scale = jnp.asarray(threshold, jnp.float32)
        # one-pass path needs a static threshold (it is baked into the
        # kernel); a traced threshold stays on the top_k path
        if FUSED_ENCODE and isinstance(threshold, (int, float)):
            # graftcheck: disable=GC101 (the isinstance guard above makes threshold a STATIC Python number here — a traced threshold takes the top_k branch)
            enc = _one_pass_threshold_encode(g, mag, k, float(threshold), n)
            return enc, scale
        return _topk_pack(g, mag, k, threshold), scale
    sign = jnp.where(g[idx] >= 0, 1, -1).astype(jnp.int32)
    enc = jnp.where(valid, sign * (idx + 1), 0).astype(jnp.int32)
    return enc, scale.astype(jnp.float32)


def threshold_decode(enc, scale, n: int):
    """Decode (and SUM) threshold messages back to a dense f32[n].

    Accepts one message (``enc [k]``, ``scale []``) or a stack of gathered
    messages (``enc [P, k]``, ``scale [P]``) — the scatter-add over all
    entries is exactly the sum-of-decodes the allreduce needs, with no
    [P, n] dense intermediate."""
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    enc = jnp.asarray(enc)
    scale_b = jnp.broadcast_to(
        jnp.asarray(scale, jnp.float32)[..., None], enc.shape)
    # empty slots (enc == 0) map out of range and are dropped by the scatter
    idx = jnp.where(enc == 0, n, jnp.abs(enc) - 1).reshape(-1)
    val = (jnp.sign(enc).astype(jnp.float32) * scale_b).reshape(-1)
    return jnp.zeros((n,), jnp.float32).at[idx].add(val, mode="drop")


# ---------------------------------------------------------------------------
# bitmap encoding (reference bitmapEncode analog)
# ---------------------------------------------------------------------------

def bitmap_encode(g, threshold: Optional[float] = None):
    """Encode a 1-D gradient into ``(words uint32[ceil(n/16)], scale f32[])``.

    2-bit codes per element: 0 → not transmitted, 1 → +scale, 2 → -scale
    (code 3 reserved).  ``threshold=None`` uses the live scale mean |g|;
    a fixed threshold reproduces the reference's bitmapEncode."""
    n = 0 if g.ndim == 0 else g.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.uint32), jnp.zeros((), jnp.float32)
    g = g.astype(jnp.float32)
    mag = jnp.abs(g)
    if threshold is None:
        scale = jnp.mean(mag)
    else:
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        scale = jnp.asarray(threshold, jnp.float32)
    sel = (mag >= scale) & (scale > 0)  # scale==0 ⇒ zero gradient ⇒ empty
    code = jnp.where(sel, jnp.where(g >= 0, 1, 2), 0).astype(jnp.uint32)
    pad = (-n) % BITMAP_LANES
    lanes = jnp.pad(code, (0, pad)).reshape(-1, BITMAP_LANES)
    shifts = (2 * jnp.arange(BITMAP_LANES, dtype=jnp.uint32))
    # codes occupy disjoint bit pairs, so the sum is a bitwise OR
    words = jnp.sum(lanes << shifts, axis=1, dtype=jnp.uint32)
    return words, scale.astype(jnp.float32)


def bitmap_decode(words, scale, n: int):
    """Decode (and SUM) bitmap messages back to a dense f32[n].

    Accepts ``words [W]`` / ``scale []`` or gathered ``words [P, W]`` /
    ``scale [P]``; leading axes are summed."""
    if n == 0:
        return jnp.zeros((0,), jnp.float32)
    words = jnp.asarray(words)
    shifts = (2 * jnp.arange(BITMAP_LANES, dtype=jnp.uint32))
    codes = (words[..., None] >> shifts) & jnp.uint32(3)          # [..., W, 16]
    codes = codes.reshape(codes.shape[:-2] + (-1,))[..., :n]      # [..., n]
    scale_b = jnp.asarray(scale, jnp.float32)[..., None]
    vals = jnp.where(codes == 1, 1.0,
                     jnp.where(codes == 2, -1.0, 0.0)) * scale_b
    if vals.ndim > 1:
        vals = jnp.sum(vals, axis=tuple(range(vals.ndim - 1)))
    return vals.astype(jnp.float32)


# ---------------------------------------------------------------------------
# the compressed collective
# ---------------------------------------------------------------------------

def compressed_pmean(g, axis_name: str, method: str = "threshold",
                     threshold: Optional[float] = None,
                     k_max: Optional[int] = None):
    """Compressed mean of a 1-D bucket over a mesh axis (use inside
    shard_map).  Encodes locally, ``all_gather``s the ENCODED buffers —
    the only bytes that cross the axis — then decode-sums.

    Returns ``(mean, local_decoded)``: the caller keeps
    ``g - local_decoded`` as its error-feedback residual (what this step
    failed to transmit, re-applied next step)."""
    if method not in METHODS:
        raise ValueError(f"method must be one of {METHODS}, got {method!r}")
    n = g.shape[0]
    p = axis_size(axis_name)
    if method == "threshold":
        k = k_max if k_max is not None else default_k_max(n)
        enc, scale = threshold_encode(g, k, threshold)
        decode = threshold_decode
    else:
        enc, scale = bitmap_encode(g, threshold)
        decode = bitmap_decode
    gathered = jax.lax.all_gather(enc, axis_name)      # [P, message]
    scales = jax.lax.all_gather(scale, axis_name)      # [P]
    local = decode(enc, scale, n)
    total = decode(gathered, scales, n)
    return total / p, local


# ---------------------------------------------------------------------------
# bucketing — the comm/compute overlap unit
# ---------------------------------------------------------------------------

class GradBucketer:
    """Partition a gradient pytree into fixed-size 1-D f32 buckets.

    Each bucket is encoded and exchanged as an independent collective, so
    XLA's latency-hiding scheduler can overlap bucket k's all_gather with
    bucket k+1's encode/decode and with the optimizer update — one fused
    whole-tree message would serialize the entire exchange behind the last
    gradient.  (The reference buckets the same way: EncodingHandler
    encodes per-parameter chunks into the Aeron send queue as they become
    ready.)  Boundaries are computed once from the params template; the
    same instance must flatten and unflatten, since bucket layout is part
    of the wire format."""

    def __init__(self, tree, bucket_bytes: int = DEFAULT_BUCKET_BYTES):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.shapes = [np.shape(l) for l in leaves]
        self.dtypes = [jnp.asarray(l).dtype for l in leaves]
        self.sizes = [int(np.prod(s)) for s in self.shapes]
        self.total = int(sum(self.sizes))
        per = max(1, int(bucket_bytes) // 4)
        self.bounds = [(s, min(s + per, self.total))
                       for s in range(0, self.total, per)]

    @property
    def n_buckets(self) -> int:
        return len(self.bounds)

    def bucket_sizes(self) -> List[int]:
        return [e - s for s, e in self.bounds]

    def flatten(self, tree) -> List:
        """tree (same structure as the template) → list of f32 buckets."""
        leaves = jax.tree_util.tree_leaves(tree)
        if not leaves:
            return []
        flat = jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32) for l in leaves])
        return [flat[s:e] for s, e in self.bounds]

    def unflatten(self, buckets: List, cast: bool = True):
        """list of f32 buckets → tree.  ``cast=True`` restores each leaf's
        template dtype (gradients); ``cast=False`` keeps f32 (residuals
        must never round-trip through a lower-precision param dtype)."""
        if not buckets:
            return jax.tree_util.tree_unflatten(self.treedef, [])
        flat = jnp.concatenate(buckets)
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            leaf = flat[off:off + size].reshape(shape)
            out.append(leaf.astype(dtype) if cast else leaf)
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)


# ---------------------------------------------------------------------------
# analytic wire/overlap model (the pipeline_schedule_stats analog)
# ---------------------------------------------------------------------------

def encoded_message_bytes(n: int, method: str = "threshold",
                          k_max: Optional[int] = None) -> int:
    """Per-participant wire bytes of one bucket's encoded message
    (indices/words buffer + the f32 scale)."""
    if method not in METHODS:
        raise ValueError(f"method must be one of {METHODS}, got {method!r}")
    if n == 0:
        return 0
    if method == "threshold":
        k = k_max if k_max is not None else default_k_max(n)
        return 4 * min(k, n) + 4
    return 4 * math.ceil(n / BITMAP_LANES) + 4


def compression_stats(n_params: int, method: str = "threshold",
                      n_slices: int = 2,
                      bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                      k_max: Optional[int] = None, itemsize: int = 4,
                      dcn_gbps: float = 25.0) -> dict:
    """Analytic DCN-tier accounting for an ``n_params`` model.

    Per-participant bytes on the wire per step:

      dense ring allreduce        ≈ 2 · itemsize · n      (reduce-scatter
                                    + all-gather phases)
      compressed ring all_gather  ≈ (P-1) · message_bytes (each rank's
                                    encoded message circulates to the
                                    other P-1 ranks)

    The ratio is ~16·2/(P-1) for both encodings — by construction, not by
    luck: threshold capacity is n/16 int32s, bitmap is n/16 uint32 words.
    ``*_exchange_ms`` divides by the DCN bandwidth for a per-step exposure
    estimate; with ``n_buckets`` independent collectives the scheduler can
    hide most of it behind remaining backward compute."""
    per = max(1, int(bucket_bytes) // 4)
    sizes = ([min(per, n_params - s) for s in range(0, n_params, per)]
             if n_params else [])
    dense = 2 * itemsize * n_params
    msg = sum(encoded_message_bytes(b, method, k_max) for b in sizes)
    compressed = max(1, n_slices - 1) * msg
    byte_rate = dcn_gbps * 1e9
    return {
        "method": method,
        "n_slices": n_slices,
        "n_buckets": len(sizes),
        "message_bytes_per_rank": msg,
        "dense_wire_bytes_per_step": dense,
        "compressed_wire_bytes_per_step": compressed,
        "wire_ratio": (dense / compressed) if compressed else float("inf"),
        "dense_exchange_ms": dense / byte_rate * 1e3,
        "compressed_exchange_ms": compressed / byte_rate * 1e3,
    }
