"""Fused Adam/Nadam update — moment update + param step in ONE VMEM pass.

The plain path (nn/updaters.py + nn/multilayer._apply_updates) lowers one
Adam step to ~4 small elementwise HLOs PER PYTREE LEAF (m, v, step, the
param subtract), each reading and writing HBM separately; on models with
many small leaves the optimizer phase is launch- and bandwidth-bound, not
compute-bound.  This module flattens a layer's {params, grads, m, v} trees
into flat bucketed f32 buffers and applies the whole update — both moment
EMAs, bias corrections, the step, and the param subtract — in one pass:

  pallas    one VMEM-resident kernel over (rows, 128) tiles (TPU compiled,
            interpret-mode on CPU for tests)
  flat-jnp  the plain-jnp fallback over the same flat buffers (f64, other
            backends, tile-unfriendly sizes, or DL4J_TPU_FUSED_UPDATE_JNP=1
            — also the CPU A/B arm that isolates the flat-bucketing win
            from the kernel itself)

Seams mirror ops/lstm_kernel.py: opt-in env flag evaluated at TRACE time,
compiled/interpret/fallback split, and callers (nn/updaters.Adam.apply)
fall back to the per-leaf path whenever ``fused_apply`` returns None.

Bit-comparability contract (tests/test_update_kernel.py): the math is
the same f32 elementwise chain in the same per-element order — flatten/
concat/slice only change layout, and the pallas grid partitions the
flat buffer without reassociating anything.  The only permitted
divergence is XLA:CPU's layout-dependent FMA contraction of
``a*x + b*y`` terms (LLVM contracts or not depending on vector-lane
boundaries), which bit-identity over identical layouts confirms.  How
that jitter is bounded depends on the output: the moments see one
contractible FMA each, so they match the per-leaf path to <= 1 ulp;
the param step inherits a few-ulp RELATIVE wobble through the
sqrt/divide chain, which is a tiny ABSOLUTE error at lr scale (~1e-9
at lr=1e-3) but can read as hundreds of ulp of the subtracted output
wherever ``p - step`` cancels toward zero — so param parity is gated
on absolute difference, not ulp (scripts/fused_update_ab.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs import trace as obs_trace

#: opt-in, read once at import (the lstm_kernel.ENABLED pattern): set
#: BEFORE the first trace of a step — already-jitted executables keep
#: whichever path they were traced with.
ENABLED = os.environ.get("DL4J_TPU_FUSED_UPDATE", "0") == "1"
#: force the flat-jnp arm even where pallas is usable (A/B isolation).
FORCE_JNP = os.environ.get("DL4J_TPU_FUSED_UPDATE_JNP", "0") == "1"

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_LANES = 128
#: flat buffers are padded to a whole number of (8, 128) f32 tiles
_TILE = 8 * _LANES


def _update_math(kind: str, p, g, m, v, lr, bc1, bc2,
                 beta1: float, beta2: float, eps: float):
    """The single source of truth for the fused step (plain Adam/Nadam
    math from nn/updaters.py, plus the param subtract).  All operands
    f32; returns (p_new, m_new, v_new)."""
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    if kind == "nadam":
        m_hat = beta1 * (m_new / bc1) + (1 - beta1) * g / bc1
        step = lr * m_hat / (jnp.sqrt(v_new / bc2) + eps)
    else:
        step = lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    return p - step, m_new, v_new


def _kernel(p_ref, g_ref, m_ref, v_ref, sc_ref, p_out, m_out, v_out, *,
            kind: str, beta1: float, beta2: float, eps: float):
    lr = sc_ref[0]
    bc1 = sc_ref[1]
    bc2 = sc_ref[2]
    p_new, m_new, v_new = _update_math(
        kind, p_ref[...], g_ref[...], m_ref[...], v_ref[...],
        lr, bc1, bc2, beta1, beta2, eps)
    p_out[...] = p_new
    m_out[...] = m_new
    v_out[...] = v_new


def _use_pallas(n: int, leaves) -> bool:
    if not _HAS_PALLAS or FORCE_JNP:
        return False
    if jax.default_backend() not in ("tpu", "cpu"):
        return False
    if any(l.dtype == jnp.float64 for l in leaves):
        return False
    # below one tile the flat-jnp path is already a single fused HLO
    return n >= _TILE


def _pallas_flat(kind: str, flat_p, flat_g, flat_m, flat_v, scalars,
                 beta1: float, beta2: float, eps: float):
    """One kernel over the padded flat buffers; returns f32 flats
    (p_new, m_new, v_new) of the original length, or None when no viable
    row tiling exists (caller falls back to flat-jnp)."""
    n = flat_p.shape[0]
    pad = (-n) % _TILE
    rows = (n + pad) // _LANES

    bm = rows if rows <= 256 else 256
    while rows % bm:
        bm -= 1
    if bm < 8:   # degenerate tiles; caller falls back
        return None
    grid = (rows // bm,)

    def shape2(a):
        return jnp.pad(a, (0, pad)).reshape(rows, _LANES)

    spec = pl.BlockSpec((bm, _LANES), lambda b: (b, 0))
    out = pl.pallas_call(
        functools.partial(_kernel, kind=kind, beta1=beta1, beta2=beta2,
                          eps=eps),
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)] * 3,
        interpret=(jax.default_backend() == "cpu"),
    )(shape2(flat_p), shape2(flat_g), shape2(flat_m), shape2(flat_v),
      scalars)
    return tuple(o.reshape(-1)[:n] for o in out)


def kind_of(updater) -> Optional[str]:
    """"adam"/"nadam" for EXACT Adam/Nadam configs (subclasses like
    AdaMax/AMSGrad carry different math), else None."""
    from ..nn.updaters import Adam, Nadam

    if type(updater) is Nadam:
        return "nadam"
    if type(updater) is Adam:
        return "adam"
    return None


def fused_apply(kind: str, updater, params, grads, state, it):
    """The fused one-pass update over a layer's flat bucketed buffers.

    Returns ``(new_params, new_state)`` matching ``Updater.apply``'s
    contract bit-for-bit, or None when the fused path is unavailable
    (disabled, f64 anywhere, or empty trees) — the caller then runs the
    per-leaf plain path."""
    if not ENABLED:
        return None
    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = treedef.flatten_up_to(grads)
    m_leaves = treedef.flatten_up_to(state["m"])
    v_leaves = treedef.flatten_up_to(state["v"])
    if not p_leaves:
        return None
    every = p_leaves + g_leaves + m_leaves + v_leaves
    if any(jnp.asarray(l).dtype == jnp.float64 for l in every):
        return None   # exact-gradient-check configs stay on the plain path

    # same scalar prelude as the plain Adam.update (bit-comparable)
    lr = updater.lr_at(it)
    t = it.astype(jnp.float32) + 1.0
    bc1 = 1.0 - jnp.power(updater.beta1, t)
    bc2 = 1.0 - jnp.power(updater.beta2, t)

    def flat(leaves):
        return jnp.concatenate(
            [jnp.ravel(l).astype(jnp.float32) for l in leaves])

    flat_p, flat_g = flat(p_leaves), flat(g_leaves)
    flat_m, flat_v = flat(m_leaves), flat(v_leaves)
    n = flat_p.shape[0]

    out = None
    if _use_pallas(n, every):
        scalars = jnp.stack([lr.astype(jnp.float32), bc1, bc2])
        out = _pallas_flat(kind, flat_p, flat_g, flat_m, flat_v, scalars,
                           updater.beta1, updater.beta2, updater.eps)
    if out is None:   # flat-jnp fallback: same math, one fused flat pass
        out = _update_math(kind, flat_p, flat_g, flat_m, flat_v,
                           lr, bc1, bc2,
                           updater.beta1, updater.beta2, updater.eps)
    new_p_flat, new_m_flat, new_v_flat = out

    def unflat(flat_buf, like_leaves):
        leaves, off = [], 0
        for l in like_leaves:
            size = l.size
            leaves.append(flat_buf[off:off + size]
                          .reshape(l.shape).astype(l.dtype))
            off += size
        return treedef.unflatten(leaves)

    new_params = unflat(new_p_flat, p_leaves)
    new_state = {"m": unflat(new_m_flat, m_leaves),
                 "v": unflat(new_v_flat, v_leaves)}
    return new_params, new_state


def jit_apply(updater):
    """Standalone jitted optimizer-update program: ``run(params, grads,
    state, it) -> (new_params, new_state)`` with each dispatch wrapped in
    the ``train/update`` span (docs/OBSERVABILITY.md taxonomy) — the
    dispatch-level harness the fused-update A/B
    (scripts/fused_update_ab.py) and scripts/step_breakdown.py time."""
    fn = jax.jit(lambda p, g, s, it: updater.apply(p, g, s, it))

    def run(params, grads, state, it) -> Tuple:
        with obs_trace.span("train/update", cat="train"):
            return fn(params, grads, state, it)

    run.jitted = fn
    return run
