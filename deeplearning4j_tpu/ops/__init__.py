"""Tensor substrate (L0): the seam where ND4J is replaced by jax.numpy/XLA.

Reference boundary: every DL4J op crosses `Nd4j.getExecutioner().exec(...)`
into libnd4j C++/CUDA (SURVEY.md §1 L0).  Here the substrate is jax.numpy;
ops are traced and fused by XLA rather than dispatched eagerly.
"""

from .dtypes import DTypePolicy, default_policy, canonical_dtype
from .activations import Activation, get_activation
from .initializers import WeightInit, init_weight
from .losses import Loss, get_loss
from .compression import (
    GradBucketer, bitmap_decode, bitmap_encode, compressed_pmean,
    compression_stats, threshold_decode, threshold_encode,
)
