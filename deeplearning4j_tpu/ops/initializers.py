"""Weight-init schemes — parity with DL4J's ``WeightInit`` enum.

Reference: nn/weights/WeightInit.java + WeightInitUtil.java (scheme math).
Fan-in/fan-out follow the reference convention: for a dense kernel
``[n_in, n_out]`` fan_in = n_in, fan_out = n_out; for conv kernels fan
includes the receptive-field size.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


class WeightInit:
    ZERO = "zero"
    ONES = "ones"
    NORMAL = "normal"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    IDENTITY = "identity"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    VAR_SCALING_UNIFORM_FAN_IN = "var_scaling_uniform_fan_in"
    VAR_SCALING_UNIFORM_FAN_OUT = "var_scaling_uniform_fan_out"
    VAR_SCALING_UNIFORM_FAN_AVG = "var_scaling_uniform_fan_avg"


def init_weight(
    rng: jax.Array,
    shape: Sequence[int],
    scheme: str,
    fan_in: float,
    fan_out: float,
    dtype=jnp.float32,
) -> Array:
    """Sample a weight tensor per the named scheme.

    Scheme formulas mirror reference WeightInitUtil (e.g. XAVIER =
    N(0, 2/(fan_in+fan_out)); RELU = N(0, 2/fan_in)).
    """
    scheme = scheme.lower()
    shape = tuple(int(s) for s in shape)
    fi, fo = max(fan_in, 1.0), max(fan_out, 1.0)

    def normal(std):
        return (jax.random.normal(rng, shape, dtype=jnp.float32) * std).astype(dtype)

    def uniform(limit):
        return jax.random.uniform(
            rng, shape, minval=-limit, maxval=limit, dtype=jnp.float32
        ).astype(dtype)

    if scheme == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if scheme == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if scheme == WeightInit.NORMAL:
        return normal(1.0 / math.sqrt(fi))
    if scheme == WeightInit.UNIFORM:
        return uniform(1.0 / math.sqrt(fi))
    if scheme == WeightInit.XAVIER:
        return normal(math.sqrt(2.0 / (fi + fo)))
    if scheme == WeightInit.XAVIER_UNIFORM:
        return uniform(math.sqrt(6.0 / (fi + fo)))
    if scheme == WeightInit.XAVIER_FAN_IN:
        return normal(math.sqrt(1.0 / fi))
    if scheme == WeightInit.RELU:
        return normal(math.sqrt(2.0 / fi))
    if scheme == WeightInit.RELU_UNIFORM:
        return uniform(math.sqrt(6.0 / fi))
    if scheme == WeightInit.SIGMOID_UNIFORM:
        return uniform(4.0 * math.sqrt(6.0 / (fi + fo)))
    if scheme == WeightInit.LECUN_NORMAL:
        return normal(math.sqrt(1.0 / fi))
    if scheme == WeightInit.LECUN_UNIFORM:
        return uniform(math.sqrt(3.0 / fi))
    if scheme == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError(f"IDENTITY init needs a square 2-D shape, got {shape}")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme.startswith("var_scaling"):
        fan = {"fan_in": fi, "fan_out": fo, "fan_avg": (fi + fo) / 2.0}[
            scheme.rsplit("_", 2)[-2] + "_" + scheme.rsplit("_", 2)[-1]
        ]
        if "normal" in scheme:
            return normal(math.sqrt(1.0 / fan))
        return uniform(math.sqrt(3.0 / fan))
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
