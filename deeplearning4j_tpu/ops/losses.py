"""Loss functions — parity with ND4J ``ILossFunction`` implementations.

Reference: DL4J output layers hold an ``ILossFunction`` (LossFunctions enum:
MCXENT, XENT, MSE, L1, L2, NEGATIVELOGLIKELIHOOD, HINGE, SQUARED_HINGE,
KL_DIVERGENCE, POISSON, COSINE_PROXIMITY, MEAN_ABSOLUTE_PERCENTAGE_ERROR,
MEAN_SQUARED_LOGARITHMIC_ERROR) whose ``computeGradient`` is hand-written.
Here losses are pure functions of (labels, pre-activation output); gradients
come from autodiff.  Softmax+MCXENT and sigmoid+XENT are computed in fused,
numerically-stable log-space form — the reference relies on clipping
(LossUtil) instead.

Conventions (match the reference):
  - per-example score = sum of per-element loss over feature axes
  - network score = mean per-example score over the (masked) minibatch
  - binary losses expect labels in {0,1}; hinge expects {-1,+1} internally
    but accepts {0,1} and maps them (as LossHinge does).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .activations import get_activation

Array = jax.Array
_EPS = 1e-7


def _activated(preout: Array, activation) -> Array:
    return get_activation(activation)(preout)


class Loss:
    """A loss = per-element function + reduction, with optional fused paths.

    ``per_example(labels, preout, activation, mask)`` returns a [batch] (or
    [batch, time]) array of per-example scores; ``__call__`` reduces to the
    mean scalar the way MultiLayerNetwork.score() does (reference
    nn/multilayer/MultiLayerNetwork.java score accumulation).
    """

    def __init__(self, name: str, elementwise: Callable[[Array, Array], Array],
                 feature_mean: bool = False):
        self.name = name
        self._elementwise = elementwise
        # reference: LossMSE = LossL2 / nOut, LossMAE = LossL1 / nOut
        # (per-example score averaged, not summed, over output columns)
        self._feature_mean = feature_mean

    def per_element(self, labels: Array, preout: Array, activation="identity") -> Array:
        if (jnp.issubdtype(labels.dtype, jnp.integer)
                and labels.ndim == preout.ndim - 1):
            # sparse class-index labels (the TPU-native data path: the host
            # ships 4-byte ids, the device materializes the one-hot) —
            # numerically identical to dense one-hot labels
            labels = jax.nn.one_hot(labels, preout.shape[-1], dtype=preout.dtype)
        if self.name in ("mcxent", "negativeloglikelihood") and _act_name(activation) == "softmax":
            logp = jax.nn.log_softmax(preout, axis=-1)
            return -labels * logp
        if self.name == "xent" and _act_name(activation) == "sigmoid":
            # stable sigmoid BCE from logits
            z, y = preout, labels
            return jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        out = _activated(preout, activation)
        return self._elementwise(labels, out)

    def per_example(
        self,
        labels: Array,
        preout: Array,
        activation="identity",
        mask: Optional[Array] = None,
    ) -> Array:
        el = self.per_element(labels, preout, activation)
        if mask is not None:
            el = el * _broadcast_mask(mask, el.shape)
        s = jnp.sum(el, axis=-1)
        if self._feature_mean:
            s = s / el.shape[-1]
        return s

    def __call__(
        self,
        labels: Array,
        preout: Array,
        activation="identity",
        mask: Optional[Array] = None,
    ) -> Array:
        """Reduce to the network score.  Mask shapes supported (reference
        ILossFunction computeScore + MaskedReductionUtil semantics):
          - mask.shape == per-example shape ([mb] or [mb, t]): average over
            present entries only (per-timestep / per-example masking)
          - mask.shape == labels.shape: per-output weighting; average over
            entries with any unmasked output
        """
        pe = self.per_example(labels, preout, activation, mask)
        if mask is not None:
            if mask.shape == pe.shape:
                present = mask
            elif mask.shape == labels.shape:
                present = (jnp.max(mask, axis=-1) > 0).astype(pe.dtype)
            else:  # broadcastable per-example mask, e.g. [mb, 1]
                present = jnp.broadcast_to(mask.reshape(mask.shape[: pe.ndim]), pe.shape)
            return jnp.sum(pe) / jnp.maximum(jnp.sum(present), 1.0)
        return jnp.mean(pe)


def _act_name(activation) -> str:
    return activation if isinstance(activation, str) else getattr(activation, "__name__", "")


def _broadcast_mask(mask: Array, shape) -> Array:
    m = mask
    while m.ndim < len(shape):
        m = m[..., None]
    return jnp.broadcast_to(m, shape)


def _mse(y, out):
    d = out - y
    return d * d


def _l2(y, out):
    d = out - y
    return d * d


def _l1(y, out):
    return jnp.abs(out - y)


def _mae(y, out):
    return jnp.abs(out - y)


def _xent(y, out):
    out = jnp.clip(out, _EPS, 1.0 - _EPS)
    return -(y * jnp.log(out) + (1.0 - y) * jnp.log1p(-out))


def _mcxent(y, out):
    return -y * jnp.log(jnp.clip(out, _EPS, 1.0))


def _hinge(y, out):
    yy = jnp.where(y > 0.5, 1.0, -1.0)
    return jnp.maximum(0.0, 1.0 - yy * out)


def _squared_hinge(y, out):
    yy = jnp.where(y > 0.5, 1.0, -1.0)
    h = jnp.maximum(0.0, 1.0 - yy * out)
    return h * h


def _kld(y, out):
    yc = jnp.clip(y, _EPS, 1.0)
    oc = jnp.clip(out, _EPS, 1.0)
    return yc * (jnp.log(yc) - jnp.log(oc))


def _poisson(y, out):
    return out - y * jnp.log(jnp.clip(out, _EPS, None))


def _cosine_proximity(y, out):
    # summed over the feature axis downstream; spread the scalar across elements
    yn = y / jnp.clip(jnp.linalg.norm(y, axis=-1, keepdims=True), _EPS)
    on = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), _EPS)
    return -(yn * on)


def _mape(y, out):
    return 100.0 * jnp.abs((y - out) / jnp.clip(jnp.abs(y), _EPS))


def _msle(y, out):
    d = jnp.log1p(jnp.clip(out, -1 + _EPS, None)) - jnp.log1p(jnp.clip(y, -1 + _EPS, None))
    return d * d


_REGISTRY = {
    "mse": Loss("mse", _mse, feature_mean=True),
    "l2": Loss("l2", _l2),
    "l1": Loss("l1", _l1),
    "mae": Loss("mae", _mae, feature_mean=True),
    "xent": Loss("xent", _xent),
    "mcxent": Loss("mcxent", _mcxent),
    "negativeloglikelihood": Loss("negativeloglikelihood", _mcxent),
    "hinge": Loss("hinge", _hinge),
    "squared_hinge": Loss("squared_hinge", _squared_hinge),
    "kl_divergence": Loss("kl_divergence", _kld),
    "poisson": Loss("poisson", _poisson),
    "cosine_proximity": Loss("cosine_proximity", _cosine_proximity),
    "mape": Loss("mape", _mape),
    "msle": Loss("msle", _msle),
}


def get_loss(name) -> Loss:
    if isinstance(name, Loss):
        return name
    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def loss_names() -> list[str]:
    return sorted(_REGISTRY)


def summed_per_example(loss_name, labels, preout, activation="identity",
                       mask=None) -> Array:
    """[mb] per-example scores: elementwise loss summed over features AND
    any trailing time axis — the single reference-scoreExamples reduction
    the output layers' score_examples methods share."""
    pe = get_loss(loss_name).per_example(labels, preout,
                                         activation or "identity", mask)
    return pe.sum(axis=tuple(range(1, pe.ndim)))


# ---------------------------------------------------------------------------
# fused sparse softmax cross-entropy (large-vocab LM loss)
# ---------------------------------------------------------------------------


@jax.custom_vjp
def sparse_softmax_xent(logits: Array, targets: Array) -> Array:
    """Mean token NLL for integer targets WITHOUT materializing the f32
    log-softmax over the vocab.

    ``logits`` [..., V] (any float dtype, typically bf16), ``targets``
    [...] int.  A naive ``log_softmax(logits.astype(f32))`` writes an f32
    [..., V] tensor plus its gradient — at GPT-2 vocab (50K) that is the
    single largest HBM stream in the train step.  Here the forward keeps
    only per-row (max, log-sum-exp) f32 statistics (fused by XLA into
    streaming reductions over the bf16 logits) and the backward rebuilds
    ``softmax − onehot`` in the logits dtype from the saved lse — ~2.5×
    less loss-region traffic, measured on the TransformerLM bench
    (docs/transformer_profile.md).  No reference analog (DL4J's LossMCXENT
    densifies labels; its vocab-scale path is sampled hierarchical
    softmax).
    """
    nll, _ = _sparse_xent_fwd(logits, targets)
    return nll


def _sparse_xent_fwd(logits, targets):
    lmax = jnp.max(logits, axis=-1)                       # [...] in dtype
    shifted = logits - lmax[..., None]
    sumexp = jnp.sum(jnp.exp(shifted.astype(jnp.float32)), axis=-1)
    lse = lmax.astype(jnp.float32) + jnp.log(sumexp)      # [..., ] f32
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = jnp.mean(lse - tgt.astype(jnp.float32))
    return nll, (logits, targets, lse)


def _sparse_xent_bwd(res, g):
    logits, targets, lse = res
    n = lse.size  # mean over all token positions
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32)
    dlogits = ((p - onehot) * (g / n)).astype(logits.dtype)
    return dlogits, None


sparse_softmax_xent.defvjp(_sparse_xent_fwd, _sparse_xent_bwd)
