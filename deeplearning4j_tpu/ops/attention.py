"""Multi-head attention — XLA reference path + fused flash (pallas) kernel.

The reference (DL4J 0.9.2) has NO attention layer at all (SURVEY.md §5
"Long-context": closest analogs are TBPTT + mask propagation).  Long-context
support is therefore designed TPU-first per SURVEY §7-M5:

  - ``mha``: plain XLA einsum-softmax-einsum attention (the semantics
    oracle; XLA fuses it well at moderate sequence lengths).
  - ``flash_mha``: blockwise streaming-softmax attention as a pallas TPU
    kernel — O(T) memory instead of O(T²), tiles sized for the MXU, f32
    accumulation.  Falls back to ``mha`` when shapes don't tile.
  - ``ring_attention`` (parallel/ring.py) reuses the same blockwise update
    rule across devices over the ``seq`` mesh axis.

Layout convention: [batch, heads, seq, head_dim] (BHTD).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.jax_compat import vma_of

Array = jax.Array

_NEG_INF = -1e30  # large-finite: keeps padded/causal-masked rows NaN-free

try:  # pallas ships in all jax wheels; guard anyway so mha still works
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


# ---------------------------------------------------------------------------
# shared layout/masking helpers
# ---------------------------------------------------------------------------


def causal_bias(tq: int, tk: int, q_off=0, k_off=0) -> Array:
    """Additive causal bias [tq, tk]: 0 where global q index ≥ global k
    index, large-negative otherwise.  Offsets may be traced values (ring
    attention passes per-device block offsets).  The single source of the
    causal-mask convention for mha / flash kernel / flash bwd / ring."""
    qi = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + q_off
    ki = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1) + k_off
    return jnp.where(qi >= ki, 0.0, _NEG_INF).astype(jnp.float32)


def split_heads(x: Array, n_heads: int) -> Array:
    """[B, T, H*D] → [B, H, T, D] (the framework's head-layout convention)."""
    b, t, dm = x.shape
    return x.reshape(b, t, n_heads, dm // n_heads).transpose(0, 2, 1, 3)


def merge_heads(x: Array) -> Array:
    """[B, H, T, D] → [B, T, H*D]."""
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


# ---------------------------------------------------------------------------
# XLA reference path
# ---------------------------------------------------------------------------


def mha(q: Array, k: Array, v: Array, *, causal: bool = False,
        mask: Optional[Array] = None, scale: Optional[float] = None) -> Array:
    """Plain attention: softmax(q·kᵀ/√d (+mask)) · v.

    q [B,H,T,D], k/v [B,H,S,D]; mask broadcastable to [B,H,T,S] with 1 =
    attend, 0 = blocked (DL4J mask convention).  Returns [B,H,T,D].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        scores = scores + causal_bias(scores.shape[-2], scores.shape[-1])
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, _NEG_INF)
    # accumulate the softmax in ≥f32 (bf16 inputs promote; f64 stays f64
    # so the float64 gradient-check suite is meaningful)
    acc_dtype = jnp.promote_types(scores.dtype, jnp.float32)
    p = jax.nn.softmax(scores.astype(acc_dtype), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# blockwise streaming-softmax update (shared by flash kernel + ring attention)
# ---------------------------------------------------------------------------


def blockwise_update(acc, m, l, q, k, v, scale, bias=None):
    """One online-softmax accumulation step (Milakov & Gimelshein / Flash).

    acc [T,D] f32 un-normalized output, m [T,1] running max, l [T,1] running
    denominator.  Processes the (q, k-block) score tile and returns updated
    (acc, m, l).  Used on-chip by the pallas kernel and across chips by ring
    attention — one math, two transports.

    Matmul operands stay in the INPUT dtype (bf16 inputs → native-rate MXU
    passes; f32 casts would triple every matmul's MXU time) while both
    matmuls accumulate in f32 via preferred_element_type and all softmax
    statistics are f32 — the standard flash precision contract.  ``p`` is
    cast to v's dtype for the second matmul (identity for f32 inputs, so
    the f32 parity/gradient-check suites see unchanged numerics).
    """
    if q.dtype == jnp.float64:
        # f64 callers (ring-attention grad checks) run the matmuls at f32
        # with f32 statistics — the historical semantics of this function
        # (the fused-kernel path excludes f64 entirely, _kernel_eligible)
        q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    nt = (((1,), (1,)), ((), ()))  # contract head_dim of both, no transpose
    s = jax.lax.dot_general(q, k, nt,
                            preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                                     # [T, S_blk]
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.dot(p.astype(v.dtype), v,
                                         preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


# ---------------------------------------------------------------------------
# flash attention pallas kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int):
    """Grid (BH, nQ, nK), k innermost — TPU grids run sequentially, so the
    running (acc, m, l) stats live in VMEM scratch across k-steps.  Also
    emits the log-sum-exp per query row (the residual the fused backward
    kernels need to rebuild p without a second online-softmax pass).
    ``km_ref`` is the optional [1, block_k] key-padding mask (1 = attend)."""
    kb = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qb = pl.program_id(1)
    bias = None
    if causal:
        bias = causal_bias(block_q, block_k, qb * block_q, kb * block_k)
    if km_ref is not None:
        kbias = jnp.where(km_ref[0, 0] != 0, 0.0, _NEG_INF).astype(jnp.float32)
        bias = kbias[None, :] if bias is None else bias + kbias[None, :]

    def _step():
        acc, m, l = blockwise_update(
            acc_ref[:], m_ref[:], l_ref[:],
            q_ref[0], k_ref[0], v_ref[0], scale, bias)
        acc_ref[:] = acc
        m_ref[:] = m
        l_ref[:] = l

    if causal:
        # whole tile above the diagonal → skip (saves ~half the FLOPs)
        @pl.when(qb * block_q + block_q - 1 >= kb * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(kb == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # Rows that never saw a live key (m still at the −LARGE init; note
        # l is NOT 0 there — every masked score is exactly −LARGE after
        # f32 absorption, so p=1 per entry and l=S) take lse = +LARGE: the
        # backward's p = exp(s − lse) then reconstructs to 0, i.e. flash's
        # convention is ZERO gradients for fully-masked rows (see
        # _xla_attention_bwd for the rationale and the mha difference).
        lse = jnp.where(m_ref[:] > _NEG_INF / 2,
                        m_ref[:] + jnp.log(l_safe), 1e30)
        lse_ref[0, 0] = lse[:, 0].astype(lse_ref.dtype)


def _pick_block(n: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return 0


def _vma(x):
    """Varying-across-mesh-axes of ``x`` (frozenset; empty outside
    shard_map) — pallas out_shapes must carry it so the kernels trace
    under shard_map's check_vma (ulysses/pipelined attention)."""
    return vma_of(x)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s vma where the jax version
    types it (pre-vma jax has no ``vma=`` kwarg and needs none)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=_vma(like))
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _kernel_eligible(q, block_q: int, block_k: int) -> bool:
    """The kernel targets the TPU memory spaces; run it compiled on tpu,
    interpreted on cpu (tests), and fall back to plain XLA elsewhere (gpu).
    f64 also falls back: the kernel accumulates in f32 VMEM scratch, which
    would silently degrade float64 gradient checks.

    CPU + varying-across-mesh operands (inside shard_map) also fall back:
    jax 0.9's pallas HLO *interpreter* emits invariant slice indices
    against the varying operand, which shard_map's check_vma rightly
    rejects — the compiled TPU kernel carries vma through its out_shapes
    and passes the check, so only the interpreter needs the escape."""
    backend = jax.default_backend()
    if backend == "cpu" and _vma(q):
        return False
    return (_HAS_PALLAS and block_q > 0 and block_k > 0
            and backend in ("tpu", "cpu") and q.dtype != jnp.float64)


def _flash_forward(q: Array, k: Array, v: Array, kmask, causal: bool,
                   scale: float):
    """→ (o [B,H,T,D], lse [B*H,T] or None-on-fallback)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    block_q = _pick_block(T)
    block_k = _pick_block(S)
    if not _kernel_eligible(q, block_q, block_k):
        m = None if kmask is None else kmask[:, None, None, :]
        return mha(q, k, v, causal=causal, mask=m, scale=scale), None

    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    grid = (B * H, T // block_q, S // block_k)
    base = functools.partial(_flash_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k)
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    if kmask is not None:
        # [B,1,S] row blocks (TPU pallas wants the last two block dims
        # (8,128)-aligned or equal to the array's); batch = flat_bh // H
        in_specs.append(pl.BlockSpec((1, 1, block_k),
                                     lambda b, i, j, H=H: (b // H, 0, j)))
        args.append(kmask.astype(jnp.int32)[:, None, :])
        kernel = base
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s):
            base(q_ref, k_ref, v_ref, None, o_ref, lse_ref, acc, m_s, l_s)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            _out_struct((B * H, T, D), q.dtype, q),
            _out_struct((B * H, 1, T), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=(jax.default_backend() == "cpu"),
    )(*args)
    return out.reshape(B, H, T, D), lse


# ---------------------------------------------------------------------------
# fused backward kernels (FlashAttention-2 style, O(T) memory)
# ---------------------------------------------------------------------------


def _bwd_tile(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, km_ref,
              qi, ki, *, scale, causal, block_q, block_k):
    """Shared tile math for both backward kernels: rebuild p from the saved
    lse and form ds — ONE definition so the masking/lse conventions cannot
    desynchronize between dq and dk/dv.

    Masking is where()-style to match the XLA oracle: no gradient flows
    through blocked score entries (ds hard-zeroed there).  Fully-masked
    rows carry the lse=+LARGE sentinel from the forward, so p — and with
    it every gradient — is exactly 0 for them.
    Returns (qb, kb, vb, gb, p, ds); operands keep the input dtype (native
    MXU rate for bf16 — see blockwise_update), p/ds are f32 stats."""
    nt = (((1,), (1,)), ((), ()))      # contract head_dim, no transposes
    qb = q_ref[0]                                   # [bq, D]
    kb = k_ref[0]                                   # [bk, D]
    vb = v_ref[0]
    gb = g_ref[0]
    s = jax.lax.dot_general(qb, kb, nt,
                            preferred_element_type=jnp.float32) * scale
    bias = jnp.zeros((block_q, block_k), jnp.float32)
    if causal:
        bias = bias + causal_bias(block_q, block_k,
                                  qi * block_q, ki * block_k)
    if km_ref is not None:
        bias = bias + jnp.where(km_ref[0, 0] != 0, 0.0,
                                _NEG_INF).astype(jnp.float32)[None, :]
    p = jnp.exp(s + bias - lse_ref[0, 0][:, None])  # [bq, bk]
    dp = jax.lax.dot_general(gb, vb, nt, preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0, 0][:, None]) * scale
    ds = ds * (bias > _NEG_INF / 2).astype(jnp.float32)
    return qb, kb, vb, gb, p, ds


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                           km_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                           *, scale, causal, block_q, block_k):
    """Grid (BH, nK, nQ), q innermost; dk/dv accumulate in VMEM scratch.
    p is rebuilt per tile from the saved lse — no [T,S] materialization."""
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step():
        qb, _, _, gb, p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, km_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        ct = (((0,), (0,)), ((), ()))  # contract the q-row dim of both
        dv_acc[:] += jax.lax.dot_general(
            p.astype(gb.dtype), gb, ct, preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(qb.dtype), qb, ct, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         km_ref, dq_ref, dq_acc,
                         *, scale, causal, block_q, block_k):
    """Grid (BH, nQ, nK), k innermost; dq accumulates in VMEM scratch."""
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _step():
        _, kb, _, _, _, ds = _bwd_tile(
            q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, km_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k)
        dq_acc[:] += jnp.dot(ds.astype(kb.dtype), kb,
                             preferred_element_type=jnp.float32)

    if causal:
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_backward(q, k, v, kmask, o, lse, g, causal, scale):
    """Fused O(T)-memory backward: rebuild p per tile from lse.  Falls back
    to the XLA recompute path when the forward did (lse is None)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    block_q = _pick_block(T)
    block_k = _pick_block(S)
    if lse is None or not _kernel_eligible(q, block_q, block_k):
        return _xla_attention_bwd(q, k, v, kmask, g, causal, scale)

    flat = lambda x: x.reshape(B * H, *x.shape[2:])
    qf, kf, vf, gf = flat(q), flat(k), flat(v), flat(g)
    # delta_i = Σ_d g_i·o_i — the softmax-jacobian row term (Dao 2023 eq. 4)
    delta = jnp.sum(gf.astype(jnp.float32) * flat(o).astype(jnp.float32),
                    axis=-1)[:, None, :]                       # [BH, 1, T]
    interp = jax.default_backend() == "cpu"

    q_spec_i = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0))
    k_spec_o = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    row_spec_i = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, j))
    if kmask is not None:
        kmi = kmask.astype(jnp.int32)[:, None, :]

    # dk/dv: grid (BH, nK, nQ)
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    base_kv = functools.partial(_flash_bwd_dkdv_kernel, **kw)
    specs_kv = [q_spec_i, k_spec_o,
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0)),
                row_spec_i, row_spec_i]
    args_kv = [qf, kf, vf, gf, lse, delta]
    if kmask is not None:
        specs_kv.append(pl.BlockSpec((1, 1, block_k),
                                     lambda b, i, j, H=H: (b // H, 0, i)))
        args_kv.append(kmi)
        kernel_kv = base_kv
    else:
        def kernel_kv(q_r, k_r, v_r, g_r, l_r, d_r, dk_r, dv_r, dka, dva):
            base_kv(q_r, k_r, v_r, g_r, l_r, d_r, None, dk_r, dv_r, dka, dva)
    dk, dv = pl.pallas_call(
        kernel_kv,
        grid=(B * H, S // block_k, T // block_q),
        in_specs=specs_kv,
        out_specs=[pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))],
        out_shape=[_out_struct((B * H, S, D), k.dtype, k),
                   _out_struct((B * H, S, D), v.dtype, v)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interp,
    )(*args_kv)

    # dq: grid (BH, nQ, nK)
    base_q = functools.partial(_flash_bwd_dq_kernel, **kw)
    specs_q = [pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
               pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
               pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
               pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
               pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
               pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))]
    args_q = [qf, kf, vf, gf, lse, delta]
    if kmask is not None:
        specs_q.append(pl.BlockSpec((1, 1, block_k),
                                    lambda b, i, j, H=H: (b // H, 0, j)))
        args_q.append(kmi)
        kernel_q = base_q
    else:
        def kernel_q(q_r, k_r, v_r, g_r, l_r, d_r, dq_r, dqa):
            base_q(q_r, k_r, v_r, g_r, l_r, d_r, None, dq_r, dqa)
    dq = pl.pallas_call(
        kernel_q,
        grid=(B * H, T // block_q, S // block_k),
        in_specs=specs_q,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((B * H, T, D), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interp,
    )(*args_q)

    unflat = lambda x: x.reshape(B, H, *x.shape[1:])
    return unflat(dq), unflat(dk), unflat(dv)


def _xla_attention_bwd(q, k, v, kmask, g, causal, scale):
    """XLA recompute backward (O(T²) memory) — the fallback for shapes the
    kernels don't tile and for f64 gradient checks."""
    # accumulate in f32 for low-precision inputs, but keep f64 at f64 so the
    # float64 gradient-check suite stays meaningful (matches mha's contract)
    acc = jnp.float64 if q.dtype == jnp.float64 else jnp.float32
    qf, kf, vf = (x.astype(acc) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        s = s + causal_bias(s.shape[-2], s.shape[-1])
    if kmask is not None:
        s = jnp.where(kmask[:, None, None, :].astype(bool), s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if causal or kmask is not None:
        # zero-grad convention for rows with NO live key (matches the
        # kernel path's lse sentinel): their p degenerates to uniform,
        # which would leak a dv contribution from rows whose output is
        # garbage-by-convention.  (mha's autodiff leaks that dv; the
        # flash contract documents the difference.)
        p = p * jnp.any(s > _NEG_INF / 2, axis=-1, keepdims=True)
    gf = g.astype(acc)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    if causal or kmask is not None:
        # where()-style masking: no score gradient through blocked entries
        ds = jnp.where(s > _NEG_INF / 2, ds, 0.0)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_mha_p(q: Array, k: Array, v: Array, kmask, causal: bool,
                 scale: float) -> Array:
    return _flash_forward(q, k, v, kmask, causal, scale)[0]


def _flash_fwd(q, k, v, kmask, causal, scale):
    o, lse = _flash_forward(q, k, v, kmask, causal, scale)
    return o, (q, k, v, kmask, o, lse)


def _flash_bwd(causal, scale, res, g):
    q, k, v, kmask, o, lse = res
    dq, dk, dv = _flash_backward(q, k, v, kmask, o, lse, g, causal, scale)
    return dq, dk, dv, None  # mask carries no gradient


_flash_mha_p.defvjp(_flash_fwd, _flash_bwd)


def flash_mha(q: Array, k: Array, v: Array, causal: bool = False,
              scale: Optional[float] = None,
              kmask: Optional[Array] = None) -> Array:
    """Fused blockwise attention — pallas TPU kernels, O(T) memory in BOTH
    directions (forward: online softmax; backward: per-tile p rebuilt from
    the saved log-sum-exp, FlashAttention-2 style).

    ``kmask`` [B, S] (1 = attend) supports DL4J-style variable-length
    padding without leaving the kernel.  Shapes that don't tile, f64, and
    non-TPU/CPU backends fall back to XLA with identical semantics — with
    one documented exception: query rows whose EVERY key is masked get
    ZERO gradients here (both paths), where ``mha``'s autodiff leaks a
    uniform-p dv contribution from them.  Such rows' outputs are
    garbage-by-convention in both (the attention layer zeroes them via the
    output mask, under which the two are gradient-identical — see
    tests/test_attention.py::test_fully_masked_rows_*).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_mha_p(q, k, v, kmask, causal, scale)
