"""Multi-head attention — XLA reference path + fused flash (pallas) kernel.

The reference (DL4J 0.9.2) has NO attention layer at all (SURVEY.md §5
"Long-context": closest analogs are TBPTT + mask propagation).  Long-context
support is therefore designed TPU-first per SURVEY §7-M5:

  - ``mha``: plain XLA einsum-softmax-einsum attention (the semantics
    oracle; XLA fuses it well at moderate sequence lengths).
  - ``flash_mha``: blockwise streaming-softmax attention as a pallas TPU
    kernel — O(T) memory instead of O(T²), tiles sized for the MXU, f32
    accumulation.  Falls back to ``mha`` when shapes don't tile.
  - ``ring_attention`` (parallel/ring.py) reuses the same blockwise update
    rule across devices over the ``seq`` mesh axis.

Layout convention: [batch, heads, seq, head_dim] (BHTD).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_NEG_INF = -1e30  # large-finite: keeps padded/causal-masked rows NaN-free

try:  # pallas ships in all jax wheels; guard anyway so mha still works
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


# ---------------------------------------------------------------------------
# shared layout/masking helpers
# ---------------------------------------------------------------------------


def causal_bias(tq: int, tk: int, q_off=0, k_off=0) -> Array:
    """Additive causal bias [tq, tk]: 0 where global q index ≥ global k
    index, large-negative otherwise.  Offsets may be traced values (ring
    attention passes per-device block offsets).  The single source of the
    causal-mask convention for mha / flash kernel / flash bwd / ring."""
    qi = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0) + q_off
    ki = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1) + k_off
    return jnp.where(qi >= ki, 0.0, _NEG_INF).astype(jnp.float32)


def split_heads(x: Array, n_heads: int) -> Array:
    """[B, T, H*D] → [B, H, T, D] (the framework's head-layout convention)."""
    b, t, dm = x.shape
    return x.reshape(b, t, n_heads, dm // n_heads).transpose(0, 2, 1, 3)


def merge_heads(x: Array) -> Array:
    """[B, H, T, D] → [B, T, H*D]."""
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


# ---------------------------------------------------------------------------
# XLA reference path
# ---------------------------------------------------------------------------


def mha(q: Array, k: Array, v: Array, *, causal: bool = False,
        mask: Optional[Array] = None, scale: Optional[float] = None) -> Array:
    """Plain attention: softmax(q·kᵀ/√d (+mask)) · v.

    q [B,H,T,D], k/v [B,H,S,D]; mask broadcastable to [B,H,T,S] with 1 =
    attend, 0 = blocked (DL4J mask convention).  Returns [B,H,T,D].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        scores = scores + causal_bias(scores.shape[-2], scores.shape[-1])
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, _NEG_INF)
    # accumulate the softmax in ≥f32 (bf16 inputs promote; f64 stays f64
    # so the float64 gradient-check suite is meaningful)
    acc_dtype = jnp.promote_types(scores.dtype, jnp.float32)
    p = jax.nn.softmax(scores.astype(acc_dtype), axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# blockwise streaming-softmax update (shared by flash kernel + ring attention)
# ---------------------------------------------------------------------------


def blockwise_update(acc, m, l, q, k, v, scale, bias=None):
    """One online-softmax accumulation step (Milakov & Gimelshein / Flash).

    acc [T,D] f32 un-normalized output, m [T,1] running max, l [T,1] running
    denominator.  Processes the (q, k-block) score tile and returns updated
    (acc, m, l).  Used on-chip by the pallas kernel and across chips by ring
    attention — one math, two transports.
    """
    s = jnp.dot(q.astype(jnp.float32), k.astype(jnp.float32).T,
                preferred_element_type=jnp.float32) * scale   # [T, S_blk]
    if bias is not None:
        s = s + bias
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                                     # [T, S_blk]
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.dot(p, v.astype(jnp.float32),
                                         preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


# ---------------------------------------------------------------------------
# flash attention pallas kernel
# ---------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int):
    """Grid (BH, nQ, nK), k innermost — TPU grids run sequentially, so the
    running (acc, m, l) stats live in VMEM scratch across k-steps."""
    kb = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qb = pl.program_id(1)
    bias = None
    if causal:
        bias = causal_bias(block_q, block_k, qb * block_q, kb * block_k)

    def _step():
        acc, m, l = blockwise_update(
            acc_ref[:], m_ref[:], l_ref[:],
            q_ref[0], k_ref[0], v_ref[0], scale, bias)
        acc_ref[:] = acc
        m_ref[:] = m
        l_ref[:] = l

    if causal:
        # whole tile above the diagonal → skip (saves ~half the FLOPs)
        @pl.when(qb * block_q + block_q - 1 >= kb * block_k)
        def _():
            _step()
    else:
        _step()

    @pl.when(kb == n_k - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _pick_block(n: int, preferred: int = 128) -> int:
    for b in (preferred, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return 0


def _flash_forward(q: Array, k: Array, v: Array, causal: bool,
                   scale: float) -> Array:
    B, H, T, D = q.shape
    S = k.shape[2]
    block_q = _pick_block(T)
    block_k = _pick_block(S)
    # the kernel targets the TPU memory spaces; run it compiled on tpu,
    # interpreted on cpu (tests), and fall back to plain XLA elsewhere (gpu).
    # f64 also falls back: the kernel accumulates in f32 VMEM scratch, which
    # would silently degrade float64 gradient checks.
    backend = jax.default_backend()
    if not (_HAS_PALLAS and block_q and block_k and backend in ("tpu", "cpu")) \
            or q.dtype == jnp.float64:
        return mha(q, k, v, causal=causal, scale=scale)

    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    grid = (B * H, T // block_q, S // block_k)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=(backend == "cpu"),
    )(qf, kf, vf)
    return out.reshape(B, H, T, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_mha(q: Array, k: Array, v: Array, causal: bool = False,
              scale: Optional[float] = None) -> Array:
    """Fused blockwise attention (pallas TPU kernel, O(T) memory forward).

    Backward recomputes scores with XLA einsums (O(T²) bwd memory — the
    standard recompute tradeoff; a fused pallas backward is a drop-in
    upgrade behind this same VJP seam).  Padding masks aren't supported
    here — layers with masks route to ``mha``.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # accumulate in f32 for low-precision inputs, but keep f64 at f64 so the
    # float64 gradient-check suite stays meaningful (matches mha's contract)
    acc = jnp.float64 if q.dtype == jnp.float64 else jnp.float32
    qf, kf, vf = (x.astype(acc) for x in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        s = s + causal_bias(s.shape[-2], s.shape[-1])
    p = jax.nn.softmax(s, axis=-1)
    gf = g.astype(acc)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_mha.defvjp(_flash_fwd, _flash_bwd)
