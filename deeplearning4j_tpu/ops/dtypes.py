"""Dtype policy for TPU-efficient mixed precision.

The reference runs float32 throughout (ND4J default dtype, set globally via
`Nd4j.setDataType`); on TPU the MXU wants bfloat16 compute with float32
accumulation/params.  A ``DTypePolicy`` carries the three dtypes every layer
needs: parameter storage, compute, and output.  Tests use pure float32 (or
float64 under ``jax.experimental.enable_x64``) so gradient checks against
central differences stay meaningful (reference test strategy:
gradientcheck/GradientCheckUtil.java:112).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

DTypeLike = Any


@dataclasses.dataclass(frozen=True)
class DTypePolicy:
    """Parameter / compute / output dtypes used by every layer.

    ``param_dtype``   — dtype params are stored in (float32 by default).
    ``compute_dtype`` — dtype activations/matmuls run in (bfloat16 on TPU).
    ``output_dtype``  — dtype of loss/metrics accumulation (float32).
    """

    param_dtype: DTypeLike = jnp.float32
    compute_dtype: DTypeLike = jnp.float32
    output_dtype: DTypeLike = jnp.float32

    def cast_to_compute(self, x):
        return jnp.asarray(x, self.compute_dtype)

    def cast_to_param(self, x):
        return jnp.asarray(x, self.param_dtype)

    def cast_to_output(self, x):
        return jnp.asarray(x, self.output_dtype)


_DEFAULT = DTypePolicy()
_MIXED = DTypePolicy(compute_dtype=jnp.bfloat16)


def default_policy() -> DTypePolicy:
    """Full-precision policy (parity/testing)."""
    return _DEFAULT


def mixed_policy() -> DTypePolicy:
    """bfloat16-compute policy for TPU throughput (MXU-native)."""
    return _MIXED


def canonical_dtype(name: str | DTypeLike) -> Any:
    """Resolve a dtype from a JSON-friendly string name."""
    if isinstance(name, str):
        return jnp.dtype(name)
    return jnp.dtype(name)
