"""Int8 quantized serving matmul — per-channel symmetric weights, int32
accumulation, calibrated activation scales.

Serving forwards are weight-bandwidth-bound at small batch: every request
re-reads every f32 weight matrix from HBM while the MXU sits idle.  Int8
weights quarter that traffic and the int8 MXU path doubles peak
throughput on v5e — the classic serving win, IF numerics hold.  This
module implements the inference-only scheme:

  weights      per-OUTPUT-channel symmetric: ``q[:, j] = round(W[:, j] /
               s_j)`` with ``s_j = max|W[:, j]| / 127`` — int8 [-127, 127],
               no zero points (symmetric keeps the matmul a pure int8 dot).
  activations  per-tensor symmetric, scale from a CALIBRATION pass that
               sweeps representative inputs through the f32 model and
               records each matmul's incoming ``max|x|`` (outliers beyond
               the calibrated range saturate).
  accumulate   int8·int8 → int32 (``preferred_element_type``), dequantized
               once at the end: ``y = acc · (s_x · s_j)`` in f32.

Injection is dtype-duck-typing, NOT a layer rewrite: ``Int8Weight``
replaces a Dense-style ``W`` leaf in the params pytree.  Dense.forward
computes ``x @ params["W"].astype(x.dtype)`` — ``astype`` returns self
and ``__rmatmul__`` runs the quantized matmul (jnp returns
NotImplemented for unknown operand types, so Python dispatches to us),
eagerly and under jit alike (Int8Weight is a registered pytree whose
leaves are the int8 values and the f32 scales).  Layers that do anything
other than ``@`` with their W keep their f32 leaf: calibration only
quantizes weights it actually observed in a matmul.

The serving seam is ``Engine.load(quantize="int8")`` (serving/engine.py):
the engine quantizes the current version behind the zoo/registry model
and AOT-warms the QUANTIZED executables per (bucket, dtype) — the
zero-serve-time-compiles contract unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Int8Weight", "quantize_weight", "calibrate", "quantize_params",
           "quantize_model", "QuantizedModel"]


class Int8Weight:
    """A quantized stand-in for a 2-D f32 weight leaf.

    ``values`` int8 [in, out]; ``scales`` f32 [out] (per-output-channel
    weight scales, amax/127); ``act_scale`` f32 [] (per-tensor activation
    scale, calibrated amax/127).  Registered as a pytree so it traces,
    jits, and device_puts like any other leaf."""

    __slots__ = ("values", "scales", "act_scale")

    def __init__(self, values, scales, act_scale):
        self.values = values
        self.scales = scales
        self.act_scale = act_scale

    # -- pytree ------------------------------------------------------------
    def tree_flatten(self):
        return (self.values, self.scales, self.act_scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- duck-typed weight surface ----------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.values.shape

    @property
    def ndim(self) -> int:
        return self.values.ndim

    @property
    def dtype(self):
        return jnp.int8

    def astype(self, dtype):
        """Dense casts W to the activation dtype before the matmul; the
        quantized path casts its OUTPUT instead (see __rmatmul__)."""
        return self

    def dequantize(self):
        """f32 reconstruction (tests / fallback): values · scales."""
        return self.values.astype(jnp.float32) * self.scales[None, :]

    def __rmatmul__(self, x):
        """``x @ w``: quantize the activation with the calibrated scale,
        int8 matmul with int32 accumulation, dequantize once."""
        out_dtype = x.dtype
        xf = x.astype(jnp.float32)
        xq = jnp.clip(jnp.round(xf / self.act_scale), -127.0, 127.0)
        xq = xq.astype(jnp.int8)
        acc = jax.lax.dot_general(
            xq, self.values,
            dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (self.act_scale * self.scales)
        return y.astype(out_dtype)


jax.tree_util.register_pytree_node(
    Int8Weight,
    lambda w: w.tree_flatten(),
    Int8Weight.tree_unflatten)


def quantize_weight(w, act_amax: float) -> Int8Weight:
    """Per-output-channel symmetric int8 quantization of a 2-D float
    weight.  ``act_amax`` is the calibrated max|x| of the activations
    feeding this matmul.  All-zero channels get scale 1 (values are all
    zero anyway); a zero act_amax (dead input) likewise."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)                      # [out]
    scales = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scales[None, :]), -127, 127).astype(jnp.int8)
    act_scale = jnp.float32(act_amax / 127.0 if act_amax > 0 else 1.0)
    return Int8Weight(q, scales.astype(jnp.float32), act_scale)


class _CalibWeight:
    """Calibration stand-in: passes f32 math through unchanged while
    recording the max|x| of every activation that hits this weight.
    Eager-only (records into a host-side dict)."""

    __slots__ = ("w", "stats", "key")

    def __init__(self, w, stats: Dict[Any, float], key):
        self.w = w
        self.stats = stats
        self.key = key

    @property
    def shape(self):
        return self.w.shape

    @property
    def ndim(self):
        return self.w.ndim

    @property
    def dtype(self):
        return self.w.dtype

    def astype(self, dtype):
        return _CalibWeight(self.w.astype(dtype), self.stats, self.key)

    def __rmatmul__(self, x):
        amax = float(jnp.max(jnp.abs(x.astype(jnp.float32))))
        self.stats[self.key] = max(self.stats.get(self.key, 0.0), amax)
        return x @ self.w


def _weight_paths(params) -> List[Tuple]:
    """Paths of quantization candidates: 2-D floating leaves whose dict
    key is 'W' (the Dense/OutputLayer matmul weight convention)."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        last = path[-1]
        key = getattr(last, "key", None)
        arr = jnp.asarray(leaf)
        if (key == "W" and arr.ndim == 2
                and jnp.issubdtype(arr.dtype, jnp.floating)):
            out.append(path)
    return out


def _tree_replace(params, repl: Dict[Tuple, Any]):
    """Rebuild the tree with ``repl[path]`` substituted at those paths."""
    def sub(path, leaf):
        r = repl.get(path)
        return leaf if r is None else r
    return jax.tree_util.tree_map_with_path(sub, params)


def calibrate(model, xs) -> Dict[Tuple, float]:
    """Sweep calibration batches through the f32 model EAGERLY, recording
    max|activation| per candidate weight.  ``xs`` is one array or a list
    of arrays (leading batch axis).  Deterministic: same model + same xs
    -> identical stats (pure forward, no RNG).  Returns {path: amax} for
    every candidate that was actually exercised by a matmul."""
    batches = xs if isinstance(xs, (list, tuple)) else [xs]
    stats: Dict[Tuple, float] = {}
    paths = _weight_paths(model.params)
    calib_params = _tree_replace(
        model.params,
        {p: _CalibWeight(_get_path(model.params, p), stats, p)
         for p in paths})
    for x in batches:
        model._apply_layers(calib_params, model.state,
                            jnp.asarray(x, jnp.float32),
                            train=False, rng=None, mask=None)
    return stats


def _get_path(tree, path):
    node = tree
    for p in path:
        node = node[getattr(p, "key", getattr(p, "idx", None))]
    return node


def quantize_params(params, stats: Dict[Tuple, float]):
    """Quantize every calibrated candidate weight; uncalibrated leaves
    (weights never seen in a matmul) stay f32."""
    repl = {p: quantize_weight(_get_path(params, p), amax)
            for p, amax in stats.items()}
    return _tree_replace(params, repl)


class QuantizedModel:
    """Serving view of a model with quantized params: same
    ``_apply_layers`` (the Int8Weight leaves redirect the matmuls), same
    state/conf — satisfies the engine's ``_jitable`` contract so
    ``Engine.load`` AOT-compiles the quantized executables."""

    def __init__(self, model, params):
        self._model = model
        self.params = params
        self.state = model.state
        self.conf = getattr(model, "conf", None)

    def _apply_layers(self, params, state, x, **kw):
        return self._model._apply_layers(params, state, x, **kw)

    def output(self, x):
        y = self._apply_layers(self.params, self.state,
                               jnp.asarray(x), train=False,
                               rng=None, mask=None)[0]
        return np.asarray(y)


def quantize_model(model, xs) -> QuantizedModel:
    """Calibrate on ``xs`` and return the int8-served view of ``model``.
    Raises if calibration found nothing to quantize (wrong input, or a
    model with no Dense-style matmuls) — silently serving f32 under an
    int8 flag would be a lie."""
    stats = calibrate(model, xs)
    if not stats:
        raise ValueError(
            "int8 calibration found no quantizable matmul weights "
            "(no 2-D 'W' leaf was exercised by the calibration forward)")
    return QuantizedModel(model, quantize_params(model.params, stats))
