"""Fused LSTM cell — the SURVEY M0 pallas kernel.

The cell's matmuls (x·W + h·RW) stay in XLA where the MXU already runs
them optimally; the elementwise gate math (3 sigmoids, 2 tanhs,
muls/adds) is fused here into ONE pallas VMEM pass per direction via
custom VJP.

**Measured on the v5e chip (mb=64, T=128, n=512): XLA's own epilogue
fusion inside ``lax.scan`` is FASTER than this kernel (fwd 3.5 ms vs
5.7 ms; grad equal)** — XLA already fuses the cell's elementwise ops into
the matmul epilogue, and a separate pallas dispatch per scan step only
adds overhead.  The kernel therefore defaults OFF (``ENABLED=False`` /
``DL4J_TPU_FUSED_LSTM=1`` to opt in); it stays in-tree as the
custom-cell seam — the place a block-diagonal, quantized, or
multi-step-fused variant (where XLA genuinely can't fuse) drops in — and
is fully parity-tested on both the interpret and compiled paths.

Seams mirror ops/attention.py's flash kernel: compiled on TPU,
interpret-mode on CPU (tests), plain jax.numpy fallback for f64 (exact
gradient checks), other backends, or tile-unfriendly shapes.  Gate order
matches nn/layers/recurrent.py: [i, f, o, g].
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

#: opt-in: XLA's scan-epilogue fusion beats the kernel at common sizes
#: (see module docstring).  Set BEFORE the first trace of a model —
#: _use_pallas is evaluated at trace time, so already-jitted executables
#: keep whichever path they were traced with (clear jax caches to switch).
ENABLED = os.environ.get("DL4J_TPU_FUSED_LSTM", "0") == "1"

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _plain_cell(z: jax.Array, c: jax.Array) -> Tuple[jax.Array, jax.Array]:
    n = c.shape[-1]
    i = jax.nn.sigmoid(z[:, :n])
    f = jax.nn.sigmoid(z[:, n:2 * n])
    o = jax.nn.sigmoid(z[:, 2 * n:3 * n])
    g = jnp.tanh(z[:, 3 * n:])
    c_new = f * c + i * g
    return o * jnp.tanh(c_new), c_new


def _bwd_math(z: jax.Array, c: jax.Array, dh: jax.Array,
              dcn: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Closed-form cell backward (single source of truth — used by the
    pallas backward kernel AND the plain fallback): recomputes the gates
    from the (z, c) residuals, returns (dz, dc)."""
    n = c.shape[-1]
    i = jax.nn.sigmoid(z[:, :n])
    f = jax.nn.sigmoid(z[:, n:2 * n])
    o = jax.nn.sigmoid(z[:, 2 * n:3 * n])
    g = jnp.tanh(z[:, 3 * n:])
    c_new = f * c + i * g
    tc = jnp.tanh(c_new)
    do = dh * tc
    dct = dcn + dh * o * (1.0 - tc * tc)
    dz = jnp.concatenate([
        dct * g * i * (1.0 - i),
        dct * c * f * (1.0 - f),
        do * o * (1.0 - o),
        dct * i * (1.0 - g * g),
    ], axis=1)
    return dz, dct * f


def _fwd_kernel(z_ref, c_ref, h_out, c_out, *, n: int):
    h, c_new = _plain_cell(z_ref[...], c_ref[...])
    h_out[...] = h
    c_out[...] = c_new


def _bwd_kernel(z_ref, c_ref, dh_ref, dcn_ref, dz_out, dc_out, *, n: int):
    dz, dc = _bwd_math(z_ref[...], c_ref[...], dh_ref[...], dcn_ref[...])
    dz_out[...] = dz
    dc_out[...] = dc


def _use_pallas(z: jax.Array, n: int) -> bool:
    if not ENABLED or not _HAS_PALLAS or z.dtype == jnp.float64:
        return False
    if jax.default_backend() not in ("tpu", "cpu"):
        return False
    # small widths don't fill the 128-wide VPU lanes — XLA's fused
    # elementwise is already fine there, so keep the plain path
    return n >= 128


def _pallas_call(kernel, z, *args, out_shapes, n):
    mb = z.shape[0]
    bm = mb if mb <= 256 else 256
    while mb % bm:
        bm -= 1
    if bm < 8:   # prime/odd batches → degenerate 1-row tiles; caller falls back
        return None
    grid = (mb // bm,)

    def spec(width):
        return pl.BlockSpec((bm, width), lambda b: (b, 0))

    widths = [a.shape[1] for a in (z,) + args]
    return pl.pallas_call(
        functools.partial(kernel, n=n),
        grid=grid,
        in_specs=[spec(w) for w in widths],
        out_specs=[spec(s[1]) for s in out_shapes],
        out_shape=[jax.ShapeDtypeStruct(s, z.dtype) for s in out_shapes],
        interpret=(jax.default_backend() == "cpu"),
    )(z, *args)


@jax.custom_vjp
def fused_lstm_cell(z: jax.Array, c: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(h', c') from preactivations z [mb, 4n] (gate order [i|f|o|g]) and
    cell state c [mb, n].  One fused VMEM pass on TPU; exact fallbacks
    elsewhere."""
    n = c.shape[-1]
    if not _use_pallas(z, n):
        return _plain_cell(z, c)
    out = _pallas_call(_fwd_kernel, z, c,
                       out_shapes=[(z.shape[0], n), (z.shape[0], n)], n=n)
    if out is None:   # no viable batch tiling
        return _plain_cell(z, c)
    return out[0], out[1]


def _cell_fwd(z, c):
    out = fused_lstm_cell(z, c)
    return out, (z, c)


def _cell_bwd(res, cts):
    z, c = res
    dh, dcn = cts
    n = c.shape[-1]
    # cotangents can arrive as zeros with a different weak type; normalize
    dh = jnp.asarray(dh, z.dtype)
    dcn = jnp.asarray(dcn, z.dtype)
    if not _use_pallas(z, n):
        return _bwd_math(z, c, dh, dcn)   # exact, f64-safe
    out = _pallas_call(_bwd_kernel, z, c, dh, dcn,
                       out_shapes=[z.shape, c.shape], n=n)
    if out is None:
        return _bwd_math(z, c, dh, dcn)
    return out[0], out[1]


fused_lstm_cell.defvjp(_cell_fwd, _cell_bwd)
