"""Paged KV-cache + deterministic attention for autoregressive decode.

The decode engine (serving/decode.py) keeps per-request K/V as explicit
JAX carry state in a **page pool**: one global buffer per layer holding
``n_pages`` fixed-size pages, with a per-slot **page table** of pool
indices.  A request's cache is the pages its table row points at —
allocation/free is host-side free-list bookkeeping (serving/decode.py),
never a device reshape.  A ring buffer is the degenerate case
(``pages_per_slot * page_size`` contiguous pages per slot, never freed
early); the paged layout additionally lets a pool smaller than
``max_slots * pages_per_slot`` oversubscribe slots when request lengths
vary, returning a finished request's pages to the free list the moment
it stops (EOS / max-tokens / deadline).

Page id 0 is the **scratch page** by convention: inactive slots' page-
table rows are all-zero, so the fixed-shape decode step can write every
slot unconditionally (no dynamic shapes, zero recompiles) while masked
slots' writes land in scratch and are never read unmasked.

Why a dedicated attention formulation instead of ops/attention.mha:
the A/B contract (bench ``continuous_batching_ab``) requires per-token
logits **bit-identical** between the incremental decode path (one query
row against the cache) and a full re-encode (all rows at once).  On
XLA, ``X @ W`` against a shared 2D weight is bitwise independent of the
number of rows — but dot-general attention scores are NOT: lowering
changes with the query count, so row k of a [T,L] score matrix differs
in final ulps from the same row computed alone.  ``det_attention``
therefore computes scores and the weighted sum as broadcast-multiply +
reduce over a trailing axis, whose per-element reduction is independent
of the leading (query) shape, and always attends over the same fixed
key length ``L`` (the slot capacity) with additive ``NEG_INF`` masking
— exp underflows to exact 0.0 for masked keys, and ``0.0 * v`` terms
cannot perturb the sum.  Both the decode path and the re-encode
reference use these functions, so bit-identity is structural.  The
price is an O(T·L·d) materialized product instead of an MXU dot — the
right trade for correctness-gated decode; the training path keeps the
flash/mha kernels.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from .attention import _NEG_INF

Array = jax.Array

NEG_INF = _NEG_INF  # shared masking convention with ops/attention.py

SCRATCH_PAGE = 0    # pool page 0: write target for masked-out slots


class KVCache(NamedTuple):
    """Device carry state: the page pools for K and V.

    ``k_pages`` / ``v_pages``: [n_layers, n_pages, page_size, n_heads,
    d_head].  Page tables and sequence positions live host-side in the
    decode engine (tiny int arrays passed per call).
    """

    k_pages: Array
    v_pages: Array


def alloc_cache(n_layers: int, n_pages: int, page_size: int, n_heads: int,
                d_head: int, dtype=jnp.float32) -> KVCache:
    """Zero-filled pool.  ``n_pages`` INCLUDES the scratch page 0."""
    shape = (n_layers, n_pages, page_size, n_heads, d_head)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages a request of ``n_tokens`` total (prompt + generated) needs."""
    return max(1, math.ceil(n_tokens / page_size))


# -- pool read/write (pure; all shapes static) ----------------------------


def write_prefill(pages: Array, layer: int, page_table_row: Array,
                  kv: Array) -> Array:
    """Scatter a prompt's projected rows into one slot's pages.

    ``page_table_row`` [pages_per_slot] int32, ``kv`` [T, H, d] written
    at positions 0..T-1.  Positions beyond the prompt's real length are
    garbage-but-finite and masked by the step bias until overwritten by
    the decode steps that reach them.
    """
    t = kv.shape[0]
    page_size = pages.shape[2]
    pos = jnp.arange(t, dtype=jnp.int32)
    page_idx = page_table_row[pos // page_size]
    return pages.at[layer, page_idx, pos % page_size].set(kv)


def write_step(pages: Array, layer: int, page_table: Array, positions: Array,
               kv: Array) -> Array:
    """Scatter one token per slot: ``page_table`` [S, pages_per_slot],
    ``positions`` [S], ``kv`` [S, H, d].  Masked slots are routed to the
    scratch page by the caller (their table rows are zeroed)."""
    page_size = pages.shape[2]
    s = jnp.arange(page_table.shape[0], dtype=jnp.int32)
    page_idx = page_table[s, positions // page_size]
    return pages.at[layer, page_idx, positions % page_size].set(kv)


def gather_layer(pages: Array, layer: int, page_table: Array) -> Array:
    """[S, pages_per_slot] table -> [S, L, H, d] contiguous view of one
    layer's cached rows (L = pages_per_slot * page_size)."""
    g = pages[layer][page_table]          # [S, pps, page, H, d]
    s, pps, page, h, d = g.shape
    return g.reshape(s, pps * page, h, d)


# -- deterministic attention ----------------------------------------------


def det_scores(q: Array, k: Array) -> Array:
    """[B,H,Tq,d] x [B,H,L,d] -> [B,H,Tq,L] via broadcast-multiply +
    trailing-axis reduce: per-element bits independent of Tq (a
    dot-general's are not — see module docstring)."""
    return jnp.sum(q[:, :, :, None, :] * k[:, :, None, :, :], axis=-1)


def det_weighted_sum(p: Array, v: Array) -> Array:
    """[B,H,Tq,L] x [B,H,L,d] -> [B,H,Tq,d]; exact-zero weights (masked
    keys) contribute exact zeros regardless of the garbage in v."""
    return jnp.sum(p[:, :, :, :, None] * v[:, :, None, :, :], axis=-2)


def det_attention(q: Array, k: Array, v: Array, bias: Array) -> Array:
    """Row-bitwise-deterministic attention over a FIXED key length.

    ``q`` [B,H,Tq,d]; ``k``/``v`` [B,H,L,d]; ``bias`` broadcastable to
    [B,H,Tq,L] with 0 on visible keys and ``NEG_INF`` elsewhere.  Every
    caller (prefill / decode step / re-encode reference) must use the
    same L so the softmax reduces over identical row lengths.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = det_scores(q, k) * scale + bias
    p = jax.nn.softmax(s, axis=-1)
    return det_weighted_sum(p, v)


class DecodeProgram(NamedTuple):
    """The pure functions + static config a model hands the decode
    engine (``ShardedTransformerLM.decode_program()``).  All fns are
    shape-polymorphic; the engine fixes shapes at AOT-warmup time.

      prefill(params, k_pages, v_pages, page_table_row, tokens, n_real)
          -> (k_pages, v_pages, logits [V])   one slot, bucketed length
      step(params, k_pages, v_pages, page_table, tokens, positions,
           active) -> (k_pages, v_pages, logits [S, V])   all slots
      reencode(params, tokens [B, L]) -> logits [B, L, V]
          the full-forward reference the bit-identity gate compares to
    """

    prefill: Callable[..., Any]
    step: Callable[..., Any]
    reencode: Callable[..., Any]
    n_layers: int
    n_heads: int
    d_head: int
    vocab_size: int
    max_len: int            # L: fixed key length = pages_per_slot * page_size
    page_size: int
    pages_per_slot: int
