"""Paged KV-cache + deterministic attention for autoregressive decode.

The decode engine (serving/decode.py) keeps per-request K/V as explicit
JAX carry state in a **page pool**: one global buffer per layer holding
``n_pages`` fixed-size pages, with a per-slot **page table** of pool
indices.  A request's cache is the pages its table row points at —
allocation/free is host-side free-list bookkeeping (serving/decode.py),
never a device reshape.  A ring buffer is the degenerate case
(``pages_per_slot * page_size`` contiguous pages per slot, never freed
early); the paged layout additionally lets a pool smaller than
``max_slots * pages_per_slot`` oversubscribe slots when request lengths
vary, returning a finished request's pages to the free list the moment
it stops (EOS / max-tokens / deadline).

Page id 0 is the **scratch page** by convention: inactive slots' page-
table rows are all-zero, so the fixed-shape decode step can write every
slot unconditionally (no dynamic shapes, zero recompiles) while masked
slots' writes land in scratch and are never read unmasked.

Why a dedicated attention formulation instead of ops/attention.mha:
the A/B contract (bench ``continuous_batching_ab``) requires per-token
logits **bit-identical** between the incremental decode path (one query
row against the cache) and a full re-encode (all rows at once).  On
XLA, ``X @ W`` against a shared 2D weight is bitwise independent of the
number of rows — but dot-general attention scores are NOT: lowering
changes with the query count, so row k of a [T,L] score matrix differs
in final ulps from the same row computed alone.  ``det_attention``
therefore computes scores and the weighted sum as broadcast-multiply +
reduce over a trailing axis, whose per-element reduction is independent
of the leading (query) shape, and always attends over the same fixed
key length ``L`` (the slot capacity) with additive ``NEG_INF`` masking
— exp underflows to exact 0.0 for masked keys, and ``0.0 * v`` terms
cannot perturb the sum.  Both the decode path and the re-encode
reference use these functions, so bit-identity is structural.  The
price is an O(T·L·d) materialized product instead of an MXU dot — the
right trade for correctness-gated decode; the training path keeps the
flash/mha kernels.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from .attention import _NEG_INF

Array = jax.Array

NEG_INF = _NEG_INF  # shared masking convention with ops/attention.py

SCRATCH_PAGE = 0    # pool page 0: write target for masked-out slots


class QuantPages(NamedTuple):
    """Int8 page pool: symmetrically quantized values plus the f32
    scales that ride alongside (``kv_dtype="int8"``).

    ``q``: [n_layers, n_pages, page_size, n_heads, d_head] int8;
    ``scale``: [n_layers, n_pages, page_size] f32 — one scale per cached
    row.  Pages fill append-only (prefill writes a range, each decode
    step appends one row), so the symmetric scale is computed per ROW at
    write time: a page-wide amax would change as rows arrive and force
    requantizing rows already stored.  Row granularity is the
    page-aligned refinement of per-page quantization that append-only
    writes admit, and every scale lives in the page-indexed side arrays
    so pages still share/free/scrub as a unit.  Dequantization happens
    in ``gather_layer`` (feeding ``det_scores``/``det_weighted_sum``
    f32), so attention math is unchanged — int8 trades bits for HBM and
    is gated behind an accuracy envelope (bench ``decode_speed_ab``).
    """

    q: Array
    scale: Array


KVPool = Union[Array, QuantPages]


class KVCache(NamedTuple):
    """Device carry state: the page pools for K and V.

    ``k_pages`` / ``v_pages``: [n_layers, n_pages, page_size, n_heads,
    d_head] (or :class:`QuantPages` when ``kv_dtype="int8"``).  Page
    tables and sequence positions live host-side in the decode engine
    (tiny int arrays passed per call).
    """

    k_pages: KVPool
    v_pages: KVPool


def alloc_cache(n_layers: int, n_pages: int, page_size: int, n_heads: int,
                d_head: int, dtype=jnp.float32,
                kv_dtype: Optional[str] = None) -> KVCache:
    """Zero-filled pool.  ``n_pages`` INCLUDES the scratch page 0.
    ``kv_dtype="int8"`` allocates int8 value pools with f32 row scales
    (a zero scale dequantizes untouched rows to the same 0.0 an f32
    pool starts with)."""
    shape = (n_layers, n_pages, page_size, n_heads, d_head)
    if kv_dtype in ("int8", "i8"):
        def pool():
            return QuantPages(jnp.zeros(shape, jnp.int8),
                              jnp.zeros(shape[:3], jnp.float32))
        return KVCache(pool(), pool())
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def pool_nbytes(cache) -> int:
    """Resident bytes of a cache (pool values + any quant scales) — the
    sessions-at-fixed-HBM arithmetic in bench ``decode_speed_ab``."""
    return int(sum(a.size * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(cache)))


def _quantize_rows(kv: Array) -> tuple:
    """Per-row symmetric int8: ``kv`` [..., H, d] → (int8 values,
    f32 scales [...]) with scale = amax/127 (ops/quantize.py scheme;
    zero rows get scale 1.0 so dequant stays exact-zero).  A non-finite
    row propagates through its SCALE, so poison isolation still sees
    NaN after dequantization."""
    amax = jnp.max(jnp.abs(kv), axis=(-2, -1))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(kv / scale[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages a request of ``n_tokens`` total (prompt + generated) needs."""
    return max(1, math.ceil(n_tokens / page_size))


# -- pool read/write (pure; all shapes static) ----------------------------


def _pool_values(pages: KVPool) -> Array:
    return pages.q if isinstance(pages, QuantPages) else pages


def _pool_set(pages: KVPool, layer, page_idx, slot_idx, kv: Array) -> KVPool:
    """Scatter f32 rows into an f32 or int8 pool (quantizing on write)."""
    if isinstance(pages, QuantPages):
        q, sc = _quantize_rows(kv)
        return QuantPages(pages.q.at[layer, page_idx, slot_idx].set(q),
                          pages.scale.at[layer, page_idx, slot_idx].set(sc))
    return pages.at[layer, page_idx, slot_idx].set(kv)


def write_prefill(pages: KVPool, layer: int, page_table_row: Array,
                  kv: Array, offset=0) -> KVPool:
    """Scatter a prompt's projected rows into one slot's pages.

    ``page_table_row`` [pages_per_slot] int32, ``kv`` [T, H, d] written
    at positions ``offset``..``offset+T-1`` (``offset`` defaults to 0;
    a prefix-cache suffix prefill passes the matched token count, a
    page multiple).  Positions beyond the prompt's real length are
    garbage-but-finite and masked by the step bias until overwritten by
    the decode steps that reach them; positions past the slot's page
    capacity (an offset prefill's bucket padding can overshoot) are
    routed to the scratch page.
    """
    t = kv.shape[0]
    page_size = _pool_values(pages).shape[2]
    pps = page_table_row.shape[0]
    pos = offset + jnp.arange(t, dtype=jnp.int32)
    idx = pos // page_size
    page_idx = jnp.where(idx < pps,
                         page_table_row[jnp.clip(idx, 0, pps - 1)],
                         SCRATCH_PAGE)
    return _pool_set(pages, layer, page_idx, pos % page_size, kv)


def write_step(pages: KVPool, layer: int, page_table: Array, positions: Array,
               kv: Array) -> KVPool:
    """Scatter one token per slot: ``page_table`` [S, pages_per_slot],
    ``positions`` [S], ``kv`` [S, H, d].  Masked slots are routed to the
    scratch page by the caller (their table rows are zeroed)."""
    page_size = _pool_values(pages).shape[2]
    s = jnp.arange(page_table.shape[0], dtype=jnp.int32)
    page_idx = page_table[s, positions // page_size]
    return _pool_set(pages, layer, page_idx, positions % page_size, kv)


def write_tokens(pages: KVPool, layer: int, page_table: Array,
                 positions: Array, kv: Array) -> KVPool:
    """Scatter a RANGE of tokens per slot — the speculative-verify
    write.  ``page_table`` [S, pages_per_slot], ``positions`` [S] (the
    absolute position of each slot's first row), ``kv`` [S, T, H, d]
    written at positions ``positions[s]``..``positions[s]+T-1``.  Rows
    past the slot's page capacity are routed to the scratch page (a
    fixed-k speculative step near ``max_len`` overshoots by
    construction — those proposals are never committed)."""
    page_size = _pool_values(pages).shape[2]
    s_n, pps = page_table.shape
    t_n = kv.shape[1]
    pos = positions[:, None] + jnp.arange(t_n, dtype=jnp.int32)[None, :]
    idx = pos // page_size
    s_ix = jnp.arange(s_n, dtype=jnp.int32)[:, None]
    page_idx = jnp.where(idx < pps,
                         page_table[s_ix, jnp.clip(idx, 0, pps - 1)],
                         SCRATCH_PAGE)
    return _pool_set(pages, layer, page_idx, pos % page_size, kv)


def gather_layer(pages: KVPool, layer: int, page_table: Array) -> Array:
    """[S, pages_per_slot] table -> [S, L, H, d] contiguous f32 view of
    one layer's cached rows (L = pages_per_slot * page_size).  Int8
    pools dequantize here — ``det_scores``/``det_weighted_sum`` always
    see f32, so the attention math is dtype-agnostic."""
    if isinstance(pages, QuantPages):
        g = (pages.q[layer][page_table].astype(jnp.float32)
             * pages.scale[layer][page_table][..., None, None])
    else:
        g = pages[layer][page_table]      # [S, pps, page, H, d]
    s, pps, page, h, d = g.shape
    return g.reshape(s, pps * page, h, d)


def scrub_pool(pages: KVPool, ids: Array) -> KVPool:
    """Zero the given page ids — values AND scales for int8 pools (a
    stale scale would re-scale the next tenant's rows)."""
    return jax.tree_util.tree_map(lambda a: a.at[:, ids].set(0), pages)


# -- page transfer (disaggregated prefill/decode) --------------------------


def gather_pages(pages: KVPool, ids: Array) -> KVPool:
    """Extract page ids as a dense payload [n_layers, len(ids), ...] —
    the device half of a prefill→decode page transfer.  Tree-aware like
    ``scrub_pool`` (int8 pools carry values AND scales), so the payload
    is bit-exact: f32 rows copy verbatim, int8 rows copy q and scale
    verbatim (dequantization happens only at attention time on the
    receiving host, same as locally).  Duplicate ids are harmless — the
    fixed-shape extract executable pads with repeats."""
    return jax.tree_util.tree_map(lambda a: a[:, ids], pages)


def set_pages(pages: KVPool, ids: Array, payload: KVPool) -> KVPool:
    """Scatter a gathered payload back at (generally DIFFERENT) page
    ids — the attach half of a transfer after page-table remap.  Padding
    and prefix-deduped entries must point at the scratch page with
    all-zero payload rows: scratch is never read unmasked, so which
    duplicate scatter wins there is immaterial."""
    return jax.tree_util.tree_map(
        lambda a, p: a.at[:, ids].set(p), pages, payload)


class PageTransfer(NamedTuple):
    """One request's extracted KV pages as a host-side transfer unit.

    ``n_pages`` real pages (payload rows beyond it, if any, are
    padding); ``k`` / ``v`` are numpy payloads shaped
    [n_layers, n_pages, page_size, n_heads, d_head] — plain f32 arrays,
    or :class:`QuantPages` of numpy arrays (int8 values + f32 row
    scales) when the pool is int8.  ``pack_transfer`` /
    ``unpack_transfer`` give the wire form; the round trip is bitwise
    for f32 and exact on (q, scale) for int8."""

    n_pages: int
    k: Any
    v: Any


_TRANSFER_MAGIC = b"KVPX1\n"


def _transfer_arrays(t: PageTransfer):
    out = []
    for name, side in (("k", t.k), ("v", t.v)):
        if isinstance(side, QuantPages):
            out.append((name + ".q", side.q))
            out.append((name + ".scale", side.scale))
        else:
            out.append((name, side))
    return out


def transfer_nbytes(t: PageTransfer) -> int:
    """Payload bytes a transfer puts on the wire (header excluded)."""
    return int(sum(np.asarray(a).nbytes for _, a in _transfer_arrays(t)))


def pack_transfer(t: PageTransfer) -> bytes:
    """Serialize a :class:`PageTransfer`: a json header (names, dtypes,
    shapes, page count) followed by the raw array bytes in header
    order.  No pickling — the wire form is self-describing and safe to
    unpack from an untrusted peer (``unpack_transfer`` validates)."""
    import json
    arrs = [(n, np.ascontiguousarray(np.asarray(a)))
            for n, a in _transfer_arrays(t)]
    header = json.dumps({
        "n_pages": int(t.n_pages),
        "arrays": [{"name": n, "dtype": a.dtype.name, "shape": a.shape}
                   for n, a in arrs],
    }).encode()
    body = b"".join(a.tobytes() for _, a in arrs)
    return (_TRANSFER_MAGIC + len(header).to_bytes(8, "big")
            + header + body)


def unpack_transfer(data: bytes) -> PageTransfer:
    """Inverse of :func:`pack_transfer`.  Raises ``ValueError`` on any
    truncated/corrupt input — the decode host fails the ONE request the
    bad bytes belong to, before any page allocation, so its free-list
    partition is untouched."""
    import json
    m = len(_TRANSFER_MAGIC)
    if len(data) < m + 8 or data[:m] != _TRANSFER_MAGIC:
        raise ValueError("not a KV page transfer (bad magic)")
    hlen = int.from_bytes(data[m:m + 8], "big")
    if len(data) < m + 8 + hlen:
        raise ValueError("truncated page transfer (header)")
    try:
        header = json.loads(data[m + 8:m + 8 + hlen])
        descs = header["arrays"]
        n_pages = int(header["n_pages"])
    except (ValueError, KeyError, TypeError) as e:
        raise ValueError(f"corrupt page transfer header: {e}") from e
    off = m + 8 + hlen
    parts: dict = {}
    for d in descs:
        dt = np.dtype(d["dtype"])
        shape = tuple(int(x) for x in d["shape"])
        nbytes = int(dt.itemsize * math.prod(shape)) if shape else dt.itemsize
        if len(data) < off + nbytes:
            raise ValueError(f"truncated page transfer (array {d['name']})")
        parts[d["name"]] = np.frombuffer(
            data[off:off + nbytes], dtype=dt).reshape(shape)
        off += nbytes

    def _side(name):
        if name in parts:
            return parts[name]
        if name + ".q" in parts and name + ".scale" in parts:
            return QuantPages(parts[name + ".q"], parts[name + ".scale"])
        raise ValueError(f"page transfer missing {name!r} payload")

    return PageTransfer(n_pages=n_pages, k=_side("k"), v=_side("v"))


# -- deterministic attention ----------------------------------------------


def det_scores(q: Array, k: Array) -> Array:
    """[B,H,Tq,d] x [B,H,L,d] -> [B,H,Tq,L] via broadcast-multiply +
    trailing-axis reduce: per-element bits independent of Tq (a
    dot-general's are not — see module docstring)."""
    return jnp.sum(q[:, :, :, None, :] * k[:, :, None, :, :], axis=-1)


def det_weighted_sum(p: Array, v: Array) -> Array:
    """[B,H,Tq,L] x [B,H,L,d] -> [B,H,Tq,d]; exact-zero weights (masked
    keys) contribute exact zeros regardless of the garbage in v."""
    return jnp.sum(p[:, :, :, :, None] * v[:, :, None, :, :], axis=-2)


def det_attention(q: Array, k: Array, v: Array, bias: Array) -> Array:
    """Row-bitwise-deterministic attention over a FIXED key length.

    ``q`` [B,H,Tq,d]; ``k``/``v`` [B,H,L,d]; ``bias`` broadcastable to
    [B,H,Tq,L] with 0 on visible keys and ``NEG_INF`` elsewhere.  Every
    caller (prefill / decode step / re-encode reference) must use the
    same L so the softmax reduces over identical row lengths.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = det_scores(q, k) * scale + bias
    p = jax.nn.softmax(s, axis=-1)
    return det_weighted_sum(p, v)


class DecodeProgram(NamedTuple):
    """The pure functions + static config a model hands the decode
    engine (``ShardedTransformerLM.decode_program()``).  All fns are
    shape-polymorphic; the engine fixes shapes at AOT-warmup time.

      prefill(params, k_pages, v_pages, page_table_row, tokens, n_real)
          -> (k_pages, v_pages, logits [V])   one slot, bucketed length
      step(params, k_pages, v_pages, page_table, tokens, positions,
           active) -> (k_pages, v_pages, logits [S, V])   all slots
      reencode(params, tokens [B, L]) -> logits [B, L, V]
          the full-forward reference the bit-identity gate compares to

    Optional decode-speed entry points (``None`` when the model does
    not provide them; the engine falls back to the plain paths):

      prefill_at(params, k_pages, v_pages, page_table_row, tokens,
                 n_real, offset) -> (k_pages, v_pages, logits [V])
          suffix prefill for a prefix-cache hit: rows land at absolute
          positions offset..offset+Tb-1 and attend over the shared
          prefix pages already in the pool
      spec_step(params, k_pages, v_pages, page_table, tokens [S, T],
                positions [S], active [S])
          -> (k_pages, v_pages, logits [S, T, V])
          speculative verify: score T tokens per slot in one call,
          writing their K/V rows (overflow rows route to scratch)
      step_multi(params, k_pages, v_pages, page_table, tokens,
                 positions, active, temps [S], top_ks [S], top_ps [S],
                 seeds [S], steps [S], budgets [S], eos_id, horizon [H])
          -> (k_pages, v_pages, tokens [H, S], finite [H, S],
              logits [H, S, V])
          fused multi-step decode: ``lax.scan`` of the step body over
          ``horizon`` (an int32 arange whose LENGTH is the fused
          horizon H), with sampling device-resident
          (``ops.sampling.sample_token`` keyed ``fold_in(seed,
          steps + j)``) so the host syncs once per H tokens.  Per-slot
          EOS (token == eos_id; pass -1 to disable) / token-budget /
          poison masking runs on device: a finished slot's page-table
          row zeroes, routing its remaining writes to the scratch page,
          so live slots' bits are untouched and fusion stays
          bit-identical to H plain steps.
    """

    prefill: Callable[..., Any]
    step: Callable[..., Any]
    reencode: Callable[..., Any]
    n_layers: int
    n_heads: int
    d_head: int
    vocab_size: int
    max_len: int            # L: fixed key length = pages_per_slot * page_size
    page_size: int
    pages_per_slot: int
    prefill_at: Any = None
    spec_step: Any = None
    step_multi: Any = None
    # tensor-parallel degree of the program's executables: >1 means the
    # fns are shard_map'd over the mesh's "data" axis (heads + page pool
    # sharded, logits replicated) — see parallel/transformer.py
    tp: int = 1
