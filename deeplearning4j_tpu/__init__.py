"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA re-design of the capability surface of Eclipse
Deeplearning4j (reference: /root/reference @ 0.9.2-SNAPSHOT).  Where the
reference executes eager per-op through JNI into libnd4j/cuDNN
(see reference nn/multilayer/MultiLayerNetwork.java:1165 fit loop), this
framework defines layers as pure functions, derives gradients with
``jax.grad``, and compiles one XLA program per training step; distributed
training uses mesh collectives (psum/ppermute) over ICI/DCN instead of
parameter averaging / Aeron UDP gradient messages.

Top-level layout:
    ops/          tensor substrate: dtype policy, activations, initializers,
                  losses, collectives, pallas kernels  (replaces ND4J, L0)
    nn/           configs-as-data, layer impls, model containers, updaters,
                  train-step factory                    (replaces deeplearning4j-nn, L1)
    datasets/     DataSet + iterator pipeline           (replaces deeplearning4j-core data, L3)
    evaluation/   Evaluation / ROC / regression metrics (replaces eval/, L1)
    parallel/     mesh builders, DP/TP/SP training, ring attention,
                  parallel inference                    (replaces scaleout, L4)
    models/       model zoo                             (replaces deeplearning4j-zoo, L5)
    nlp/          embeddings: Word2Vec family, SequenceVectors,
                  ParagraphVectors, GloVe               (replaces deeplearning4j-nlp, L5)
    graph/        graph + random walks + DeepWalk       (replaces deeplearning4j-graph, L5)
    clustering/   KMeans + brute-force KNN on the MXU   (replaces nearestneighbors, L5)
    plot/         exact t-SNE, device-resident          (replaces core plot/, L3)
    modelimport/  Keras HDF5 import                     (replaces deeplearning4j-modelimport, L5)
    utils/        serialization, gradient checks        (replaces util/, gradientcheck/)
"""

__version__ = "0.1.0"
