"""Updaters + LR schedules + gradient normalization.

Parity surface: ND4J ``IUpdater`` configs (org.nd4j.linalg.learning.config:
Sgd, Nesterovs, Adam, AdaMax, Nadam, AdaGrad, AdaDelta, RmsProp, NoOp) and
DL4J's updater machinery (nn/updater/BaseMultiLayerUpdater.java:38 —
``update():208-223`` applies per-block updater math, ``preApply():318``
applies gradient normalization/clipping).

Design: an Updater is a dataclass with ``init_state(params)`` and
``update(grads, state, iteration)`` → (updates, new_state); the train step
applies ``params -= updates`` (the reference's in-place
StepFunction.step equivalent).  The reference's flattened-view UpdaterBlock
machinery disappears: XLA fuses the per-leaf update ops as well as a flat
buffer would, without the aliasing hazards.

LR schedules follow LearningRatePolicy (nn/conf/LearningRatePolicy.java):
exponential / inverse / poly / sigmoid / step / map.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from .layers.base import register_config

Array = jax.Array


# ---------------------------------------------------------------------------
# learning-rate schedules
# ---------------------------------------------------------------------------


@register_config
@dataclasses.dataclass
class Schedule:
    """Fixed LR (base class doubles as the trivial schedule)."""

    lr: float = 1e-3

    def __call__(self, it: Array) -> Array:
        return jnp.asarray(self.lr, jnp.float32)


@register_config
@dataclasses.dataclass
class ExponentialSchedule(Schedule):
    decay: float = 0.99

    def __call__(self, it):
        return self.lr * jnp.power(self.decay, it.astype(jnp.float32))


@register_config
@dataclasses.dataclass
class InverseSchedule(Schedule):
    decay: float = 0.01
    power: float = 1.0

    def __call__(self, it):
        return self.lr / jnp.power(1.0 + self.decay * it.astype(jnp.float32), self.power)


@register_config
@dataclasses.dataclass
class PolySchedule(Schedule):
    power: float = 1.0
    max_iter: int = 10000

    def __call__(self, it):
        frac = jnp.clip(it.astype(jnp.float32) / self.max_iter, 0.0, 1.0)
        return self.lr * jnp.power(1.0 - frac, self.power)


@register_config
@dataclasses.dataclass
class SigmoidSchedule(Schedule):
    decay: float = 0.01
    steps: int = 1000

    def __call__(self, it):
        return self.lr / (1.0 + jnp.exp(-self.decay * (it.astype(jnp.float32) - self.steps)))


@register_config
@dataclasses.dataclass
class StepSchedule(Schedule):
    decay: float = 0.1
    steps: int = 1000

    def __call__(self, it):
        return self.lr * jnp.power(self.decay, jnp.floor(it.astype(jnp.float32) / self.steps))


def resolve_schedule(lr_or_schedule) -> Schedule:
    if isinstance(lr_or_schedule, Schedule):
        return lr_or_schedule
    return Schedule(lr=float(lr_or_schedule))


# ---------------------------------------------------------------------------
# updaters
# ---------------------------------------------------------------------------


def _zeros_like_tree(params):
    return jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)


def _tree_update(fn, grads, *state_trees):
    """Apply ``fn(g, *state_leaves) -> (out1, out2, ...)`` leafwise over the
    gradient tree, returning one tree per output slot.  Replaces the
    flatten/zip/unflatten plumbing every updater needs."""
    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_states = [treedef.flatten_up_to(s) for s in state_trees]
    outs = [fn(g, *(fs[i] for fs in flat_states)) for i, g in enumerate(flat_g)]
    if not isinstance(outs[0], tuple):
        return treedef.unflatten(outs)
    return tuple(treedef.unflatten([o[j] for o in outs]) for j in range(len(outs[0])))


@dataclasses.dataclass
class Updater:
    """Base updater config.  ``schedule`` may be a Schedule or raw float."""

    lr: Any = 1e-3

    def lr_at(self, it: Array) -> Array:
        return resolve_schedule(self.lr)(it)

    def init_state(self, params) -> Dict:
        return {}

    def update(self, grads, state, it: Array):
        raise NotImplementedError

    def apply(self, params, grads, state, it: Array):
        """One full optimizer application: updater math + the param step
        (``params -= updates`` in f32, cast back to each leaf's dtype) —
        what nn/multilayer._apply_updates runs per layer.  Subclasses
        with a fused one-pass kernel (ops/update_kernel.py) override
        this; the base implementation is the bit-exact reference."""
        updates, new_state = self.update(grads, state, it)
        new_params = jax.tree_util.tree_map(
            lambda pp, uu: (pp.astype(jnp.float32) - uu).astype(pp.dtype),
            params, updates)
        return new_params, new_state


@register_config
@dataclasses.dataclass
class Sgd(Updater):
    def update(self, grads, state, it):
        lr = self.lr_at(it)
        return jax.tree_util.tree_map(lambda g: lr * g.astype(jnp.float32), grads), state


@register_config
@dataclasses.dataclass
class Nesterovs(Updater):
    lr: Any = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return {"v": _zeros_like_tree(params)}

    def update(self, grads, state, it):
        lr, mu = self.lr_at(it), self.momentum

        def upd(g, v):
            # ND4J Nesterovs.java: vNew = mu*v - lr*g; update = mu*v - (1+mu)*vNew
            g = g.astype(jnp.float32)
            v_new = mu * v - lr * g
            return mu * v - (1.0 + mu) * v_new, v_new

        updates, new_v = _tree_update(upd, grads, state["v"])
        return updates, {"v": new_v}


@register_config
@dataclasses.dataclass
class Adam(Updater):
    """Adam (reference updater/AdamUpdater.java).

    ``moment_dtype`` (opt-in, e.g. "bfloat16") stores BOTH moments in a
    reduced dtype: the m/v read+write traffic is the dominant optimizer
    HBM cost on large models (~3.9 GB/step ≈ 20 ms on the GPT-2-small
    TransformerLM bench, docs/transformer_profile.md), and bf16 keeps
    f32's exponent range so v's dynamic range survives — only mantissa
    precision drops, quantified by tests/test_updaters_bf16.py.  The
    update math always runs in f32; only the carried state narrows."""

    lr: Any = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    moment_dtype: Any = None

    def _moments_like(self, params):
        z = _zeros_like_tree(params)
        if self.moment_dtype is None:
            return z
        dt = jnp.dtype(self.moment_dtype)
        return jax.tree_util.tree_map(lambda a: a.astype(dt), z)

    def init_state(self, params):
        return {"m": self._moments_like(params),
                "v": self._moments_like(params)}

    def update(self, grads, state, it):
        lr = self.lr_at(it)
        t = it.astype(jnp.float32) + 1.0
        bc1 = 1.0 - jnp.power(self.beta1, t)
        bc2 = 1.0 - jnp.power(self.beta2, t)

        def upd(g, m, v):
            g = g.astype(jnp.float32)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(jnp.float32) + (1 - self.beta2) * g * g
            step = lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            return step, m_new.astype(m.dtype), v_new.astype(v.dtype)

        updates, new_m, new_v = _tree_update(upd, grads, state["m"], state["v"])
        return updates, {"m": new_m, "v": new_v}

    def apply(self, params, grads, state, it):
        """Routes through the fused one-pass kernel (moment update +
        param step in one VMEM pass over flat bucketed buffers,
        ops/update_kernel.py) when it is enabled and applicable; the
        kernel's output is bit-identical to the per-leaf base path, which
        remains the fallback.  Exact Adam/Nadam only — AdaMax/AMSGrad
        subclasses carry different math and always take the base path."""
        from ..ops import update_kernel

        kind = update_kernel.kind_of(self)
        if kind is not None:
            fused = update_kernel.fused_apply(
                kind, self, params, grads, state, it)
            if fused is not None:
                return fused
        return super().apply(params, grads, state, it)


@register_config
@dataclasses.dataclass
class AdaMax(Adam):
    def update(self, grads, state, it):
        lr = self.lr_at(it)
        t = it.astype(jnp.float32) + 1.0
        bc1 = 1.0 - jnp.power(self.beta1, t)

        def upd(g, m, u):
            g = g.astype(jnp.float32)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            u_new = jnp.maximum(self.beta2 * u.astype(jnp.float32), jnp.abs(g))
            step = lr * (m_new / bc1) / (u_new + self.eps)
            return step, m_new.astype(m.dtype), u_new.astype(u.dtype)

        updates, new_m, new_v = _tree_update(upd, grads, state["m"], state["v"])
        return updates, {"m": new_m, "v": new_v}


@register_config
@dataclasses.dataclass
class Nadam(Adam):
    def update(self, grads, state, it):
        lr = self.lr_at(it)
        t = it.astype(jnp.float32) + 1.0
        bc1 = 1.0 - jnp.power(self.beta1, t)
        bc2 = 1.0 - jnp.power(self.beta2, t)

        def upd(g, m, v):
            g = g.astype(jnp.float32)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(jnp.float32) + (1 - self.beta2) * g * g
            m_hat = self.beta1 * (m_new / bc1) + (1 - self.beta1) * g / bc1
            step = lr * m_hat / (jnp.sqrt(v_new / bc2) + self.eps)
            return step, m_new.astype(m.dtype), v_new.astype(v.dtype)

        updates, new_m, new_v = _tree_update(upd, grads, state["m"], state["v"])
        return updates, {"m": new_m, "v": new_v}


@register_config
@dataclasses.dataclass
class AMSGrad(Adam):
    """AMSGrad (Reddi et al. 2018) — Adam with a monotone max on the
    second moment (upstream ND4J learning/config/AmsGrad.java; the
    reference's updater family resolves through nd4j).  State: m, v, and
    the running max v_hat."""

    def init_state(self, params):
        return {"m": self._moments_like(params),
                "v": self._moments_like(params),
                "vhat": self._moments_like(params)}

    def update(self, grads, state, it):
        lr = self.lr_at(it)
        t = it.astype(jnp.float32) + 1.0
        bc1 = 1.0 - jnp.power(self.beta1, t)
        bc2 = 1.0 - jnp.power(self.beta2, t)

        def upd(g, m, v, vh):
            g = g.astype(jnp.float32)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(jnp.float32) + (1 - self.beta2) * g * g
            vh_new = jnp.maximum(vh.astype(jnp.float32), v_new)
            step = lr * (m_new / bc1) / (jnp.sqrt(vh_new / bc2) + self.eps)
            return (step, m_new.astype(m.dtype), v_new.astype(v.dtype),
                    vh_new.astype(vh.dtype))

        updates, new_m, new_v, new_vh = _tree_update(
            upd, grads, state["m"], state["v"], state["vhat"])
        return updates, {"m": new_m, "v": new_v, "vhat": new_vh}


@register_config
@dataclasses.dataclass
class AdaGrad(Updater):
    lr: Any = 1e-1
    eps: float = 1e-6

    def init_state(self, params):
        return {"h": _zeros_like_tree(params)}

    def update(self, grads, state, it):
        lr = self.lr_at(it)

        def upd(g, h):
            g = g.astype(jnp.float32)
            h_new = h + g * g
            return lr * g / (jnp.sqrt(h_new) + self.eps), h_new

        updates, new_h = _tree_update(upd, grads, state["h"])
        return updates, {"h": new_h}


@register_config
@dataclasses.dataclass
class AdaDelta(Updater):
    rho: float = 0.95
    eps: float = 1e-6

    def init_state(self, params):
        return {"g2": _zeros_like_tree(params), "dx2": _zeros_like_tree(params)}

    def update(self, grads, state, it):
        def upd(g, g2, dx2):
            g = g.astype(jnp.float32)
            g2_new = self.rho * g2 + (1 - self.rho) * g * g
            step = jnp.sqrt(dx2 + self.eps) / jnp.sqrt(g2_new + self.eps) * g
            dx2_new = self.rho * dx2 + (1 - self.rho) * step * step
            return step, g2_new, dx2_new

        updates, new_g2, new_dx2 = _tree_update(upd, grads, state["g2"], state["dx2"])
        return updates, {"g2": new_g2, "dx2": new_dx2}


@register_config
@dataclasses.dataclass
class RmsProp(Updater):
    lr: Any = 1e-3
    rms_decay: float = 0.95
    eps: float = 1e-8

    def init_state(self, params):
        return {"g2": _zeros_like_tree(params)}

    def update(self, grads, state, it):
        lr = self.lr_at(it)

        def upd(g, g2):
            g = g.astype(jnp.float32)
            g2_new = self.rms_decay * g2 + (1 - self.rms_decay) * g * g
            return lr * g / (jnp.sqrt(g2_new) + self.eps), g2_new

        updates, new_g2 = _tree_update(upd, grads, state["g2"])
        return updates, {"g2": new_g2}


@register_config
@dataclasses.dataclass
class NoOp(Updater):
    def update(self, grads, state, it):
        return jax.tree_util.tree_map(lambda g: jnp.zeros_like(g, jnp.float32), grads), state


# ---------------------------------------------------------------------------
# gradient normalization (BaseMultiLayerUpdater.preApply parity)
# ---------------------------------------------------------------------------


class GradientNormalization:
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENTWISE_ABSOLUTE = "clip_elementwise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


def normalize_gradients(layer_grads: Dict[str, Array], mode: str, threshold: float) -> Dict[str, Array]:
    """Apply one layer's gradient normalization (reference preApply():318).

    ``layer_grads`` is the {param_name: grad} dict for a single layer.
    """
    if mode in (None, GradientNormalization.NONE):
        return layer_grads
    leaves, treedef = jax.tree_util.tree_flatten(layer_grads)
    if mode == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        norm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
        scale = 1.0 / jnp.maximum(norm, 1e-8)
        return treedef.unflatten([g * scale.astype(g.dtype) for g in leaves])
    if mode == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return treedef.unflatten([
            g / jnp.maximum(jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2)), 1e-8).astype(g.dtype)
            for g in leaves])
    if mode == GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE:
        return treedef.unflatten([jnp.clip(g, -threshold, threshold) for g in leaves])
    if mode == GradientNormalization.CLIP_L2_PER_LAYER:
        norm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
        scale = jnp.where(norm > threshold, threshold / (norm + 1e-8), 1.0)
        return treedef.unflatten([g * scale.astype(g.dtype) for g in leaves])
    if mode == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        out = []
        for g in leaves:
            norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            scale = jnp.where(norm > threshold, threshold / (norm + 1e-8), 1.0)
            out.append(g * scale.astype(g.dtype))
        return treedef.unflatten(out)
    raise ValueError(f"unknown gradient normalization mode {mode}")
