"""MultiLayerNetwork — the sequential-stack model container.

Parity target: reference nn/multilayer/MultiLayerNetwork.java (3,177 LoC):
``init():545`` (param flattening), ``fit(DataSetIterator):1165``,
``backprop():1260``, ``output():1867``, score accumulation, masking, and the
Solver/updater wiring (optimize/solvers/StochasticGradientDescent.java:58).

Design inversion (SURVEY.md §7): instead of the reference's eager per-op
forward + hand-written ``calcBackpropGradients`` loop + mutable flat param
buffer, the entire step — forward, loss, backward (jax.grad), gradient
normalization (preApply parity), per-layer updater math, and the parameter
update — is ONE jit-compiled XLA program.  Params/state/opt-state are
pytrees (list of per-layer dicts, keys matching the reference's param names
"W"/"b"/"RW"/"gamma"/...); donation avoids double-buffering params in HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datasets.dataset import DataSet
from ..datasets.iterators import DataSetIterator, ListDataSetIterator
from ..obs import trace as obs_trace
from .conf.inputs import InputType
from .conf.preprocessors import Preprocessor
from .conf.regularizers import apply_constraints, maybe_weight_noise
from .layers.base import Layer, config_from_dict, config_to_dict
from .updaters import Adam, GradientNormalization, Updater, normalize_gradients
from ..optimize.score import LazyScore, materialize_scores

Array = jax.Array


def _as_device(a):
    """Device-array passthrough for batch leaves: an already-device-resident
    array (DevicePrefetchIterator output, a pre-sharded mesh batch, a
    reused benchmark batch) enters the step untouched — no fresh host
    staging, no re-placement, and in particular never a device→host→device
    round trip.  Host arrays take the ordinary ``jnp.asarray`` upload."""
    if a is None or isinstance(a, jax.Array):
        return a
    return jnp.asarray(a)


class DivergenceError(RuntimeError):
    """The opt-in divergence guard exhausted its bad-step budget: too many
    consecutive steps produced non-finite gradients/loss, so skipping
    updates is no longer masking a transient (bad batch, overflow spike)
    but a diverged run.  The message carries the "non-finite gradient"
    marker the elastic FailureDetector recognizes, so an ElasticTrainer
    wrapping this net escalates to checkpoint-restore instead of dying."""

    def __init__(self, bad_steps: int, budget: int):
        super().__init__(
            f"non-finite gradients for {bad_steps} consecutive steps "
            f"(budget {budget}) — updates were skipped but the run is "
            "diverging; restore a checkpoint (ElasticTrainer recovers this "
            "automatically) or lower the learning rate")
        self.bad_steps = bad_steps
        self.budget = budget


@dataclasses.dataclass
class MultiLayerConfiguration:
    """Configs-as-data for a sequential net (reference
    MultiLayerConfiguration + per-layer NeuralNetConfiguration).  JSON
    round-trip via ``to_dict``/``from_dict`` is the serialization contract
    that checkpointing, transfer learning, and the zoo build on (reference
    nn/conf/serde/)."""

    layers: List[Layer] = dataclasses.field(default_factory=list)
    input_type: Optional[InputType] = None
    preprocessors: Dict[int, Preprocessor] = dataclasses.field(default_factory=dict)
    updater: Updater = dataclasses.field(default_factory=Adam)
    gradient_normalization: str = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    seed: int = 12345
    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    backprop_type: str = "standard"       # or "tbptt"
    tbptt_length: int = 20

    def to_dict(self) -> dict:
        d = config_to_dict(self)
        d["type"] = "MultiLayerConfiguration"
        d["preprocessors"] = {str(k): config_to_dict(v) for k, v in self.preprocessors.items()}
        d["input_type"] = None if self.input_type is None else self.input_type.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        d = dict(d)
        d.pop("type", None)
        pre = {int(k): config_from_dict(v) for k, v in (d.pop("preprocessors") or {}).items()}
        it = d.pop("input_type")
        conf = MultiLayerConfiguration(
            layers=[config_from_dict(l) for l in d.pop("layers")],
            input_type=None if it is None else InputType.from_dict(it),
            preprocessors=pre,
            updater=config_from_dict(d.pop("updater")),
            **{k: v for k, v in d.items()},
        )
        return conf


class ListBuilder:
    """Fluent builder parity with NeuralNetConfiguration.Builder().list()
    (reference NeuralNetConfiguration.java:206-303)."""

    def __init__(self, **defaults):
        self._conf = MultiLayerConfiguration()
        self._defaults = defaults

    def seed(self, s: int) -> "ListBuilder":
        self._conf.seed = s
        return self

    def updater(self, u: Updater) -> "ListBuilder":
        self._conf.updater = u
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0) -> "ListBuilder":
        self._conf.gradient_normalization = mode
        self._conf.gradient_normalization_threshold = threshold
        return self

    def layer(self, layer: Layer) -> "ListBuilder":
        for k, v in self._defaults.items():
            # apply builder-level defaults to layers that kept dataclass defaults
            if hasattr(layer, k) and getattr(layer, k) == type(layer).__dataclass_fields__[k].default:
                setattr(layer, k, v)
        self._conf.layers.append(layer)
        return self

    def preprocessor(self, index: int, pre: Preprocessor) -> "ListBuilder":
        self._conf.preprocessors[index] = pre
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._conf.input_type = t
        return self

    def tbptt(self, length: int) -> "ListBuilder":
        self._conf.backprop_type = "tbptt"
        self._conf.tbptt_length = length
        return self

    def dtype(self, param_dtype: str = "float32", compute_dtype: str = "float32") -> "ListBuilder":
        self._conf.param_dtype = param_dtype
        self._conf.compute_dtype = compute_dtype
        return self

    def build(self) -> MultiLayerConfiguration:
        return self._conf


class NeuralNetConfiguration:
    """Entry point mirroring the reference's builder DSL."""

    @staticmethod
    def builder(**defaults) -> ListBuilder:
        return ListBuilder(**defaults)


class MultiLayerNetwork:
    """Sequential model: init / fit / output / score / evaluate.

    Functional core, stateful shell: ``params``/``state``/``opt_state`` live
    on the object for the user-facing API (like the reference's mutable
    model), but every computation runs through pure jit'd functions.
    """

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params: List[Dict[str, Array]] = []
        self.state: List[Dict[str, Array]] = []
        self.opt_state: List[Dict] = []
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self.input_types: List[InputType] = []
        self._jit_step = None
        self._jit_step_guarded = None
        self._nan_guard_budget: Optional[int] = None
        self._bad_steps = 0
        self._jit_multi_step = None
        self._jit_step_tbptt = None
        self._jit_step_tbptt_scan = None
        self._it_dev = None        # device-resident iteration counter
        self._it_dev_val = -1      # python value _it_dev mirrors
        self._jit_output = None
        self._jit_score = None
        self._jit_score_examples = None
        self._jit_recon_logprob: Dict = {}
        self._jit_stream = None
        self._stream_carries = None
        self._rng = jax.random.PRNGKey(conf.seed)
        self._infer_types()

    # ------------------------------------------------------------------
    # shape inference + init
    # ------------------------------------------------------------------

    def _infer_types(self) -> None:
        """Propagate InputType through preprocessors+layers, auto-inserting
        shape adapters where the layer's expected kind mismatches (the
        reference's setInputType + getPreProcessorForInputType pass)."""
        from .conf.preprocessors import CnnToFeedForward, CnnToRnn, FeedForwardToCnn
        self.input_types = []
        t = self.conf.input_type
        if t is None:
            return
        for i, layer in enumerate(self.conf.layers):
            if i in self.conf.preprocessors:
                t = self.conf.preprocessors[i].output_type(t)
            elif layer.wants is not None and t.kind != layer.wants:
                pre = None
                if t.kind == "cnn" and layer.wants == "ff":
                    pre = CnnToFeedForward()
                elif t.kind == "cnn_flat" and layer.wants == "cnn":
                    pre = FeedForwardToCnn(t.height, t.width, t.channels)
                elif t.kind == "cnn_flat" and layer.wants == "ff":
                    t = InputType.feed_forward(t.flat_size())
                elif t.kind == "cnn" and layer.wants == "rnn":
                    pre = CnnToRnn()
                elif t.kind == "rnn" and layer.wants == "ff":
                    pre = None  # Dense-family layers broadcast over time
                if pre is not None:
                    self.conf.preprocessors[i] = pre
                    t = pre.output_type(t)
            self.input_types.append(t)
            layer.infer_nin(t)
            t = layer.output_type(t)
        self.output_type = t

    def init(self, rng: Optional[Array] = None) -> None:
        """Initialize params/state (reference init():545; param views become
        per-layer dicts — no flat buffer needed, XLA fuses updates)."""
        if not self.input_types:
            raise ValueError("conf.input_type must be set before init() "
                             "(or call set_input_type on the builder)")
        rng = rng if rng is not None else self._rng
        dtype = jnp.dtype(self.conf.param_dtype)
        keys = jax.random.split(rng, len(self.conf.layers))
        self.params, self.state, self.opt_state = [], [], []
        for layer, k, t in zip(self.conf.layers, keys, self.input_types):
            p = layer.init_params(k, t, dtype)
            s = layer.init_state(t, dtype)
            self.params.append(p)
            self.state.append(s)
            self.opt_state.append(self._updater_for(layer).init_state(p) if p else {})
        self.iteration = 0

    def _updater_for(self, layer: Layer) -> Updater:
        return layer.updater if layer.updater is not None else self.conf.updater

    def _iter_scalar(self, advance: int):
        from ..utils import device_iteration
        return device_iteration(self, advance)

    def num_params(self) -> int:
        return sum(int(np.prod(x.shape)) for p in self.params for x in jax.tree_util.tree_leaves(p))

    def summary(self) -> str:
        """Layer table: name, output shape, param count (reference
        MultiLayerNetwork.summary():3702)."""
        if not self.params:
            raise ValueError("call init() before summary()")
        rows = [("idx", "layer", "out", "params")]
        for i, (layer, p) in enumerate(zip(self.conf.layers, self.params)):
            n = sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(p))
            # the layer's OWN output type — input_types[i+1] would show the
            # next layer's post-preprocessor input instead (e.g. a conv
            # layer reporting the flattened CnnToFeedForward shape)
            out = layer.output_type(self.input_types[i])
            rows.append((str(i), type(layer).__name__, str(out), f"{n:,}"))
        widths = [max(len(r[c]) for r in rows) for c in range(4)]
        lines = ["  ".join(v.ljust(w) for v, w in zip(r, widths)) for r in rows]
        lines.insert(1, "-" * len(lines[0]))
        lines.append(f"total params: {self.num_params():,}")
        return "\n".join(lines)

    # ------------------------------------------------------------------
    # pure forward / loss
    # ------------------------------------------------------------------

    def _apply_layers(self, params, state, x, *, train: bool, rng, mask,
                      upto: Optional[int] = None, carries=None):
        """Run layers [0, upto) returning (y, new_state, mask, activations,
        new_carries).

        ``upto=None`` runs all layers.  The activations list is the
        feedForwardToLayer capture (reference MultiLayerNetwork.java:893) —
        under jit, unused entries are DCE'd so capture is free unless used.
        ``carries`` (list per layer or None) threads recurrent hidden state
        for TBPTT / streaming (reference rnnActivateUsingStoredState).
        """
        n = len(self.conf.layers) if upto is None else upto
        # layers needing the compute dtype independent of their input's
        # dtype (integer-index LSTM inputs) read it from this attribute —
        # refreshed per trace because conf.compute_dtype is user-mutable
        for layer in self.conf.layers:
            layer._compute_dtype = self.conf.compute_dtype
        new_state = list(state)
        new_carries = list(carries) if carries is not None else [None] * len(self.conf.layers)
        acts: List[Array] = []
        x = x.astype(jnp.dtype(self.conf.compute_dtype)) if jnp.issubdtype(x.dtype, jnp.floating) else x
        keys = jax.random.split(rng, n) if (rng is not None and n > 0) else [None] * n
        for i in range(n):
            layer = self.conf.layers[i]
            if i in self.conf.preprocessors:
                pre = self.conf.preprocessors[i]
                if getattr(pre, "wants_rng", False) and keys[i] is not None:
                    # stochastic preprocessors (BinomialSampling) draw fresh
                    # noise from the per-step stream during training
                    x = pre.apply(x, rng=jax.random.fold_in(keys[i], 13))
                else:
                    x = pre.apply(x)
            kwargs = {}
            if layer.recurrent and carries is not None:
                kwargs["carry"] = carries[i]
            p_i = maybe_weight_noise(layer, params[i], train, keys[i])
            out = layer.forward(p_i, state[i], x, train=train, rng=keys[i],
                                mask=mask, **kwargs)
            x, mask = out.y, out.mask
            new_state[i] = out.state
            new_carries[i] = out.carry
            acts.append(x)
        return x, new_state, mask, acts, new_carries

    def _loss(self, params, state, x, labels, *, train: bool, rng,
              mask=None, label_mask=None, carries=None):
        """Full score: output-layer loss + L1/L2 (reference computeGradientAndScore)."""
        n = len(self.conf.layers)
        h, new_state, mask_out, _, new_carries = self._apply_layers(
            params, state, x, train=train, rng=rng, mask=mask, upto=n - 1, carries=carries)
        last = self.conf.layers[n - 1]
        if (n - 1) in self.conf.preprocessors:
            pre = self.conf.preprocessors[n - 1]
            if getattr(pre, "wants_rng", False) and rng is not None:
                h = pre.apply(h, rng=jax.random.fold_in(rng, 20_000 + n))
            else:
                h = pre.apply(h)
        if train and rng is not None:
            # output layers honor input dropout too (reference BaseOutputLayer);
            # _maybe_dropout no-ops when the layer has no dropout configured
            h = last._maybe_dropout(h, train, jax.random.fold_in(rng, n - 1))
        lm = label_mask if label_mask is not None else (mask_out if labels is not None and getattr(labels, "ndim", 0) == 3 else None)
        if not hasattr(last, "score"):
            raise ValueError(f"last layer {type(last).__name__} has no score(); "
                             "use OutputLayer/LossLayer/RnnOutputLayer")
        loss = last.score(params[n - 1], state[n - 1], h, labels, mask=lm)
        if train and hasattr(last, "update_centers"):
            # center-loss moving-average update rides the state path
            new_state[n - 1] = last.update_centers(
                state[n - 1], jax.lax.stop_gradient(h), jax.lax.stop_gradient(labels))
        # accumulate in f64 when computing in f64 (gradient checks), else f32
        acc = jnp.float64 if jnp.dtype(self.conf.compute_dtype) == jnp.float64 else jnp.float32
        reg = jnp.zeros((), acc)
        for layer, p in zip(self.conf.layers, params):
            if p:
                reg = reg + layer.regularization_score(p).astype(acc)
        if train:
            from .layers.base import AUX_LOSS_KEY
            for s in new_state:
                if isinstance(s, dict) and AUX_LOSS_KEY in s:
                    reg = reg + s[AUX_LOSS_KEY].astype(acc)
        total = loss.astype(acc) + reg
        if carries is not None:
            return total, (new_state, new_carries)
        return total, new_state

    # ------------------------------------------------------------------
    # train step (jit once, reuse across iterations)
    # ------------------------------------------------------------------

    def _apply_updates(self, grads, params, opt_state, itf):
        """Shared updater application (the reference's BaseMultiLayerUpdater
        update loop: preApply normalization + per-block updater math)."""
        conf = self.conf
        new_params, new_opt = [], []
        for i, layer in enumerate(conf.layers):
            g, p, os = grads[i], params[i], opt_state[i]
            if not p:
                new_params.append(p)
                new_opt.append(os)
                continue
            if conf.gradient_normalization != GradientNormalization.NONE:
                g = normalize_gradients(g, conf.gradient_normalization,
                                        conf.gradient_normalization_threshold)
            # L2/L1 gradient contribution comes via autodiff of the reg score.
            # apply = updater math + param step; Adam/Nadam route through
            # the fused one-pass kernel (ops/update_kernel.py) when enabled
            p2, os2 = self._updater_for(layer).apply(p, g, os, itf)
            if layer.constraints:
                p2 = apply_constraints(layer.constraints, p2)
            new_params.append(p2)
            new_opt.append(os2)
        return new_params, new_opt

    def _make_step(self):
        def step(params, state, opt_state, it, x, labels, rng, mask, label_mask):
            def loss_fn(p):
                loss, new_state = self._loss(p, state, x, labels, train=True, rng=rng,
                                             mask=mask, label_mask=label_mask)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(grads, params, opt_state,
                                                      it.astype(jnp.float32))
            return new_params, new_state, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    # divergence guard (opt-in)
    # ------------------------------------------------------------------

    def set_nan_guard(self, budget: Optional[int] = 3) -> None:
        """Opt-in divergence guard: every step checks loss + gradients for
        NaN/Inf in-program; a non-finite step applies NO update (params,
        optimizer state, and batch-norm state come back bit-identical) and
        burns one unit of ``budget``.  ``budget`` consecutive bad steps
        raise :class:`DivergenceError` — recoverable under ElasticTrainer,
        which restores the last checkpoint.  ``budget=None`` disables the
        guard; disabled (the default) the training step is the exact same
        jitted program as before — zero cost, bit-identical.

        Cost when enabled: the per-step skipped/ok flag is read on host,
        which turns the async fit_batch chain into one device sync per
        step.  Use it for runs where a poisoned step costs more than the
        sync (large-scale / long-horizon training), not for microbenchmarks.
        """
        if budget is not None and budget < 1:
            raise ValueError(f"nan guard budget must be >= 1, got {budget}")
        self._nan_guard_budget = budget
        self._bad_steps = 0

    @staticmethod
    def _grads_finite(loss, grads):
        """Scalar bool: loss and every gradient leaf are finite."""
        ok = jnp.isfinite(loss)
        for g in jax.tree_util.tree_leaves(grads):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
        return ok

    @staticmethod
    def _select_tree(ok, new, old):
        """tree of where(ok, new, old) — the guarded step's skip switch.
        jnp.where keeps the OLD bits exactly when ok is False (NaNs in the
        rejected branch do not propagate through a select)."""
        return jax.tree_util.tree_map(
            lambda n, o: jnp.where(ok, n, o), new, old)

    def _make_step_guarded(self):
        """_make_step plus the in-program non-finite check: same math on
        the good path, but a step whose loss or gradients contain NaN/Inf
        returns the INPUT params/state/opt-state unchanged (bit-identical)
        together with ok=False, so the host can count bad steps against
        the budget.  Built only when the guard is enabled — the default
        path keeps its exact pre-guard program."""
        def step(params, state, opt_state, it, x, labels, rng, mask, label_mask):
            def loss_fn(p):
                loss, new_state = self._loss(p, state, x, labels, train=True,
                                             rng=rng, mask=mask,
                                             label_mask=label_mask)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            ok = self._grads_finite(loss, grads)
            new_params, new_opt = self._apply_updates(grads, params, opt_state,
                                                      it.astype(jnp.float32))
            return (self._select_tree(ok, new_params, params),
                    self._select_tree(ok, new_state, state),
                    self._select_tree(ok, new_opt, opt_state), loss, ok)

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _note_guarded_step(self, ok: bool) -> None:
        """Host-side budget accounting shared by the plain and sharded
        guarded steps: reset on a good step, escalate past the budget."""
        if ok:
            self._bad_steps = 0
            return
        self._bad_steps += 1
        import logging
        logging.getLogger("deeplearning4j_tpu").warning(
            "non-finite gradients at iteration %d — update skipped "
            "(%d/%d bad steps)", self.iteration, self._bad_steps,
            self._nan_guard_budget)
        if self._bad_steps > self._nan_guard_budget:
            # self-resetting: the raise IS the escalation — whoever catches
            # it (ElasticTrainer) restores a checkpoint, and the fresh run
            # deserves a fresh budget, not an instant re-raise
            bad, self._bad_steps = self._bad_steps, 0
            raise DivergenceError(bad, self._nan_guard_budget)

    def _fit_batch_guarded(self, ds: DataSet):
        """fit_batch through the guarded step (set_nan_guard enabled)."""
        if self._jit_step_guarded is None:
            self._jit_step_guarded = self._make_step_guarded()
        self._rng, sub = jax.random.split(self._rng)
        with obs_trace.span("train/step", cat="train", guarded=True,
                            iteration=self.iteration + 1):
            with obs_trace.span("train/h2d", cat="train"):
                x = _as_device(ds.features)
                y = (None if ds.labels is None
                     else jax.tree_util.tree_map(_as_device, ds.labels))
                m = _as_device(ds.features_mask)
                lm = _as_device(ds.labels_mask)
            with obs_trace.span("train/dispatch", cat="train"):
                self.params, self.state, self.opt_state, loss, ok = \
                    self._jit_step_guarded(
                        self.params, self.state, self.opt_state,
                        self._iter_scalar(1), x, y, sub, m, lm)
        self.iteration += 1
        # the guard's documented cost: reading the flag is a device sync
        self._note_guarded_step(bool(ok))
        score = LazyScore(loss)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, score)
        return score

    def _make_step_tbptt(self):
        """One TBPTT chunk step: like _make_step but threads recurrent
        carries; truncation is automatic because each chunk is its own
        value_and_grad (reference doTruncatedBPTT():1386).  Used for the
        tail chunk when T % tbptt_length != 0."""
        def step(params, state, opt_state, it, x, labels, rng, mask, label_mask, carries):
            def loss_fn(p):
                loss, aux = self._loss(p, state, x, labels, train=True, rng=rng,
                                       mask=mask, label_mask=label_mask, carries=carries)
                return loss, aux

            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(grads, params, opt_state,
                                                      it.astype(jnp.float32))
            return new_params, new_state, new_opt, new_carries, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _make_step_tbptt_scan(self):
        """Whole-batch TBPTT: every T//L chunk optimizer-step runs inside
        ONE jit via lax.scan — one upload + one dispatch per minibatch
        instead of per chunk.  The reference walks chunks in a Java loop
        (doTruncatedBPTT():1386); on a remote TPU each interleaved
        host→device upload costs ~45ms of serialized latency, so chunk
        steps must be fused device-side.  Semantics identical: sequential
        chunk steps, carries threaded, per-chunk iteration counter."""
        L = self.conf.tbptt_length

        def step(params, state, opt_state, it0, x, labels, rng, mask,
                 label_mask, carries):
            n = x.shape[1] // L
            mb = x.shape[0]
            if carries is None:
                # carry init traced into the program — no per-batch eager
                # zeros dispatches on the host
                dtype = jnp.dtype(self.conf.compute_dtype)
                carries = [l.init_carry(mb, dtype) if l.recurrent else None
                           for l in self.conf.layers]

            def chunkify(a):
                """[mb, n·L, ...] → [n, mb, L, ...] scan-major."""
                if a is None:
                    return None
                a2 = a.reshape((a.shape[0], n, L) + a.shape[2:])
                return jnp.moveaxis(a2, 1, 0)

            xs = chunkify(x)
            ys = jax.tree_util.tree_map(chunkify, labels)
            ms = chunkify(mask)
            lms = chunkify(label_mask)
            keys = jax.random.split(rng, n + 1)
            its = it0 + jnp.arange(n, dtype=jnp.int32)

            def body(carry, inp):
                params, state, opt_state, carries = carry
                xc, yc, mc, lmc, k, it = inp

                def loss_fn(p):
                    loss, aux = self._loss(p, state, xc, yc, train=True,
                                           rng=k, mask=mc, label_mask=lmc,
                                           carries=carries)
                    return loss, aux

                (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                new_params, new_opt = self._apply_updates(
                    grads, params, opt_state, it.astype(jnp.float32))
                return (new_params, new_state, new_opt, new_carries), loss

            (params, state, opt_state, carries), losses = jax.lax.scan(
                body, (params, state, opt_state, carries),
                (xs, ys, ms, lms, keys[:n], its))
            # mean + fresh rng computed in-program: a fit_batch with no
            # tail chunk runs exactly ONE device dispatch
            return (params, state, opt_state, carries, losses,
                    jnp.mean(losses), keys[n])

        return jax.jit(step, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    # layerwise unsupervised pretraining
    # ------------------------------------------------------------------

    def pretrainable_layers(self) -> List[int]:
        """Indices of layers with an unsupervised objective (reference
        Layer.isPretrainLayer(): RBM, AutoEncoder, VariationalAutoencoder)."""
        return [i for i, l in enumerate(self.conf.layers)
                if hasattr(l, "contrastive_divergence")
                or hasattr(l, "reconstruction_score")]

    def pretrain(self, data, epochs: int = 1) -> Dict[int, List[float]]:
        """Greedy layerwise unsupervised pretraining (reference
        MultiLayerNetwork.pretrain(DataSetIterator):220): each pretrainable
        layer is trained on features produced by the (already-pretrained)
        layers below it, in order; supervised layers are skipped.  Labels
        in the iterator are ignored.  Follow with ``fit`` for the classic
        pretrain→fine-tune workflow.  Returns {layer_index: losses}."""
        return {i: self.pretrain_layer(i, data, epochs)
                for i in self.pretrainable_layers()}

    def pretrain_layer(self, i: int, data, epochs: int = 1) -> List[float]:
        """Unsupervised pretraining of layer ``i`` only (reference
        pretrainLayer:243): inputs are featurized through layers [0, i)
        in inference mode (no dropout — the layer's own corruption/sampling
        is the only noise source), then the layer's objective — CD-k for
        RBM, reconstruction loss for AutoEncoder, negative ELBO for VAE —
        is driven through the layer's REAL updater (schedules, momentum,
        Adam moments — the reference also routes RBM Gibbs statistics
        through the normal Solver/updater path).  Featurize + objective +
        update run as ONE jitted program per batch."""
        layer = self.conf.layers[i]
        is_rbm = hasattr(layer, "cd_gradients")
        if not is_rbm and not hasattr(layer, "reconstruction_score"):
            raise ValueError(
                f"layer {i} ({type(layer).__name__}) has no unsupervised "
                "objective — pretrainable layers: RBM (contrastive "
                "divergence), AutoEncoder / VariationalAutoencoder "
                "(reconstruction/ELBO)")
        updater = self._updater_for(layer)

        def step(params, state, opt_i, it, x, rng):
            feat, _, _, _, _ = self._apply_layers(
                params, state, x, train=False, rng=None, mask=None, upto=i)
            if i in self.conf.preprocessors:
                pre = self.conf.preprocessors[i]
                if getattr(pre, "wants_rng", False):
                    # stochastic preprocessors (BinomialSampling) must draw
                    # FRESH noise per batch, as in the fit path
                    feat = pre.apply(feat, rng=jax.random.fold_in(rng, 13))
                else:
                    feat = pre.apply(feat)
            if is_rbm:
                g, loss = layer.cd_gradients(params[i], feat, rng)
            else:
                loss, g = jax.value_and_grad(
                    lambda p: layer.reconstruction_score(
                        p, feat, rng=rng, train=True))(params[i])
            if self.conf.gradient_normalization != GradientNormalization.NONE:
                g = normalize_gradients(
                    g, self.conf.gradient_normalization,
                    self.conf.gradient_normalization_threshold)
            p2, opt2 = updater.apply(params[i], g, opt_i, it)
            if layer.constraints:
                p2 = apply_constraints(layer.constraints, p2)
            return p2, opt2, loss

        jit_step = jax.jit(step, donate_argnums=(2,))
        losses: List[float] = []
        it = 0
        for _ in range(epochs):
            for ds in self._as_iterator(data):
                self._rng, sub = jax.random.split(self._rng)
                self.params[i], self.opt_state[i], loss = jit_step(
                    self.params, self.state, self.opt_state[i],
                    np.float32(it), jnp.asarray(ds.features), sub)
                it += 1
                losses.append(LazyScore(loss))
        materialize_scores(losses)
        return losses

    def fit_batch(self, ds: DataSet):
        """One optimization step on one minibatch (reference fit(DataSet)).

        Returns the loss as a :class:`LazyScore` — a float-like view of the
        device scalar that only syncs when read, so chained ``fit_batch``
        calls keep the TPU busy with zero per-step host round trips (the
        readback the reference pays at MultiLayerNetwork.java:1165)."""
        if self.conf.backprop_type == "tbptt":
            if self._nan_guard_budget is not None:
                raise NotImplementedError(
                    "the nan guard does not compose with TBPTT yet — chunk "
                    "steps apply updates inside a scan; run with "
                    "set_nan_guard(None)")
            return self._fit_batch_tbptt(ds)
        if self._nan_guard_budget is not None:
            return self._fit_batch_guarded(ds)
        if self._jit_step is None:
            self._jit_step = self._make_step()
        self._rng, sub = jax.random.split(self._rng)
        # span taxonomy (docs/OBSERVABILITY.md): train/step wraps the
        # host side of one optimizer step; h2d is the batch staging,
        # dispatch the fused XLA program (fwd+bwd+grad-exchange+update
        # run on device inside it).  No-ops when tracing is off.
        with obs_trace.span("train/step", cat="train",
                            iteration=self.iteration + 1):
            with obs_trace.span("train/h2d", cat="train"):
                # device-resident batches (DevicePrefetchIterator /
                # pre-sharded mesh input) pass through _as_device untouched
                x = _as_device(ds.features)
                # labels may be a pytree (e.g. Yolo2OutputLayer's dict
                # targets)
                y = (None if ds.labels is None
                     else jax.tree_util.tree_map(_as_device, ds.labels))
                m = _as_device(ds.features_mask)
                lm = _as_device(ds.labels_mask)
            with obs_trace.span("train/dispatch", cat="train"):
                self.params, self.state, self.opt_state, loss = self._jit_step(
                    self.params, self.state, self.opt_state,
                    self._iter_scalar(1), x, y, sub, m, lm)
        self.iteration += 1
        score = LazyScore(loss)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, score)
        return score

    def _make_multi_step(self):
        """k optimizer steps fused into ONE dispatch via lax.scan over
        stacked batches (round-4 verdict Next #5: the transformer profile
        measured a 12.6% device-IDLE bucket from per-step dispatch gaps on
        the tunnelled chip; chaining k steps amortizes the gap to 1/k).
        Update math and iteration counters match k fit_batch calls
        exactly (bit-for-bit without dropout/noise); the rng STREAM
        differs — one base split fanned to k keys here vs k sequential
        splits there — so stochastic (dropout/weight-noise) runs are
        reproducible within each path but not across the two."""
        def multi(params, state, opt_state, it0, xs, ys, rng, masks, lmasks):
            n = xs.shape[0]
            keys = jax.random.split(rng, n)
            its = it0 + jnp.arange(n, dtype=jnp.int32)

            def body(carry, inp):
                params, state, opt = carry
                x, y, k, it, m, lm = inp

                def loss_fn(p):
                    loss, new_state = self._loss(p, state, x, y, train=True,
                                                 rng=k, mask=m, label_mask=lm)
                    return loss, new_state

                (loss, new_state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                new_params, new_opt = self._apply_updates(
                    grads, params, opt, it.astype(jnp.float32))
                return (new_params, new_state, new_opt), loss

            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state),
                (xs, ys, keys, its, masks, lmasks))
            return params, state, opt_state, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def fit_batches(self, batches):
        """k optimizer steps in ONE device dispatch (lax.scan) over a list
        of same-shaped DataSets.  Per-step listeners fire after the fused
        dispatch with that step's device-resident loss.  TBPTT configs and
        stateful listeners fall back to per-batch fit_batch calls (their
        semantics need params on host mid-run).  Returns [k] LazyScores."""
        batches = list(batches)
        if not batches:
            return []
        if self.conf.backprop_type == "tbptt" or any(
                getattr(l, "requires_model_state", False)
                for l in self.listeners):
            return [self.fit_batch(ds) for ds in batches]
        if self._jit_multi_step is None:
            self._jit_multi_step = self._make_multi_step()

        def stack(get):
            vals = [get(ds) for ds in batches]
            if any(v is None for v in vals):
                if not all(v is None for v in vals):
                    raise ValueError("fit_batches needs uniform masks: "
                                     "all batches or none")
                return None
            return jax.tree_util.tree_map(
                lambda *leaves: jnp.stack([_as_device(a) for a in leaves]),
                *vals)

        self._rng, sub = jax.random.split(self._rng)
        n = len(batches)
        self.params, self.state, self.opt_state, losses = self._jit_multi_step(
            self.params, self.state, self.opt_state, self._iter_scalar(n),
            stack(lambda d: d.features), stack(lambda d: d.labels), sub,
            stack(lambda d: d.features_mask), stack(lambda d: d.labels_mask))
        self.iteration += n
        scores = [LazyScore(losses[i]) for i in range(n)]
        for i, score in enumerate(scores):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration - n + i + 1, score)
        return scores

    def _fit_batch_tbptt(self, ds: DataSet) -> float:
        """Truncated BPTT: slice the time axis into tbptt_length chunks,
        carry recurrent state forward between chunks, one optimizer step per
        chunk (reference doTruncatedBPTT():1386 semantics).  All full
        chunks run in one scanned jit (_make_step_tbptt_scan); a ragged
        tail chunk runs through the per-chunk step."""
        # device arrays pass through untouched (np.asarray would force a
        # device→host round trip); [mb, time, features] dense — or
        # [mb, time] integer indices (sparse inputs gathered by the LSTM /
        # sparse labels one-hotted in the loss)
        def _keep(a):
            return a if isinstance(a, jax.Array) else (
                None if a is None else np.asarray(a))
        x = _keep(ds.features)
        y = _keep(ds.labels)

        def _rank_ok(a):
            return a.ndim == 3 or (a.ndim == 2
                                   and jnp.issubdtype(a.dtype, jnp.integer))
        if not _rank_ok(x) or (y is not None and not _rank_ok(y)):
            raise ValueError("TBPTT requires [mb, time, features] inputs and "
                             "[mb, time, classes] labels (or [mb, time] "
                             "integer index arrays)")
        L = self.conf.tbptt_length
        mb, T = x.shape[0], x.shape[1]
        fm = _keep(ds.features_mask)
        lm = _keep(ds.labels_mask)
        # Listeners that act on the model mid-run (checkpointing, eval)
        # need each chunk's params at callback time — the fused scan only
        # has end-of-batch params, so such listeners route through the
        # per-chunk step loop (slower: one dispatch per chunk).  Plain
        # score/throughput listeners keep the fused path; they get called
        # after the batch with per-chunk losses.
        if any(getattr(l, "requires_model_state", False) for l in self.listeners):
            return self._fit_batch_tbptt_chunked(x, y, fm, lm, mb, T, L)
        n = T // L
        tail = T % L
        carries = None
        chunk_losses = []
        mean_loss = None
        if n:
            if self._jit_step_tbptt_scan is None:
                self._jit_step_tbptt_scan = self._make_step_tbptt_scan()
            cut = None if tail == 0 else n * L
            clip = (lambda a: a) if cut is None else (
                lambda a: None if a is None else a[:, :cut])
            (self.params, self.state, self.opt_state, carries, losses,
             mean_loss, self._rng) = self._jit_step_tbptt_scan(
                self.params, self.state, self.opt_state,
                self._iter_scalar(n),
                jnp.asarray(clip(x)),
                None if y is None else jnp.asarray(clip(y)),
                self._rng, clip(fm), clip(lm), None)
            self.iteration += n
            if self.listeners:
                chunk_losses = [(self.iteration - n + i + 1, LazyScore(losses[i]))
                                for i in range(n)]
        if tail:
            if self._jit_step_tbptt is None:
                self._jit_step_tbptt = self._make_step_tbptt()
            if carries is None:
                dtype = jnp.dtype(self.conf.compute_dtype)
                carries = [l.init_carry(mb, dtype) if l.recurrent else None
                           for l in self.conf.layers]
            s = n * L
            self._rng, sub = jax.random.split(self._rng)
            self.params, self.state, self.opt_state, carries, loss = self._jit_step_tbptt(
                self.params, self.state, self.opt_state,
                self._iter_scalar(1),
                jnp.asarray(x[:, s:]),
                None if y is None else jnp.asarray(y[:, s:]), sub,
                None if fm is None else jnp.asarray(fm[:, s:]),
                None if lm is None else jnp.asarray(lm[:, s:]), carries)
            self.iteration += 1
            if self.listeners:
                chunk_losses.append((self.iteration, LazyScore(loss)))
            mean_loss = loss if mean_loss is None else (
                (mean_loss * n + loss) / (n + 1))
        for it, score in chunk_losses:
            for lst in self.listeners:
                lst.iteration_done(self, it, score)
        return LazyScore(mean_loss)

    def _fit_batch_tbptt_chunked(self, x, y, fm, lm, mb, T, L):
        """Per-chunk TBPTT loop: one dispatch per chunk so listeners with
        ``requires_model_state`` observe each chunk's params (the fused
        scan path only has end-of-batch params)."""
        if self._jit_step_tbptt is None:
            self._jit_step_tbptt = self._make_step_tbptt()
        dtype = jnp.dtype(self.conf.compute_dtype)
        carries = [l.init_carry(mb, dtype) if l.recurrent else None
                   for l in self.conf.layers]
        total, chunks = None, 0
        for s in range(0, T, L):
            self._rng, sub = jax.random.split(self._rng)
            self.params, self.state, self.opt_state, carries, loss = self._jit_step_tbptt(
                self.params, self.state, self.opt_state,
                self._iter_scalar(1),
                jnp.asarray(x[:, s:s + L]),
                None if y is None else jnp.asarray(y[:, s:s + L]), sub,
                None if fm is None else jnp.asarray(fm[:, s:s + L]),
                None if lm is None else jnp.asarray(lm[:, s:s + L]), carries)
            self.iteration += 1
            total = loss if total is None else total + loss
            chunks += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration, LazyScore(loss))
        return LazyScore(total / max(chunks, 1))

    # ------------------------------------------------------------------
    # streaming RNN inference (rnnTimeStep parity)
    # ------------------------------------------------------------------

    def rnn_time_step(self, x) -> np.ndarray:
        """Stateful streaming inference: feeds [mb, f] (one step) or
        [mb, t, f] — or [mb] / [mb, t] integer index inputs — and keeps
        hidden state across calls (reference rnnTimeStep():2636)."""
        xa = jnp.asarray(x)
        if jnp.issubdtype(xa.dtype, jnp.integer):
            squeeze = xa.ndim == 1
            if squeeze:
                xa = xa[:, None]
        else:
            squeeze = xa.ndim == 2
            if squeeze:
                xa = xa[:, None, :]
        mb = xa.shape[0]
        if self._stream_carries is not None:
            for c in jax.tree_util.tree_leaves(self._stream_carries):
                if c.shape[0] != mb:  # batch size changed → fresh state
                    self._stream_carries = None
                break
        if self._stream_carries is None:
            dtype = jnp.dtype(self.conf.compute_dtype)
            self._stream_carries = [l.init_carry(mb, dtype) if l.recurrent else None
                                    for l in self.conf.layers]
        if self._jit_stream is None:
            def fwd(params, state, xx, carries):
                y, _, _, _, new_carries = self._apply_layers(
                    params, state, xx, train=False, rng=None, mask=None, carries=carries)
                return y, new_carries
            self._jit_stream = jax.jit(fwd)
        y, self._stream_carries = self._jit_stream(self.params, self.state, xa,
                                                   self._stream_carries)
        out = np.asarray(y)
        return out[:, 0] if squeeze and out.ndim == 3 else out

    def rnn_clear_previous_state(self) -> None:
        """Reset streaming state (reference rnnClearPreviousState)."""
        self._stream_carries = None

    def fit(self, data, epochs: int = 1) -> List[float]:
        """Train over a DataSetIterator / DataSet / (x, y) for N epochs
        (reference fit(DataSetIterator):1165; async prefetch is the
        iterator's job — wrap with AsyncDataSetIterator for host-side
        parity, or DevicePrefetchIterator to keep batches already
        transferred/normalized on device: fit_batch accepts its
        device-resident pytrees without re-staging them)."""
        it = self._as_iterator(data)
        losses: List[float] = []
        synced = 0
        for _ in range(epochs):
            for ds in it:
                losses.append(self.fit_batch(ds))
            synced = self._end_epoch(losses, synced)
        return losses

    def _end_epoch(self, losses, synced: int) -> int:
        """Epoch epilogue shared by fit() and ShardedTrainer.fit — ONE
        place, so epoch semantics can't diverge between plain and mesh
        training: materialize the epoch's scores in one batched device
        transfer (keeps the intra-epoch loop async while freeing the
        per-step 0-d buffers), bump the counter, fire epoch_done
        listeners.  Returns the new synced watermark."""
        materialize_scores(losses[synced:])
        self.epoch += 1
        for lst in self.listeners:
            if hasattr(lst, "epoch_done"):
                lst.epoch_done(self, self.epoch)
        return len(losses)

    @staticmethod
    def _as_iterator(data) -> DataSetIterator:
        if isinstance(data, DataSetIterator):
            return data
        if isinstance(data, DataSet):
            return ListDataSetIterator([data])
        if isinstance(data, tuple) and len(data) == 2:
            return ListDataSetIterator([DataSet(np.asarray(data[0]), np.asarray(data[1]))])
        raise TypeError(f"cannot iterate {type(data)}")

    # ------------------------------------------------------------------
    # inference / scoring
    # ------------------------------------------------------------------

    def output(self, x, mask=None) -> np.ndarray:
        """Inference activations of the last layer (reference output():1867)."""
        if self._jit_output is None:
            def fwd(params, state, xx, m):
                y, _, _, _, _ = self._apply_layers(params, state, xx, train=False, rng=None, mask=m)
                return y
            self._jit_output = jax.jit(fwd)
        y = self._jit_output(self.params, self.state, jnp.asarray(x),
                             None if mask is None else jnp.asarray(mask))
        return np.asarray(y)

    def feed_forward(self, x, train: bool = False) -> List[np.ndarray]:
        """All layer activations (reference feedForward(); activation-capture
        mode for transfer learning / debugging)."""
        _, _, _, acts, _ = self._apply_layers(self.params, self.state, jnp.asarray(x),
                                              train=train, rng=None, mask=None)
        return [np.asarray(a) for a in acts]

    def score_examples(self, ds: DataSet,
                       add_regularization_terms: bool = True) -> np.ndarray:
        """Per-example scores [N] WITHOUT batch reduction (reference
        MultiLayerNetwork.scoreExamples:2139,2156).  With
        ``add_regularization_terms`` the network's L1/L2 score is added to
        every example (the reference's semantics).  For unmasked
        feed-forward outputs ``mean(score_examples(ds, True)) ==
        score(ds)`` exactly; RNN outputs sum the per-timestep loss over the
        sequence (reference semantics), so there mean == t·score, and
        per-timestep masks weight examples differently from score()'s
        present-entry normalization.  Runs as one jitted program."""
        if self._jit_score_examples is None:
            def fn(params, state, x, y, m, lm, add_reg):
                n = len(self.conf.layers)
                h, _, mask_out, _, _ = self._apply_layers(
                    params, state, x, train=False, rng=None, mask=m,
                    upto=n - 1)
                last = self.conf.layers[n - 1]
                if (n - 1) in self.conf.preprocessors:
                    h = self.conf.preprocessors[n - 1].apply(h)
                if not hasattr(last, "score_examples"):
                    raise ValueError(
                        f"last layer {type(last).__name__} has no "
                        "score_examples(); supported: OutputLayer, "
                        "LossLayer, RnnOutputLayer, CenterLossOutputLayer")
                lmask = lm if lm is not None else (
                    mask_out if y is not None and getattr(y, "ndim", 0) == 3
                    else None)
                pe = last.score_examples(params[n - 1], state[n - 1], h, y,
                                         mask=lmask)
                reg = jnp.zeros((), pe.dtype)
                for layer, p in zip(self.conf.layers, params):
                    if p:
                        reg = reg + layer.regularization_score(p).astype(pe.dtype)
                return jnp.where(add_reg, pe + reg, pe)

            self._jit_score_examples = jax.jit(fn, static_argnums=())
        pe = self._jit_score_examples(
            self.params, self.state, jnp.asarray(ds.features),
            None if ds.labels is None else jax.tree_util.tree_map(jnp.asarray, ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
            jnp.asarray(add_regularization_terms))
        return np.asarray(pe)

    def reconstruction_log_probability(self, x, layer_index: Optional[int] = None,
                                       num_samples: int = 5) -> np.ndarray:
        """Importance-weighted log p(x) per example from a
        VariationalAutoencoder layer (reference
        VariationalAutoencoder.reconstructionLogProbability:977): inputs are
        featurized through the layers below it, then the layer's IWAE bound
        runs with ``num_samples`` importance samples.  ``layer_index=None``
        uses the first VAE layer."""
        if layer_index is None:
            layer_index = next(
                (i for i, l in enumerate(self.conf.layers)
                 if hasattr(l, "reconstruction_log_probability")), None)
            if layer_index is None:
                raise ValueError("no VariationalAutoencoder layer in this network")
        layer = self.conf.layers[layer_index]
        if not hasattr(layer, "reconstruction_log_probability"):
            raise ValueError(f"layer {layer_index} ({type(layer).__name__}) "
                             "is not a VariationalAutoencoder")
        self._rng, sub = jax.random.split(self._rng)

        key = (layer_index, num_samples)
        if self._jit_recon_logprob.get(key) is None:
            def fn(params, state, xx, rng):
                feat, _, _, _, _ = self._apply_layers(
                    params, state, xx, train=False, rng=None, mask=None,
                    upto=layer_index)
                if layer_index in self.conf.preprocessors:
                    feat = self.conf.preprocessors[layer_index].apply(feat)
                return layer.reconstruction_log_probability(
                    params[layer_index], feat, rng=rng,
                    num_samples=num_samples)

            self._jit_recon_logprob[key] = jax.jit(fn)
        return np.asarray(self._jit_recon_logprob[key](
            self.params, self.state, jnp.asarray(x), sub))

    def reconstruction_probability(self, x, layer_index: Optional[int] = None,
                                   num_samples: int = 5) -> np.ndarray:
        """exp(reconstruction_log_probability) — reference
        reconstructionProbability; prefer the log form for high-dim data."""
        return np.exp(self.reconstruction_log_probability(
            x, layer_index, num_samples))

    def score(self, ds: DataSet) -> float:
        """Loss on a DataSet without updating (reference score(DataSet))."""
        if self._jit_score is None:
            def score_fn(params, state, x, y, m, lm):
                loss, _ = self._loss(params, state, x, y, train=False, rng=None,
                                     mask=m, label_mask=lm)
                return loss
            self._jit_score = jax.jit(score_fn)
        loss = self._jit_score(
            self.params, self.state, jnp.asarray(ds.features),
            None if ds.labels is None else jax.tree_util.tree_map(jnp.asarray, ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask))
        return float(loss)

    def evaluate(self, data, evaluation=None):
        """Accumulate classification metrics over an iterator (reference
        MultiLayerNetwork.evaluate → eval/Evaluation)."""
        from ..evaluation.evaluation import Evaluation
        ev = evaluation if evaluation is not None else Evaluation()
        for ds in self._as_iterator(data):
            out = self.output(ds.features, mask=ds.features_mask)
            ev.eval(ds.labels, out, mask=ds.labels_mask)
        return ev

    # ------------------------------------------------------------------
    # listeners / serde
    # ------------------------------------------------------------------

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def clone_params(self):
        return jax.tree_util.tree_map(lambda a: a, self.params)

    def save(self, path: str, save_updater: bool = True) -> None:
        from ..utils.serializer import save_model
        save_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "MultiLayerNetwork":
        from ..utils.serializer import load_model
        return load_model(path, load_updater=load_updater)
