"""Transfer learning — surgery on trained networks.

Parity target: reference nn/transferlearning/TransferLearning.java (847 LoC
Builder/GraphBuilder), FineTuneConfiguration, TransferLearningHelper
(featurization), nn/layers/FrozenLayer.

Because params are per-layer dicts (not one flat buffer), surgery is
structural: freeze = wrap conf layer in FrozenLayer (same param tree, zero
gradients via stop_gradient); replace/append layers = re-init just those
entries.  The reference's nOutReplace weight re-init is ``n_out_replace``.
"""

from __future__ import annotations

import copy
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..datasets.dataset import DataSet
from .layers.base import Layer
from .layers.special import FrozenLayer
from .multilayer import MultiLayerConfiguration, MultiLayerNetwork


class TransferLearning:
    """Builder for network surgery (reference TransferLearning.Builder).

    >>> new_net = (TransferLearning(trained)
    ...            .fine_tune_configuration(updater=Adam(lr=1e-4))
    ...            .set_feature_extractor(1)        # freeze layers 0..1
    ...            .remove_output_layer()
    ...            .add_layer(OutputLayer(n_out=5, activation="softmax"))
    ...            .build())
    """

    def __init__(self, net: MultiLayerNetwork):
        self._src = net
        self._conf = copy.deepcopy(net.conf)
        self._params = [dict(p) for p in net.params]
        self._state = [dict(s) for s in net.state]
        self._freeze_upto: Optional[int] = None
        self._appended: List[Layer] = []
        self._removed = 0
        self._nout_replace: Optional[tuple] = None

    def fine_tune_configuration(self, updater=None, seed: Optional[int] = None,
                                **conf_overrides) -> "TransferLearning":
        """Override global training conf (reference FineTuneConfiguration)."""
        if updater is not None:
            self._conf.updater = updater
        if seed is not None:
            self._conf.seed = seed
        for k, v in conf_overrides.items():
            if not hasattr(self._conf, k):
                raise ValueError(f"unknown conf field '{k}'")
            setattr(self._conf, k, v)
        return self

    def set_feature_extractor(self, layer_index: int) -> "TransferLearning":
        """Freeze layers [0, layer_index] (reference setFeatureExtractor)."""
        self._freeze_upto = layer_index
        return self

    def remove_output_layer(self) -> "TransferLearning":
        return self.remove_last_layers(1)

    def remove_last_layers(self, n: int) -> "TransferLearning":
        self._removed += n
        return self

    def add_layer(self, layer: Layer) -> "TransferLearning":
        self._appended.append(layer)
        return self

    def n_out_replace(self, layer_index: int, n_out: int,
                      weight_init: str = "xavier") -> "TransferLearning":
        """Change a layer's n_out and re-init it + the next layer's n_in
        (reference nOutReplace)."""
        self._nout_replace = (layer_index, n_out, weight_init)
        return self

    def build(self) -> MultiLayerNetwork:
        conf = self._conf
        params = self._params
        state = self._state

        # 1. remove tail layers
        if self._removed:
            conf.layers = conf.layers[:-self._removed]
            params = params[:-self._removed]
            state = state[:-self._removed]
            for i in list(conf.preprocessors):
                if i >= len(conf.layers):
                    del conf.preprocessors[i]

        # 2. append new layers (params initialized after type inference)
        n_carried = len(conf.layers)
        conf.layers = conf.layers + list(self._appended)

        # 3. nOut replacement
        if self._nout_replace is not None:
            idx, n_out, winit = self._nout_replace
            conf.layers[idx].n_out = n_out
            conf.layers[idx].weight_init = winit
            conf.layers[idx].n_in = 0  # re-infer
            if idx + 1 < len(conf.layers) and hasattr(conf.layers[idx + 1], "n_in"):
                conf.layers[idx + 1].n_in = 0

        # 4. freeze
        if self._freeze_upto is not None:
            for i in range(self._freeze_upto + 1):
                if not isinstance(conf.layers[i], FrozenLayer):
                    conf.layers[i] = FrozenLayer(layer=conf.layers[i])

        # 5. build net, re-init, then splice carried params back in
        net = MultiLayerNetwork(conf)
        net.init()
        reinit = set()
        if self._nout_replace is not None:
            reinit = {self._nout_replace[0], self._nout_replace[0] + 1}
        for i in range(min(n_carried, len(conf.layers))):
            if i in reinit:
                continue
            if params[i]:
                net.params[i] = params[i]
                net.state[i] = state[i]
        return net


class TransferLearningHelper:
    """Featurization helper (reference TransferLearningHelper): run the
    frozen front once per dataset, then train only the unfrozen tail —
    saving the frozen forward on every epoch."""

    def __init__(self, net: MultiLayerNetwork, frozen_upto: int):
        self.full = net
        self.frozen_upto = frozen_upto
        # tail net: layers after the frozen point, sharing param arrays
        tail_conf = copy.deepcopy(net.conf)
        tail_conf.layers = net.conf.layers[frozen_upto + 1:]
        tail_conf.preprocessors = {
            i - (frozen_upto + 1): p for i, p in net.conf.preprocessors.items()
            if i > frozen_upto}
        # tail input = OUTPUT type of layer frozen_upto, pre-preprocessor:
        # the carried-over preprocessor at tail index 0 will re-apply its
        # transform during _infer_types, and featurize() emits raw layer
        # activations — using the post-preprocessor type here would apply
        # the transform twice.
        if frozen_upto + 1 < len(net.input_types):
            tail_conf.input_type = net.conf.layers[frozen_upto].output_type(
                net.input_types[frozen_upto])
        else:
            tail_conf.input_type = net.output_type
        self.tail = MultiLayerNetwork(tail_conf)
        self.tail.init()
        self.tail.params = net.params[frozen_upto + 1:]
        self.tail.state = net.state[frozen_upto + 1:]

    def featurize(self, ds: DataSet) -> DataSet:
        """Forward through the frozen front (reference featurize)."""
        acts = self.full.feed_forward(ds.features)
        return DataSet(np.asarray(acts[self.frozen_upto]), ds.labels,
                       ds.features_mask, ds.labels_mask)

    def fit_featurized(self, ds: DataSet, epochs: int = 1):
        losses = self.tail.fit(ds, epochs=epochs)
        # write trained tail params back into the full network
        for j, p in enumerate(self.tail.params):
            self.full.params[self.frozen_upto + 1 + j] = p
            self.full.state[self.frozen_upto + 1 + j] = self.tail.state[j]
        return losses

    def output(self, x):
        return self.full.output(x)
