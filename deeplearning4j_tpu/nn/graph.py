"""ComputationGraph — arbitrary-DAG model container.

Parity target: reference nn/graph/ComputationGraph.java (3,379 LoC; topo
sort :394,727-742, fit :866, computeGradientAndScore :1295) plus the 14
GraphVertex impls (nn/graph/vertex/: LayerVertex, MergeVertex,
ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex, ReshapeVertex,
ScaleVertex, ShiftVertex, L2Vertex, L2NormalizeVertex, PoolHelperVertex,
PreprocessorVertex, InputVertex) and the rnn vertices
(conf/graph/rnn/LastTimeStepVertex, DuplicateToTimeSeriesVertex).

Same design inversion as MultiLayerNetwork: the reference walks the topo
order twice per iteration calling eager doForward/doBackward per vertex
(GraphVertex.java:117-123); here one traced function evaluates the DAG and
jax.grad differentiates it, all fused into a single XLA program per step.

Vertices are registered dataclasses: ``forward(inputs, ...)`` for pure
shape/math vertices; LayerVertex wraps any Layer.  Multi-input/multi-output
training uses MultiDataSet; single-in/single-out works with plain DataSet.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..datasets.dataset import DataSet, MultiDataSet
from ..datasets.iterators import DataSetIterator, ListDataSetIterator
from .conf.inputs import InputType
from .conf.regularizers import apply_constraints, maybe_weight_noise
from .layers.base import Layer, config_from_dict, config_to_dict, register_config
from .updaters import Adam, GradientNormalization, Updater, normalize_gradients
from ..optimize.score import LazyScore, materialize_scores

Array = jax.Array


# ---------------------------------------------------------------------------
# graph vertices (non-layer)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GraphVertex:
    """Base for parameter-free DAG vertices."""

    def forward(self, inputs: List[Array], masks: List[Optional[Array]]):
        raise NotImplementedError

    def output_type(self, in_types: List[InputType]) -> InputType:
        return in_types[0]

    def output_mask(self, masks: List[Optional[Array]]) -> Optional[Array]:
        for m in masks:
            if m is not None:
                return m
        return None


@register_config
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel (last) axis (reference
    MergeVertex: NCHW channel concat ≡ NHWC last-axis concat)."""

    def forward(self, inputs, masks):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, in_types):
        t0 = in_types[0]
        if t0.kind == "cnn":
            return InputType.convolutional(t0.height, t0.width,
                                           sum(t.channels for t in in_types))
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in in_types), t0.timesteps)
        return InputType.feed_forward(sum(t.size for t in in_types))


@register_config
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """add / subtract / product / average / max of equal-shape inputs
    (reference ElementWiseVertex.Op)."""

    op: str = "add"

    def forward(self, inputs, masks):
        if self.op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if self.op == "subtract":
            return inputs[0] - inputs[1]
        if self.op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if self.op == "average":
            return sum(inputs) / len(inputs)
        if self.op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"unknown ElementWise op {self.op}")


@register_config
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (reference SubsetVertex)."""

    from_idx: int = 0
    to_idx: int = 0

    def forward(self, inputs, masks):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def output_type(self, in_types):
        n = self.to_idx - self.from_idx + 1
        t = in_types[0]
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "cnn":
            # forward() slices the channel (last, NHWC) axis
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)


@register_config
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Stack along the batch axis (reference StackVertex)."""

    def forward(self, inputs, masks):
        return jnp.concatenate(inputs, axis=0)


@register_config
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``stack_size`` along batch (reference UnstackVertex)."""

    index: int = 0
    stack_size: int = 1

    def forward(self, inputs, masks):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.index * step:(self.index + 1) * step]


@register_config
@dataclasses.dataclass
class ReshapeVertex(GraphVertex):
    """Reshape trailing dims, batch preserved (reference ReshapeVertex)."""

    shape: List[int] = dataclasses.field(default_factory=list)

    def forward(self, inputs, masks):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))

    def output_type(self, in_types):
        if len(self.shape) == 1:
            return InputType.feed_forward(self.shape[0])
        if len(self.shape) == 3:
            return InputType.convolutional(*self.shape)
        if len(self.shape) == 2:
            return InputType.recurrent(self.shape[1], self.shape[0])
        return in_types[0]


@register_config
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    factor: float = 1.0

    def forward(self, inputs, masks):
        return inputs[0] * self.factor


@register_config
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def forward(self, inputs, masks):
        return inputs[0] + self.shift


@register_config
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def forward(self, inputs, masks):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=tuple(range(1, x.ndim)), keepdims=True))
        return x / jnp.maximum(norm, self.eps)


@register_config
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs → [mb, 1] (reference L2Vertex)."""

    eps: float = 1e-8

    def forward(self, inputs, masks):
        a, b = inputs[0], inputs[1]
        d = (a - b).reshape((a.shape[0], -1))
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps)

    def output_type(self, in_types):
        return InputType.feed_forward(1)


@register_config
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps an InputPreProcessor as a vertex (reference PreprocessorVertex)."""

    preprocessor: Any = None

    def forward(self, inputs, masks):
        return self.preprocessor.apply(inputs[0])

    def output_type(self, in_types):
        return self.preprocessor.output_type(in_types[0])


@register_config
@dataclasses.dataclass
class PoolHelperVertex(GraphVertex):
    """Strips the first row/col of a CNN activation (reference
    PoolHelperVertex — GoogLeNet ceil-pooling import shim)."""

    def forward(self, inputs, masks):
        return inputs[0][:, 1:, 1:, :]

    def output_type(self, in_types):
        t = in_types[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)


@register_config
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """[mb,t,f] → [mb,f] last present timestep, honoring the input's mask
    (reference conf/graph/rnn/LastTimeStepVertex)."""

    def forward(self, inputs, masks):
        x = inputs[0]
        m = masks[0]
        if m is not None:
            idx = jnp.maximum(jnp.sum(m.astype(jnp.int32), axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx]
        return x[:, -1]

    def output_type(self, in_types):
        return InputType.feed_forward(in_types[0].size)

    def output_mask(self, masks):
        return None


@register_config
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[mb,f] → [mb,t,f], t taken from a reference rnn input (reference
    DuplicateToTimeSeriesVertex; the second input supplies the length)."""

    def forward(self, inputs, masks):
        x, ref = inputs[0], inputs[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], ref.shape[1], x.shape[1]))

    def output_type(self, in_types):
        return InputType.recurrent(in_types[0].size, in_types[1].timesteps)

    def output_mask(self, masks):
        return masks[1] if len(masks) > 1 else None


@register_config
@dataclasses.dataclass
class LayerVertex(GraphVertex):
    """Wraps any Layer as a DAG vertex (reference vertex/impl/LayerVertex)."""

    layer: Optional[Layer] = None


# ---------------------------------------------------------------------------
# configuration + builder
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class VertexSpec:
    name: str
    vertex: Any              # LayerVertex or GraphVertex subclass
    inputs: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ComputationGraphConfiguration:
    """DAG config (reference ComputationGraphConfiguration + GraphBuilder)."""

    network_inputs: List[str] = dataclasses.field(default_factory=list)
    input_types: Dict[str, InputType] = dataclasses.field(default_factory=dict)
    vertices: List[VertexSpec] = dataclasses.field(default_factory=list)
    network_outputs: List[str] = dataclasses.field(default_factory=list)
    updater: Updater = dataclasses.field(default_factory=Adam)
    gradient_normalization: str = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    seed: int = 12345
    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    backprop_type: str = "standard"       # or "tbptt"
    tbptt_length: int = 20

    def to_dict(self) -> dict:
        return {
            "type": "ComputationGraphConfiguration",
            "network_inputs": list(self.network_inputs),
            "input_types": {k: v.to_dict() for k, v in self.input_types.items()},
            "vertices": [
                {"name": v.name, "vertex": config_to_dict(v.vertex), "inputs": list(v.inputs)}
                for v in self.vertices
            ],
            "network_outputs": list(self.network_outputs),
            "updater": config_to_dict(self.updater),
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold": self.gradient_normalization_threshold,
            "seed": self.seed,
            "param_dtype": self.param_dtype,
            "compute_dtype": self.compute_dtype,
            "backprop_type": self.backprop_type,
            "tbptt_length": self.tbptt_length,
        }

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            network_inputs=list(d["network_inputs"]),
            input_types={k: InputType.from_dict(v) for k, v in d["input_types"].items()},
            vertices=[VertexSpec(v["name"], config_from_dict(v["vertex"]), list(v["inputs"]))
                      for v in d["vertices"]],
            network_outputs=list(d["network_outputs"]),
            updater=config_from_dict(d["updater"]),
            gradient_normalization=d.get("gradient_normalization", GradientNormalization.NONE),
            gradient_normalization_threshold=d.get("gradient_normalization_threshold", 1.0),
            seed=d.get("seed", 12345),
            param_dtype=d.get("param_dtype", "float32"),
            compute_dtype=d.get("compute_dtype", "float32"),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_length=d.get("tbptt_length", 20),
        )


class GraphBuilder:
    """Fluent DAG builder (reference ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self):
        self._conf = ComputationGraphConfiguration()

    def seed(self, s: int) -> "GraphBuilder":
        self._conf.seed = s
        return self

    def updater(self, u: Updater) -> "GraphBuilder":
        self._conf.updater = u
        return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0) -> "GraphBuilder":
        self._conf.gradient_normalization = mode
        self._conf.gradient_normalization_threshold = threshold
        return self

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_inputs.extend(names)
        return self

    def set_input_types(self, **types: InputType) -> "GraphBuilder":
        self._conf.input_types.update(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        self._conf.vertices.append(VertexSpec(name, LayerVertex(layer=layer), list(inputs)))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._conf.vertices.append(VertexSpec(name, vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_outputs.extend(names)
        return self

    def tbptt(self, length: int) -> "GraphBuilder":
        """Truncated BPTT over the time axis (reference GraphBuilder
        .backpropType(TruncatedBPTT).tBPTTLength)."""
        self._conf.backprop_type = "tbptt"
        self._conf.tbptt_length = length
        return self

    def build(self) -> ComputationGraphConfiguration:
        return self._conf


# ---------------------------------------------------------------------------
# runtime
# ---------------------------------------------------------------------------


class ComputationGraph:
    """DAG model with the MultiLayerNetwork training surface.

    Params/state/opt-state are dicts keyed by vertex name (vs. the
    reference's flattened views)."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Dict[str, Dict[str, Array]] = {}
        self.state: Dict[str, Dict[str, Array]] = {}
        self.opt_state: Dict[str, Dict] = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[Any] = []
        self._jit_step = None
        self._jit_step_tbptt = None
        self._jit_step_tbptt_scan = None
        self._jit_multi_step = None
        self._it_dev = None        # device-resident iteration counter
        self._it_dev_val = -1
        self._jit_output = None
        self._jit_score_examples = None
        self._jit_stream = None
        self._stream_carries = None
        self._rng = jax.random.PRNGKey(conf.seed)
        self._spec_by_name = {v.name: v for v in conf.vertices}
        self.topo_order = self._topological_sort()
        self.vertex_in_types: Dict[str, List[InputType]] = {}
        self.vertex_out_types: Dict[str, InputType] = {}
        self._infer_types()

    # -- structure ---------------------------------------------------------

    def _topological_sort(self) -> List[str]:
        """Kahn topo sort of vertex names (reference topo sort :394,727-742)."""
        spec_by_name = self._spec_by_name
        for s in self.conf.vertices:
            for inp in s.inputs:
                if inp not in spec_by_name and inp not in self.conf.network_inputs:
                    raise ValueError(f"vertex '{s.name}' references unknown input '{inp}'")
        indeg = {v.name: 0 for v in self.conf.vertices}
        dependents: Dict[str, List[str]] = {n: [] for n in indeg}
        for s in self.conf.vertices:
            for inp in s.inputs:
                if inp in spec_by_name:
                    indeg[s.name] += 1
                    dependents[inp].append(s.name)
        order = [n for n, d in sorted(indeg.items()) if d == 0]
        queue = list(order)
        seen = set(order)
        result = []
        while queue:
            n = queue.pop(0)
            result.append(n)
            for dep in dependents[n]:
                indeg[dep] -= 1
                if indeg[dep] == 0 and dep not in seen:
                    seen.add(dep)
                    queue.append(dep)
        if len(result) != len(self.conf.vertices):
            cyc = set(indeg) - set(result)
            raise ValueError(f"graph has a cycle involving {sorted(cyc)}")
        return result

    def _spec(self, name: str) -> VertexSpec:
        return self._spec_by_name[name]

    def _infer_types(self) -> None:
        types: Dict[str, InputType] = dict(self.conf.input_types)
        if not types:
            return
        for name in self.topo_order:
            spec = self._spec(name)
            in_types = [types[i] for i in spec.inputs]
            self.vertex_in_types[name] = in_types
            if isinstance(spec.vertex, LayerVertex):
                layer = spec.vertex.layer
                t = in_types[0]
                layer.infer_nin(t)
                types[name] = layer.output_type(t)
            else:
                types[name] = spec.vertex.output_type(in_types)
            self.vertex_out_types[name] = types[name]

    # -- init --------------------------------------------------------------

    def init(self, rng: Optional[Array] = None) -> None:
        if not self.vertex_out_types:
            raise ValueError("set_input_types(...) required before init()")
        rng = rng if rng is not None else self._rng
        dtype = jnp.dtype(self.conf.param_dtype)
        keys = jax.random.split(rng, max(len(self.conf.vertices), 1))
        self.params, self.state, self.opt_state = {}, {}, {}
        for k, spec in zip(keys, self.conf.vertices):
            if isinstance(spec.vertex, LayerVertex):
                layer = spec.vertex.layer
                t = self.vertex_in_types[spec.name][0]
                p = layer.init_params(k, t, dtype)
                self.params[spec.name] = p
                self.state[spec.name] = layer.init_state(t, dtype)
                self.opt_state[spec.name] = (
                    self._updater_for(layer).init_state(p) if p else {})
            else:
                self.params[spec.name] = {}
                self.state[spec.name] = {}
                self.opt_state[spec.name] = {}
        self.iteration = 0

    def _updater_for(self, layer: Layer) -> Updater:
        return layer.updater if layer.updater is not None else self.conf.updater

    def num_params(self) -> int:
        return sum(int(np.prod(x.shape))
                   for p in self.params.values()
                   for x in jax.tree_util.tree_leaves(p))

    def summary(self) -> str:
        """Vertex table in topological order: name, type, inputs, param
        count (reference ComputationGraph.summary():3967)."""
        if not self.params:
            raise ValueError("call init() before summary()")
        rows = [("vertex", "type", "inputs", "params")]
        for name in self.topo_order:
            spec = self._spec(name)
            v = spec.vertex
            tname = (type(v.layer).__name__ if isinstance(v, LayerVertex)
                     else type(v).__name__)
            n = sum(int(np.prod(x.shape)) for x in
                    jax.tree_util.tree_leaves(self.params.get(name, {})))
            rows.append((name, tname, ",".join(spec.inputs) or "-", f"{n:,}"))
        widths = [max(len(r[c]) for r in rows) for c in range(4)]
        lines = ["  ".join(val.ljust(w) for val, w in zip(r, widths))
                 for r in rows]
        lines.insert(1, "-" * len(lines[0]))
        lines.append(f"total params: {self.num_params():,}")
        return "\n".join(lines)

    # -- pure forward / loss ------------------------------------------------

    def _apply(self, params, state, inputs: Dict[str, Array], *, train: bool, rng,
               masks: Optional[Dict[str, Optional[Array]]] = None,
               stop_before_output_score: bool = False, carries=None):
        """Evaluate the DAG.  Returns (activations dict, new_state, masks
        dict, new_carries).

        When ``stop_before_output_score`` the output LayerVertices are NOT
        applied (their score() consumes the pre-layer activations).
        ``carries`` (dict name→carry, None entries for stateless vertices)
        threads recurrent hidden state through LayerVertices for TBPTT /
        streaming — the DAG analog of the reference's
        rnnActivateUsingStoredState (ComputationGraph.java:1602)."""
        compute = jnp.dtype(self.conf.compute_dtype)
        # integer-index inputs can't carry the compute dtype — stamp it on
        # layers so e.g. LSTM gathers in the right precision
        for spec in self.conf.vertices:
            if getattr(spec.vertex, "layer", None) is not None:
                spec.vertex.layer._compute_dtype = self.conf.compute_dtype
        acts: Dict[str, Array] = {}
        mks: Dict[str, Optional[Array]] = {}
        for k, v in inputs.items():
            acts[k] = v.astype(compute) if jnp.issubdtype(v.dtype, jnp.floating) else v
            mks[k] = (masks or {}).get(k)
        new_state = dict(state)
        new_carries = dict(carries) if carries is not None else {}
        keys = (jax.random.split(rng, len(self.topo_order))
                if rng is not None else [None] * len(self.topo_order))
        for key, name in zip(keys, self.topo_order):
            spec = self._spec(name)
            if stop_before_output_score and name in self.conf.network_outputs:
                continue
            xin = [acts[i] for i in spec.inputs]
            min_ = [mks[i] for i in spec.inputs]
            if isinstance(spec.vertex, LayerVertex):
                layer = spec.vertex.layer
                kwargs = {}
                if layer.recurrent and carries is not None:
                    kwargs["carry"] = carries.get(name)
                p_v = maybe_weight_noise(layer, params[name], train, key)
                out = layer.forward(
                    p_v, state[name], xin[0], train=train, rng=key,
                    mask=min_[0], **kwargs)
                acts[name], mks[name] = out.y, out.mask
                new_state[name] = out.state
                if layer.recurrent and carries is not None:
                    new_carries[name] = out.carry
            else:
                acts[name] = spec.vertex.forward(xin, min_)
                mks[name] = spec.vertex.output_mask(min_)
        return acts, new_state, mks, new_carries

    def _iter_scalar(self, advance: int):
        from ..utils import device_iteration
        return device_iteration(self, advance)

    def _init_carries(self, mb: int) -> Dict[str, Any]:
        """Zero carries for every recurrent LayerVertex (None elsewhere)."""
        dtype = jnp.dtype(self.conf.compute_dtype)
        carries: Dict[str, Any] = {}
        for spec in self.conf.vertices:
            if isinstance(spec.vertex, LayerVertex) and spec.vertex.layer.recurrent:
                carries[spec.name] = spec.vertex.layer.init_carry(mb, dtype)
        return carries

    def _loss(self, params, state, inputs: Dict[str, Array], labels: Dict[str, Any],
              *, train: bool, rng, masks=None, label_masks=None, carries=None):
        acts, new_state, mks, new_carries = self._apply(
            params, state, inputs, train=train, rng=rng,
            masks=masks, stop_before_output_score=True, carries=carries)
        acc = jnp.float64 if jnp.dtype(self.conf.compute_dtype) == jnp.float64 else jnp.float32
        total = jnp.zeros((), acc)
        for oi, out_name in enumerate(self.conf.network_outputs):
            spec = self._spec(out_name)
            layer = spec.vertex.layer
            if not hasattr(layer, "score"):
                raise ValueError(f"output vertex '{out_name}' has no score()")
            h = acts[spec.inputs[0]]
            if train and rng is not None:
                # output layers honor input dropout (parity w/ multilayer._loss);
                # _maybe_dropout no-ops when the layer has no dropout configured
                h = layer._maybe_dropout(h, train, jax.random.fold_in(rng, 10_000 + oi))
            lm = (label_masks or {}).get(out_name)
            total = total + layer.score(params[out_name], state[out_name], h,
                                        labels[out_name], mask=lm).astype(acc)
            if train and hasattr(layer, "update_centers"):
                new_state[out_name] = layer.update_centers(
                    state[out_name], jax.lax.stop_gradient(h),
                    jax.lax.stop_gradient(labels[out_name]))
        for spec in self.conf.vertices:
            if isinstance(spec.vertex, LayerVertex) and self.params.get(spec.name):
                total = total + spec.vertex.layer.regularization_score(
                    params[spec.name]).astype(acc)
        if train:
            from .layers.base import AUX_LOSS_KEY
            for s in new_state.values():
                if isinstance(s, dict) and AUX_LOSS_KEY in s:
                    total = total + s[AUX_LOSS_KEY].astype(acc)
        if carries is not None:
            return total, (new_state, new_carries)
        return total, new_state

    # -- training ----------------------------------------------------------

    def _apply_updates(self, grads, params, opt_state, itf):
        """Shared per-vertex updater application (grad normalization, updater
        math, dtype-preserving cast, post-update constraints) — used by both
        the standard and TBPTT jitted steps."""
        conf = self.conf
        new_params, new_opt = dict(params), dict(opt_state)
        for spec in conf.vertices:
            name = spec.name
            if not isinstance(spec.vertex, LayerVertex) or not params[name]:
                continue
            g = grads[name]
            if conf.gradient_normalization != GradientNormalization.NONE:
                g = normalize_gradients(g, conf.gradient_normalization,
                                        conf.gradient_normalization_threshold)
            upd = self._updater_for(spec.vertex.layer)
            # apply = updater math + param step; Adam/Nadam route through
            # the fused one-pass kernel (ops/update_kernel.py) when enabled
            new_params[name], os2 = upd.apply(params[name], g,
                                              opt_state[name], itf)
            if spec.vertex.layer.constraints:
                new_params[name] = apply_constraints(
                    spec.vertex.layer.constraints, new_params[name])
            new_opt[name] = os2
        return new_params, new_opt

    def _make_step(self):
        conf = self.conf

        def step(params, state, opt_state, it, inputs, labels, rng, masks, label_masks):
            def loss_fn(p):
                return self._loss(p, state, inputs, labels, train=True, rng=rng,
                                  masks=masks, label_masks=label_masks)

            (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(
                grads, params, opt_state, it.astype(jnp.float32))
            return new_params, new_state, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _make_step_tbptt(self):
        """One TBPTT chunk step — used for the ragged tail chunk and the
        stateful-listener fallback (reference doTruncatedBPTT:1553)."""
        conf = self.conf

        def step(params, state, opt_state, it, inputs, labels, rng, masks,
                 label_masks, carries):
            def loss_fn(p):
                return self._loss(p, state, inputs, labels, train=True, rng=rng,
                                  masks=masks, label_masks=label_masks,
                                  carries=carries)

            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = self._apply_updates(
                grads, params, opt_state, it.astype(jnp.float32))
            return new_params, new_state, new_opt, new_carries, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _make_step_tbptt_scan(self):
        """Whole-batch TBPTT for the DAG: all T//L chunk optimizer-steps in
        ONE jit via lax.scan (see multilayer._make_step_tbptt_scan for the
        per-chunk-upload cost this removes).  Temporal entries (rank-3
        features/labels, [mb,T] masks) are chunked into scan inputs;
        static entries (rank-2 inputs, per-sequence masks) ride the trace
        closure unchanged."""
        L = self.conf.tbptt_length

        def step(params, state, opt_state, it0, inputs, labels, rng,
                 masks_t, masks_s, lmasks_t, lmasks_s, carries):
            # masks arrive PRE-SPLIT into temporal/static dicts: the caller
            # classifies against the ORIGINAL T, because after tail
            # clipping a static rank-2 mask's dim-1 could coincidentally
            # equal the clipped n·L and be mistaken for temporal here
            T = next(a.shape[1]
                     for a in list(inputs.values()) + list(labels.values())
                     if a is not None and a.ndim == 3)
            n = T // L
            mb = next(iter(inputs.values())).shape[0]
            if carries is None:
                carries = self._init_carries(mb)

            def chunkify(a):
                a2 = a.reshape((a.shape[0], n, L) + a.shape[2:])
                return jnp.moveaxis(a2, 1, 0)

            def split_temporal(d, temporal_pred):
                xs = {k: chunkify(v) for k, v in (d or {}).items()
                      if temporal_pred(v)}
                static = {k: v for k, v in (d or {}).items()
                          if not temporal_pred(v)}
                return xs, static

            is_t = lambda a: a is not None and a.ndim == 3
            xs_in, st_in = split_temporal(inputs, is_t)
            xs_lab, st_lab = split_temporal(labels, is_t)
            xs_m = {k: chunkify(v) for k, v in (masks_t or {}).items()}
            st_m = dict(masks_s or {})
            xs_lm = {k: chunkify(v) for k, v in (lmasks_t or {}).items()}
            st_lm = dict(lmasks_s or {})
            keys = jax.random.split(rng, n + 1)
            its = it0 + jnp.arange(n, dtype=jnp.int32)

            def body(carry, xs):
                params, state, opt_state, carries = carry
                ci, cl, cm, clm, k, it = xs

                def loss_fn(p):
                    return self._loss(p, state, {**st_in, **ci},
                                      {**st_lab, **cl}, train=True, rng=k,
                                      masks={**st_m, **cm},
                                      label_masks={**st_lm, **clm},
                                      carries=carries)

                (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                new_params, new_opt = self._apply_updates(
                    grads, params, opt_state, it.astype(jnp.float32))
                return (new_params, new_state, new_opt, new_carries), loss

            (params, state, opt_state, carries), losses = jax.lax.scan(
                body, (params, state, opt_state, carries),
                (xs_in, xs_lab, xs_m, xs_lm, keys[:n], its))
            return (params, state, opt_state, carries, losses,
                    jnp.mean(losses), keys[n])

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _to_mds(self, ds) -> MultiDataSet:
        if isinstance(ds, MultiDataSet):
            return ds
        if isinstance(ds, DataSet):
            return MultiDataSet([ds.features], [ds.labels],
                                [ds.features_mask], [ds.labels_mask])
        raise TypeError(type(ds))

    def score_examples(self, ds, add_regularization_terms: bool = True) -> np.ndarray:
        """Per-example scores [N] (reference ComputationGraph.scoreExamples):
        each output layer's unreduced loss summed per example across
        outputs; with ``add_regularization_terms`` the network L1/L2 score
        is added to every example.  For unmasked feed-forward outputs
        ``mean(score_examples(ds, True)) == score(ds)``; RNN outputs sum
        over time (mean == t·score there)."""
        mds = self._to_mds(ds)
        if self._jit_score_examples is None:
            def fn(params, state, inputs, labels, masks, lmasks, add_reg):
                acts, _, mks, _ = self._apply(
                    params, state, inputs, train=False, rng=None,
                    masks=masks, stop_before_output_score=True)
                pe = None
                for out_name in self.conf.network_outputs:
                    spec = self._spec(out_name)
                    layer = spec.vertex.layer
                    if not hasattr(layer, "score_examples"):
                        raise ValueError(
                            f"output vertex '{out_name}' "
                            f"({type(layer).__name__}) has no score_examples()")
                    h = acts[spec.inputs[0]]
                    # mirror MultiLayerNetwork.score_examples: with no
                    # explicit label mask, rank-3 (RNN) labels fall back to
                    # the forward-propagated feature mask of this output's
                    # input — masked-sequence per-example scores must agree
                    # between the two containers
                    lmask = lmasks.get(out_name)
                    y_out = labels[out_name]
                    if lmask is None and getattr(y_out, "ndim", 0) == 3:
                        lmask = mks.get(spec.inputs[0])
                    s = layer.score_examples(params[out_name], state[out_name],
                                             h, y_out, mask=lmask)
                    pe = s if pe is None else pe + s
                reg = jnp.zeros((), pe.dtype)
                for spec in self.conf.vertices:
                    if isinstance(spec.vertex, LayerVertex) and params.get(spec.name):
                        reg = reg + spec.vertex.layer.regularization_score(
                            params[spec.name]).astype(pe.dtype)
                return jnp.where(add_reg, pe + reg, pe)

            self._jit_score_examples = jax.jit(fn)
        inputs = {n: jnp.asarray(f) for n, f in
                  zip(self.conf.network_inputs, mds.features)}
        labels = {n: jax.tree_util.tree_map(jnp.asarray, l)
                  for n, l in zip(self.conf.network_outputs, mds.labels)}
        masks = {n: (None if m is None else jnp.asarray(m))
                 for n, m in zip(self.conf.network_inputs, mds.features_masks or
                                 [None] * len(self.conf.network_inputs))}
        lmasks = {n: (None if m is None else jnp.asarray(m))
                  for n, m in zip(self.conf.network_outputs, mds.labels_masks or
                                  [None] * len(self.conf.network_outputs))}
        pe = self._jit_score_examples(self.params, self.state, inputs, labels,
                                      masks, lmasks,
                                      jnp.asarray(add_regularization_terms))
        return np.asarray(pe)

    # -- layerwise unsupervised pretraining --------------------------------

    def pretrainable_layers(self) -> List[str]:
        """Names of LayerVertices with an unsupervised objective (reference
        Layer.isPretrainLayer())."""
        return [s.name for s in self.conf.vertices
                if isinstance(s.vertex, LayerVertex)
                and (hasattr(s.vertex.layer, "contrastive_divergence")
                     or hasattr(s.vertex.layer, "reconstruction_score"))]

    def pretrain(self, data, epochs: int = 1) -> Dict[str, List[float]]:
        """Greedy layerwise unsupervised pretraining over the DAG in
        topological order (reference ComputationGraph.pretrain:651); labels
        are ignored.  Returns {vertex_name: losses}."""
        wanted = set(self.pretrainable_layers())
        order = [n for n in self.topo_order if n in wanted]
        return {n: self.pretrain_layer(n, data, epochs) for n in order}

    def pretrain_layer(self, name: str, data, epochs: int = 1) -> List[float]:
        """Unsupervised pretraining of one LayerVertex (reference
        pretrainLayer(String, MultiDataSetIterator)): the vertex's input is
        produced by an inference-mode DAG pass (XLA dead-code-eliminates
        everything downstream of it), then the layer's objective — CD-k /
        reconstruction / ELBO — runs with the layer's updater in the same
        jitted program."""
        spec = self._spec_by_name.get(name)
        if spec is None or not isinstance(spec.vertex, LayerVertex):
            raise ValueError(f"'{name}' is not a LayerVertex")
        layer = spec.vertex.layer
        is_rbm = hasattr(layer, "cd_gradients")
        if not is_rbm and not hasattr(layer, "reconstruction_score"):
            raise ValueError(
                f"vertex '{name}' ({type(layer).__name__}) has no "
                "unsupervised objective (RBM / AutoEncoder / VAE)")
        updater = self._updater_for(layer)

        def step(params, state, opt_v, it, inputs, rng):
            acts, _, _, _ = self._apply(params, state, inputs, train=False,
                                        rng=None, masks=None,
                                        stop_before_output_score=True)
            src = spec.inputs[0]
            feat = acts[src] if src in acts else inputs[src]
            if is_rbm:
                g, loss = layer.cd_gradients(params[name], feat, rng)
            else:
                loss, g = jax.value_and_grad(
                    lambda p: layer.reconstruction_score(
                        p, feat, rng=rng, train=True))(params[name])
            if self.conf.gradient_normalization != GradientNormalization.NONE:
                g = normalize_gradients(
                    g, self.conf.gradient_normalization,
                    self.conf.gradient_normalization_threshold)
            p2, opt2 = updater.apply(params[name], g, opt_v, it)
            if layer.constraints:
                p2 = apply_constraints(layer.constraints, p2)
            return p2, opt2, loss

        jit_step = jax.jit(step, donate_argnums=(2,))
        losses: List[float] = []
        it = 0
        for _ in range(epochs):
            for ds in self._as_iterator(data):
                mds = self._to_mds(ds)
                inputs = {n: jnp.asarray(f) for n, f in
                          zip(self.conf.network_inputs, mds.features)}
                self._rng, sub = jax.random.split(self._rng)
                self.params[name], self.opt_state[name], loss = jit_step(
                    self.params, self.state, self.opt_state[name],
                    np.float32(it), inputs, sub)
                it += 1
                losses.append(LazyScore(loss))
        materialize_scores(losses)
        return losses

    def fit_batch(self, ds):
        """One step; returns a :class:`LazyScore` (device-resident loss that
        syncs only when read — see optimize/score.py)."""
        mds = self._to_mds(ds)
        if self.conf.backprop_type == "tbptt":
            return self._fit_batch_tbptt(mds)
        if self._jit_step is None:
            self._jit_step = self._make_step()
        self._rng, sub = jax.random.split(self._rng)
        inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.network_inputs, mds.features)}
        labels = {n: jax.tree_util.tree_map(jnp.asarray, l)
                  for n, l in zip(self.conf.network_outputs, mds.labels)}
        masks = {n: (None if m is None else jnp.asarray(m))
                 for n, m in zip(self.conf.network_inputs, mds.features_masks or
                                 [None] * len(self.conf.network_inputs))}
        lmasks = {n: (None if m is None else jnp.asarray(m))
                  for n, m in zip(self.conf.network_outputs, mds.labels_masks or
                                  [None] * len(self.conf.network_outputs))}
        self.params, self.state, self.opt_state, loss = self._jit_step(
            self.params, self.state, self.opt_state,
            jnp.asarray(self.iteration, jnp.int32), inputs, labels, sub, masks, lmasks)
        self.iteration += 1
        score = LazyScore(loss)
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration, score)
        return score

    def _make_multi_step(self):
        """k optimizer steps fused into ONE dispatch via lax.scan over
        stacked batches — the graph-container twin of
        MultiLayerNetwork._make_multi_step (round-4 verdict Next #5:
        amortizes the per-step dispatch gap, the 12.6% device-IDLE bucket
        in docs/transformer_profile.md, to 1/k).  Same rng-stream caveat
        as the MLN twin: one base split fanned to k keys, so stochastic
        runs differ from k sequential fit_batch calls."""
        def multi(params, state, opt_state, it0, inputs, labels, rng,
                  masks, lmasks):
            n = jax.tree_util.tree_leaves(inputs)[0].shape[0]
            keys = jax.random.split(rng, n)
            its = it0 + jnp.arange(n, dtype=jnp.int32)

            def body(carry, inp):
                params, state, opt = carry
                xs, ys, k, it, ms, lms = inp

                def loss_fn(p):
                    return self._loss(p, state, xs, ys, train=True, rng=k,
                                      masks=ms, label_masks=lms)

                (loss, new_state), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                new_params, new_opt = self._apply_updates(
                    grads, params, opt, it.astype(jnp.float32))
                return (new_params, new_state, new_opt), loss

            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state),
                (inputs, labels, keys, its, masks, lmasks))
            return params, state, opt_state, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def fit_batches(self, batches):
        """k steps in ONE device dispatch over same-shaped DataSets /
        MultiDataSets (see MultiLayerNetwork.fit_batches).  Returns [k]
        LazyScores; TBPTT configs fall back to per-batch calls."""
        mdss = [self._to_mds(ds) for ds in batches]
        if not mdss:
            return []
        # stateful listeners (checkpoint/eval) need params at EACH step's
        # callback time — the fused scan only has end-of-run params
        if self.conf.backprop_type == "tbptt" or any(
                getattr(l, "requires_model_state", False)
                for l in self.listeners):
            return [self.fit_batch(m) for m in mdss]
        if self._jit_multi_step is None:
            self._jit_multi_step = self._make_multi_step()

        def stack_named(names, get):
            out = {}
            for i, name in enumerate(names):
                vals = [get(m, i) for m in mdss]
                if any(v is None for v in vals):
                    if not all(v is None for v in vals):
                        raise ValueError("fit_batches needs uniform masks: "
                                         "all batches or none")
                    out[name] = None
                else:
                    out[name] = jax.tree_util.tree_map(
                        lambda *ls: jnp.stack([jnp.asarray(a) for a in ls]),
                        *vals)
            return out

        n_in = len(self.conf.network_inputs)
        n_out = len(self.conf.network_outputs)
        inputs = stack_named(self.conf.network_inputs,
                             lambda m, i: m.features[i])
        labels = stack_named(self.conf.network_outputs,
                             lambda m, i: m.labels[i])
        masks = stack_named(self.conf.network_inputs,
                            lambda m, i: (m.features_masks or [None] * n_in)[i])
        lmasks = stack_named(self.conf.network_outputs,
                             lambda m, i: (m.labels_masks or [None] * n_out)[i])
        self._rng, sub = jax.random.split(self._rng)
        n = len(mdss)
        self.params, self.state, self.opt_state, losses = self._jit_multi_step(
            self.params, self.state, self.opt_state,
            jnp.asarray(self.iteration, jnp.int32), inputs, labels, sub,
            masks, lmasks)
        self.iteration += n
        scores = [LazyScore(losses[i]) for i in range(n)]
        for i, score in enumerate(scores):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration - n + i + 1, score)
        return scores

    def _fit_batch_tbptt(self, mds: MultiDataSet) -> float:
        """Slice the time axis into tbptt_length chunks, carry recurrent
        state forward, one optimizer step per chunk (reference
        doTruncatedBPTT:1553).  All rank-3 inputs/labels must share T.
        Full chunks run in one scanned jit; a ragged tail — and the
        stateful-listener case — use the per-chunk step."""
        feats = [np.asarray(f) for f in mds.features]
        labs = [None if l is None else np.asarray(l) for l in mds.labels]
        T = None
        for a in feats + [l for l in labs if l is not None]:
            if a.ndim == 3:
                if T is not None and a.shape[1] != T:
                    raise ValueError("TBPTT requires equal time lengths across "
                                     f"inputs/labels (got {a.shape[1]} vs {T})")
                T = a.shape[1]
        if T is None:
            raise ValueError("TBPTT requires at least one [mb, time, f] array")
        mb = feats[0].shape[0]
        L = self.conf.tbptt_length
        fmasks = mds.features_masks or [None] * len(feats)
        lmasks_l = mds.labels_masks or [None] * len(labs)

        def tslice(a, s, e):
            """Features/labels: only rank-3 arrays carry a time axis —
            rank-2 static inputs pass through whole (their dim-1 may
            coincidentally equal T)."""
            if a is None:
                return None
            return a[:, s:e] if a.ndim == 3 else a

        def mslice(m, s, e):
            """Masks are [mb, T] when temporal; other shapes pass through."""
            if m is None:
                return None
            m = np.asarray(m)
            return m[:, s:e] if m.ndim == 2 and m.shape[1] == T else m

        def dicts(s, e):
            inputs = {n: jnp.asarray(tslice(f, s, e))
                      for n, f in zip(self.conf.network_inputs, feats)}
            labels = {n: (None if l is None else jnp.asarray(tslice(l, s, e)))
                      for n, l in zip(self.conf.network_outputs, labs)}
            masks = {n: (None if m is None else jnp.asarray(mslice(m, s, e)))
                     for n, m in zip(self.conf.network_inputs, fmasks)}
            lmasks = {n: (None if m is None else jnp.asarray(mslice(m, s, e)))
                      for n, m in zip(self.conf.network_outputs, lmasks_l)}
            return inputs, labels, masks, lmasks

        stateful = any(getattr(l, "requires_model_state", False)
                       for l in self.listeners)
        n = T // L
        tail = T % L
        carries = None
        chunk_losses = []
        mean_loss = None
        if n and not stateful:
            if self._jit_step_tbptt_scan is None:
                self._jit_step_tbptt_scan = self._make_step_tbptt_scan()
            inputs, labels, masks, lmasks = dicts(0, n * L)

            def split_by_orig_T(slcd, originals, names):
                """Temporal = the ORIGINAL array was [mb, T]; a static
                mask whose dim-1 happens to equal the clipped n·L must
                not be chunkified (the scan can't tell them apart)."""
                t, s = {}, {}
                for name in names:
                    orig = originals.get(name)
                    m = slcd.get(name)
                    is_temporal = (orig is not None and orig.ndim == 2
                                   and orig.shape[1] == T)
                    (t if is_temporal else s)[name] = m
                return t, s

            orig_fm = {nm: (None if m is None else np.asarray(m))
                       for nm, m in zip(self.conf.network_inputs, fmasks)}
            orig_lm = {nm: (None if m is None else np.asarray(m))
                       for nm, m in zip(self.conf.network_outputs, lmasks_l)}
            masks_t, masks_s = split_by_orig_T(masks, orig_fm,
                                               self.conf.network_inputs)
            lm_t, lm_s = split_by_orig_T(lmasks, orig_lm,
                                         self.conf.network_outputs)
            (self.params, self.state, self.opt_state, carries, losses,
             mean_loss, self._rng) = self._jit_step_tbptt_scan(
                self.params, self.state, self.opt_state,
                self._iter_scalar(n), inputs, labels, self._rng,
                masks_t, masks_s, lm_t, lm_s, None)
            self.iteration += n
            if self.listeners:
                chunk_losses = [(self.iteration - n + i + 1, LazyScore(losses[i]))
                                for i in range(n)]
        if tail or stateful:
            if self._jit_step_tbptt is None:
                self._jit_step_tbptt = self._make_step_tbptt()
            if carries is None:
                carries = self._init_carries(mb)
            total, chunks = None, 0
            start = 0 if stateful else n * L
            for s in range(start, T, L):
                inputs, labels, masks, lmasks = dicts(s, s + L)
                self._rng, sub = jax.random.split(self._rng)
                (self.params, self.state, self.opt_state, carries, loss
                 ) = self._jit_step_tbptt(
                    self.params, self.state, self.opt_state,
                    self._iter_scalar(1), inputs, labels, sub,
                    masks, lmasks, carries)
                self.iteration += 1
                total = loss if total is None else total + loss
                chunks += 1
                if stateful:
                    # per-chunk callbacks with each chunk's params
                    for lst in self.listeners:
                        lst.iteration_done(self, self.iteration,
                                           LazyScore(loss))
                elif self.listeners:
                    chunk_losses.append((self.iteration, LazyScore(loss)))
            tail_mean = total / max(chunks, 1)
            if stateful:
                return LazyScore(tail_mean)
            mean_loss = tail_mean if mean_loss is None else (
                (mean_loss * n + total) / (n + chunks))
        for it, score in chunk_losses:
            for lst in self.listeners:
                lst.iteration_done(self, it, score)
        return LazyScore(mean_loss)

    def fit(self, data, epochs: int = 1) -> List[float]:
        losses = []
        it = self._as_iterator(data)
        synced = 0
        for _ in range(epochs):
            for ds in it:
                losses.append(self.fit_batch(ds))
            synced = self._end_epoch(losses, synced)
        return losses

    def _end_epoch(self, losses, synced: int) -> int:
        """Shared epoch epilogue (see MultiLayerNetwork._end_epoch):
        batched score materialization, epoch bump, epoch_done listeners —
        the graph container previously skipped the listener callbacks."""
        materialize_scores(losses[synced:])
        self.epoch += 1
        for lst in self.listeners:
            if hasattr(lst, "epoch_done"):
                lst.epoch_done(self, self.epoch)
        return len(losses)

    @staticmethod
    def _as_iterator(data):
        if isinstance(data, DataSetIterator):
            return data
        if isinstance(data, (DataSet, MultiDataSet)):
            return ListDataSetIterator([data])
        if isinstance(data, tuple) and len(data) == 2:
            return ListDataSetIterator([DataSet(np.asarray(data[0]), np.asarray(data[1]))])
        raise TypeError(type(data))

    # -- inference ----------------------------------------------------------

    def output(self, *features, masks=None) -> List[np.ndarray]:
        """Activations of all output vertices, in network_outputs order
        (reference ComputationGraph.output)."""
        if self._jit_output is None:
            def fwd(params, state, inputs, mks):
                acts, _, _, _ = self._apply(params, state, inputs, train=False,
                                            rng=None, masks=mks)
                return [acts[n] for n in self.conf.network_outputs]
            self._jit_output = jax.jit(fwd)
        inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.network_inputs, features)}
        mks = {n: (None if masks is None or masks[i] is None else jnp.asarray(masks[i]))
               for i, n in enumerate(self.conf.network_inputs)} if masks else None
        outs = self._jit_output(self.params, self.state, inputs, mks)
        return [np.asarray(o) for o in outs]

    def rnn_time_step(self, *features) -> List[np.ndarray]:
        """Stateful streaming inference over the DAG: each rank-2 input
        [mb, f] is treated as one timestep, rank-3 inputs stream their
        chunk; recurrent vertex state persists across calls (reference
        ComputationGraph.rnnTimeStep:1500)."""
        arrs = []
        ranks = []
        for f in features:
            a = jnp.asarray(f)
            ranks.append(a.ndim)
            if a.ndim == 2:
                a = a[:, None, :]
            arrs.append(a)
        # single-step squeeze only when EVERY input was a single timestep;
        # mixed-rank calls keep full sequence outputs
        squeeze = all(r == 2 for r in ranks)
        mb = arrs[0].shape[0]
        if self._stream_carries is not None:
            for c in jax.tree_util.tree_leaves(self._stream_carries):
                if c.shape[0] != mb:  # batch size changed → fresh state
                    self._stream_carries = None
                break
        if self._stream_carries is None:
            self._stream_carries = self._init_carries(mb)
        if self._jit_stream is None:
            def fwd(params, state, inputs, carries):
                acts, _, _, new_carries = self._apply(
                    params, state, inputs, train=False, rng=None, carries=carries)
                return [acts[n] for n in self.conf.network_outputs], new_carries
            self._jit_stream = jax.jit(fwd)
        inputs = {n: a for n, a in zip(self.conf.network_inputs, arrs)}
        outs, self._stream_carries = self._jit_stream(
            self.params, self.state, inputs, self._stream_carries)
        result = []
        for o in outs:
            o = np.asarray(o)
            result.append(o[:, 0] if squeeze and o.ndim == 3 else o)
        return result

    def rnn_clear_previous_state(self) -> None:
        """Reset streaming state (reference rnnClearPreviousState)."""
        self._stream_carries = None

    def _mask_dicts(self, mds: MultiDataSet):
        masks = {n: (None if m is None else jnp.asarray(m))
                 for n, m in zip(self.conf.network_inputs, mds.features_masks or
                                 [None] * len(self.conf.network_inputs))}
        lmasks = {n: (None if m is None else jnp.asarray(m))
                  for n, m in zip(self.conf.network_outputs, mds.labels_masks or
                                  [None] * len(self.conf.network_outputs))}
        return masks, lmasks

    def score(self, ds) -> float:
        mds = self._to_mds(ds)
        inputs = {n: jnp.asarray(f) for n, f in zip(self.conf.network_inputs, mds.features)}
        labels = {n: jax.tree_util.tree_map(jnp.asarray, l)
                  for n, l in zip(self.conf.network_outputs, mds.labels)}
        masks, lmasks = self._mask_dicts(mds)
        loss, _ = self._loss(self.params, self.state, inputs, labels,
                             train=False, rng=None, masks=masks, label_masks=lmasks)
        return float(loss)

    def evaluate(self, data, evaluation=None, output_index: int = 0):
        """Classification metrics for ONE output head (``output_index``),
        with masks honored — evaluate each head separately for multi-output
        graphs (reference ComputationGraph.evaluate scores output 0 too)."""
        from ..evaluation.evaluation import Evaluation
        ev = evaluation if evaluation is not None else Evaluation()
        for ds in self._as_iterator(data):
            mds = self._to_mds(ds)
            outs = self.output(*mds.features, masks=mds.features_masks)
            lm = None if mds.labels_masks is None else mds.labels_masks[output_index]
            ev.eval(mds.labels[output_index], outs[output_index], mask=lm)
        return ev

    def set_listeners(self, *listeners) -> None:
        self.listeners = list(listeners)

    def save(self, path: str, save_updater: bool = True) -> None:
        from ..utils.serializer import save_model
        save_model(self, path, save_updater=save_updater)

    @staticmethod
    def load(path: str, load_updater: bool = True) -> "ComputationGraph":
        from ..utils.serializer import load_model
        return load_model(path, load_updater=load_updater)
