"""NN core (L1): configs-as-data, layer impls, containers, updaters, train step.

Replaces the reference's deeplearning4j-nn module (SURVEY.md §1 L1).  Key
inversion: the reference pairs every declarative layer config
(nn/conf/layers/*) with a hand-written runtime impl (nn/layers/*) carrying
its own backpropGradient; here each layer is ONE dataclass whose ``forward``
is a pure function and whose backward pass is derived by jax.grad, with the
whole fit step compiled to a single XLA program.
"""
