"""YOLOv2 output layer.

Reference: nn/conf/layers/objdetect/Yolo2OutputLayer.java +
nn/layers/objdetect/Yolo2OutputLayer.java (721 LoC): grid-cell predictions
[mb, B*(5+C), H, W] with anchor boxes; loss = λ_coord·(xy + √wh) +
confidence (IOU target, λ_noobj on empty cells) + per-cell class
cross-entropy.  Here layout is NHWC: [mb, H, W, B*(5+C)], labels
[mb, H, W, 4 + C_onehot + objmask] simplified to the canonical YOLOv2
target encoding below.

Label format accepted: ``labels`` dict with
  "boxes":  [mb, H, W, B, 4]  target (tx, ty, tw, th) in cell coords
  "obj":    [mb, H, W, B]     1 where an object is assigned to anchor b
  "cls":    [mb, H, W, C]     one-hot class per cell
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    anchors: List[List[float]] = dataclasses.field(
        default_factory=lambda: [[1.0, 1.0], [2.0, 2.0]])
    n_classes: int = 20
    lambda_coord: float = 5.0
    lambda_noobj: float = 0.5

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        return in_type

    @property
    def n_boxes(self) -> int:
        return len(self.anchors)

    def _split(self, x):
        b, c = self.n_boxes, self.n_classes
        mb, h, w, _ = x.shape
        x = x.reshape(mb, h, w, b, 5 + c)
        txy = jax.nn.sigmoid(x[..., 0:2])
        twh = x[..., 2:4]
        conf = jax.nn.sigmoid(x[..., 4])
        cls_logits = x[..., 5:]
        return txy, twh, conf, cls_logits

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(x, state, mask)

    def score(self, params, state, x, labels, *, mask: Optional[Array] = None) -> Array:
        txy, twh, conf, cls_logits = self._split(x)
        boxes, obj, cls = labels["boxes"], labels["obj"], labels["cls"]
        obj = obj.astype(x.dtype)
        # coordinate loss (λ_coord, √wh per YOLOv2 paper / reference impl)
        xy_loss = jnp.sum(obj[..., None] * (txy - boxes[..., 0:2]) ** 2, axis=-1)
        anchors = jnp.asarray(self.anchors, x.dtype)  # [B,2]
        pred_wh = jnp.exp(jnp.clip(twh, -10, 10)) * anchors
        true_wh = jnp.exp(jnp.clip(boxes[..., 2:4], -10, 10)) * anchors
        wh_loss = jnp.sum(obj[..., None] * (jnp.sqrt(pred_wh + 1e-8) - jnp.sqrt(true_wh + 1e-8)) ** 2, axis=-1)
        coord = self.lambda_coord * (xy_loss + wh_loss)
        # confidence: target 1 for assigned anchors, 0 elsewhere (λ_noobj)
        conf_loss = obj * (conf - 1.0) ** 2 + self.lambda_noobj * (1 - obj) * conf ** 2
        # per-anchor class cross-entropy, counted for each responsible anchor
        # (YOLOv2: every assigned predictor predicts the cell's class)
        logp = jax.nn.log_softmax(cls_logits, axis=-1)          # [mb,h,w,B,C]
        cls_loss = -jnp.sum(cls[..., None, :] * logp, axis=-1)  # [mb,h,w,B]
        per_cell = jnp.sum(coord + conf_loss, axis=-1) + jnp.sum(cls_loss * obj, axis=-1)
        per_example = jnp.sum(per_cell, axis=(1, 2))
        return jnp.mean(per_example)

    def decode_predictions(self, x, conf_threshold: float = 0.5):
        """Post-process to (boxes, confidences, class probabilities) — the
        reference's getPredictedObjects equivalent, vectorized."""
        txy, twh, conf, cls_logits = self._split(x)
        mb, h, w = conf.shape[:3]
        gy = jnp.arange(h, dtype=x.dtype)[None, :, None, None]
        gx = jnp.arange(w, dtype=x.dtype)[None, None, :, None]
        cx = (txy[..., 0] + gx) / w
        cy = (txy[..., 1] + gy) / h
        anchors = jnp.asarray(self.anchors, x.dtype)
        wh = jnp.exp(jnp.clip(twh, -10, 10)) * anchors / jnp.asarray([w, h], x.dtype)
        probs = jax.nn.softmax(cls_logits, axis=-1)
        return {
            "cx": cx, "cy": cy, "w": wh[..., 0], "h": wh[..., 1],
            "conf": conf, "class_probs": probs,
            "detect": conf > conf_threshold,
        }
