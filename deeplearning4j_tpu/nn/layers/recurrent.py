"""Recurrent family — ``lax.scan`` replaces the reference's hand-written
per-timestep loop (nn/layers/recurrent/LSTMHelpers.java:68,392 shared
fwd/bwd for all LSTM variants; CudnnLSTMHelper on GPU).

Layout: [mb, time, features] (reference is [mb, features, time]).  Gate
order in the fused 4*n_out kernels: [i, f, o, g] (input, forget, output,
cell-candidate).  Param keys match LSTMParamInitializer.java:48-50:
"W" (input weights), "RW" (recurrent weights), "b".

GravesLSTM adds peephole connections (param "pW": [3*n_out] for i,f,o —
reference GravesLSTMParamInitializer packs them into RW's extra columns; we
keep a separate key for clarity).  GravesBidirectionalLSTM runs forward and
backward passes and SUMS their outputs
(reference GravesBidirectionalLSTM.java:219 "sum outputs").

Statefulness: ``rnnTimeStep``-style streaming inference (reference
MultiLayerNetwork.rnnTimeStep:2636) is provided by ``step()`` which takes and
returns the carry explicitly — functional, jit-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.activations import get_activation
from ...ops.initializers import init_weight
from ...ops.losses import get_loss
from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


def _lstm_cell(cfg, params, carry, x_t, mask_t=None, suffix="", zx_t=None):
    """One LSTM step.  carry = (h, c); x_t [mb, n_in]; mask_t [mb] or None.
    ``zx_t`` is the precomputed input projection x_t @ W (see _scan_lstm —
    batching the projection over all timesteps is one big MXU matmul
    instead of T small ones, and enables integer-index inputs).

    The standard sigmoid/tanh non-peephole cell calls
    ops/lstm_kernel.fused_lstm_cell — which resolves to XLA's (faster,
    epilogue-fused) plain math by default and to the pallas kernel when
    opted in via DL4J_TPU_FUSED_LSTM=1; custom activations and peepholes
    use the general path."""
    h, c = carry
    if zx_t is None:
        zx_t = x_t @ params["W" + suffix].astype(x_t.dtype)
    RW = params["RW" + suffix].astype(zx_t.dtype)
    b = params["b" + suffix].astype(zx_t.dtype)
    z = zx_t + h @ RW + b  # [mb, 4*n_out]
    n = cfg.n_out
    if (not cfg.peephole and cfg.gate_activation == "sigmoid"
            and cfg.activation == "tanh"):
        from ...ops.lstm_kernel import fused_lstm_cell
        h_new, c_new = fused_lstm_cell(z, c)
        if mask_t is not None:
            m = mask_t[:, None].astype(h_new.dtype)
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        return (h_new, c_new)
    zi, zf, zo, zg = z[:, :n], z[:, n:2 * n], z[:, 2 * n:3 * n], z[:, 3 * n:]
    gate = get_activation(cfg.gate_activation)
    act = get_activation(cfg.activation)
    if cfg.peephole:
        pW = params["pW" + suffix].astype(z.dtype)
        pi, pf, po = pW[:n], pW[n:2 * n], pW[2 * n:]
        i = gate(zi + c * pi)
        f = gate(zf + c * pf)
        c_new = f * c + i * act(zg)
        o = gate(zo + c_new * po)
    else:
        i, f, o = gate(zi), gate(zf), gate(zo)
        c_new = f * c + i * act(zg)
    h_new = o * act(c_new)
    if mask_t is not None:
        m = mask_t[:, None].astype(h_new.dtype)
        h_new = m * h_new + (1 - m) * h
        c_new = m * c_new + (1 - m) * c
    return (h_new, c_new)


def _scan_lstm(cfg, params, x, mask, h0, c0, reverse=False, suffix=""):
    """Scan the cell over time. x [mb,t,f] (or int indices [mb,t]) →
    outputs [mb,t,n_out] + final carry.

    The input projection x @ W is hoisted out of the scan: one [mb·t, f]
    × [f, 4n] MXU matmul instead of t small ones.  Integer inputs take the
    gather form W[x] — mathematically identical to one_hot(x) @ W with the
    same parameters, but the host ships 2-byte indices instead of f-float
    one-hots (a ~vocab× smaller transfer, which matters on tunnelled
    TPUs and real pods alike)."""
    W = params["W" + suffix]
    if jnp.issubdtype(x.dtype, jnp.integer):
        # gather in the COMPUTE dtype (h0's dtype — the carry carries it):
        # W.dtype is the param dtype, which under mixed precision (f32
        # params, bf16 compute) would poison the scan carry dtype
        zx = W[x].astype(h0.dtype)      # [mb, t, 4n] embedding-style gather
    else:
        zx = x @ W.astype(x.dtype)      # [mb, t, 4n]
    zxT = jnp.swapaxes(zx, 0, 1)        # [t, mb, 4n]
    maskT = None if mask is None else jnp.swapaxes(mask, 0, 1)  # [t, mb]

    def body(carry, inp):
        zx_t, m_t = inp
        new = _lstm_cell(cfg, params, carry, None, m_t, suffix, zx_t=zx_t)
        return new, new[0]

    inputs = (zxT, maskT if maskT is not None else jnp.ones(zxT.shape[:2], zx.dtype))
    # unroll=4: XLA pipelines/fuses across unrolled cell iterations —
    # measured +40% char-RNN training throughput vs unroll=1 on the chip
    # (unroll=8 regresses: code bloat); semantics unchanged
    (hF, cF), hs = lax.scan(body, (h0, c0), inputs, reverse=reverse,
                            unroll=4)
    return jnp.swapaxes(hs, 0, 1), (hF, cF)


@register_layer
@dataclasses.dataclass
class LSTM(Layer):
    """Standard LSTM, no peepholes (reference nn/conf/layers/LSTM.java)."""

    wants = "rnn"
    recurrent = True

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"
    activation: str = "tanh"
    peephole: bool = False

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, in_type.timesteps)

    def _init_direction(self, rng, dtype, suffix="") -> Dict[str, Array]:
        k1, k2, k3 = jax.random.split(rng, 3)
        n = self.n_out
        b = jnp.zeros((4 * n,), dtype)
        b = b.at[n:2 * n].set(self.forget_gate_bias_init)  # forget-gate bias
        p = {
            "W" + suffix: init_weight(k1, (self.n_in, 4 * n), self._winit(), self.n_in, n, dtype),
            "RW" + suffix: init_weight(k2, (n, 4 * n), self._winit(), n, n, dtype),
            "b" + suffix: b,
        }
        if self.peephole:
            p["pW" + suffix] = init_weight(k3, (3 * n,), "uniform", n, n, dtype)
        return p

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return self._init_direction(rng, dtype)

    def zero_carry(self, mb: int, dtype=jnp.float32) -> Tuple[Array, Array]:
        return (jnp.zeros((mb, self.n_out), dtype), jnp.zeros((mb, self.n_out), dtype))

    def init_carry(self, mb: int, dtype=jnp.float32):
        return self.zero_carry(mb, dtype)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None,
                carry=None) -> ForwardOut:
        if not jnp.issubdtype(x.dtype, jnp.integer):
            x = self._maybe_dropout(x, train, rng)
            cdt = x.dtype
        else:
            # index inputs: dropout on raw ids is meaningless — skip; the
            # compute dtype comes from the container (set per trace by
            # _apply_layers), falling back to the param dtype
            cdt = jnp.dtype(getattr(self, "_compute_dtype", None)
                            or params["W"].dtype)
        h0, c0 = carry if carry is not None else self.zero_carry(x.shape[0], cdt)
        ys, final = _scan_lstm(self, params, x, mask, h0, c0)
        return ForwardOut(ys, state, mask, final)

    def step(self, params, carry, x_t):
        """Single streaming step (rnnTimeStep parity): x_t [mb, n_in]
        dense, or [mb] integer indices (same gather form as _scan_lstm)."""
        if jnp.issubdtype(x_t.dtype, jnp.integer):
            new = _lstm_cell(self, params, carry, None, zx_t=params["W"][x_t])
        else:
            new = _lstm_cell(self, params, carry, x_t)
        return new[0], new


@register_layer
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference GravesLSTM.java, per
    Graves 2013 'Generating Sequences with RNNs')."""

    peephole: bool = True


@register_layer
@dataclasses.dataclass
class GravesBidirectionalLSTM(LSTM):
    """Bidirectional peephole LSTM; fwd+bwd outputs are SUMMED
    (reference GravesBidirectionalLSTM.java:219).  Not streamable: the
    backward pass needs the whole sequence, so no carry support (matches
    the reference, which disallows rnnTimeStep on bidirectional layers)."""

    recurrent = False
    peephole: bool = True

    def init_carry(self, mb, dtype=jnp.float32):
        return None

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        kf, kb = jax.random.split(rng)
        p = self._init_direction(kf, dtype, suffix="F")
        p.update(self._init_direction(kb, dtype, suffix="B"))
        return p

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        if not jnp.issubdtype(x.dtype, jnp.integer):
            x = self._maybe_dropout(x, train, rng)
            cdt = x.dtype
        else:
            cdt = jnp.dtype(getattr(self, "_compute_dtype", None)
                            or params["WF"].dtype)
        h0, c0 = self.zero_carry(x.shape[0], cdt)
        fwd, _ = _scan_lstm(self, params, x, mask, h0, c0, reverse=False, suffix="F")
        bwd, _ = _scan_lstm(self, params, x, mask, h0, c0, reverse=True, suffix="B")
        return ForwardOut(fwd + bwd, state, mask)


@register_layer
@dataclasses.dataclass
class SimpleRnn(Layer):
    """Vanilla RNN: h_t = act(x_t·W + h_{t-1}·RW + b)."""

    wants = "rnn"
    recurrent = True

    n_in: int = 0
    n_out: int = 0
    activation: str = "tanh"

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, in_type.timesteps)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        k1, k2 = jax.random.split(rng)
        return {
            "W": init_weight(k1, (self.n_in, self.n_out), self._winit(), self.n_in, self.n_out, dtype),
            "RW": init_weight(k2, (self.n_out, self.n_out), self._winit(), self.n_out, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def init_carry(self, mb: int, dtype=jnp.float32):
        return jnp.zeros((mb, self.n_out), dtype)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None,
                carry=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        act = get_activation(self.activation)
        W = params["W"].astype(x.dtype)
        RW = params["RW"].astype(x.dtype)
        b = params["b"].astype(x.dtype)
        xT = jnp.swapaxes(x, 0, 1)
        maskT = None if mask is None else jnp.swapaxes(mask, 0, 1)

        def body(h, inp):
            x_t, m_t = inp
            h_new = act(x_t @ W + h @ RW + b)
            if maskT is not None:
                m = m_t[:, None].astype(h_new.dtype)
                h_new = m * h_new + (1 - m) * h
            return h_new, h_new

        h0 = carry if carry is not None else self.init_carry(x.shape[0], x.dtype)
        inputs = (xT, maskT if maskT is not None else jnp.ones(xT.shape[:2], x.dtype))
        hF, hs = lax.scan(body, h0, inputs)
        return ForwardOut(jnp.swapaxes(hs, 0, 1), state, mask, hF)


@register_layer
@dataclasses.dataclass
class Bidirectional(Layer):
    """Wrapper running any recurrent layer fwd+bwd with a combine mode
    (CONCAT / ADD / MUL / AVERAGE) — generalizes the reference's
    Graves-only bidirectionality."""

    layer: Optional[Layer] = None
    mode: str = "concat"

    def infer_nin(self, in_type: InputType) -> None:
        self.layer.infer_nin(in_type)

    def output_type(self, in_type: InputType) -> InputType:
        inner = self.layer.output_type(in_type)
        if self.mode == "concat":
            return InputType.recurrent(inner.size * 2, inner.timesteps)
        return inner

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        kf, kb = jax.random.split(rng)
        return {
            "fwd": self.layer.init_params(kf, in_type, dtype),
            "bwd": self.layer.init_params(kb, in_type, dtype),
        }

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        rf = rb = None
        if rng is not None:
            rf, rb = jax.random.split(rng)
        fwd = self.layer.forward(params["fwd"], {}, x, train=train, rng=rf, mask=mask).y
        xrev = jnp.flip(x, axis=1)
        mrev = None if mask is None else jnp.flip(mask, axis=1)
        bwd = self.layer.forward(params["bwd"], {}, xrev, train=train, rng=rb, mask=mrev).y
        bwd = jnp.flip(bwd, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([fwd, bwd], axis=-1)
        elif self.mode == "add":
            y = fwd + bwd
        elif self.mode == "mul":
            y = fwd * bwd
        elif self.mode == "average":
            y = 0.5 * (fwd + bwd)
        else:
            raise ValueError(self.mode)
        return ForwardOut(y, state, mask)

    def regularization_score(self, params):
        return self.layer.regularization_score(params["fwd"]) + self.layer.regularization_score(params["bwd"])


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(Layer):
    """Time-distributed dense + per-timestep loss (reference
    nn/conf/layers/RnnOutputLayer.java; masked loss averaging per
    LossFunction masking semantics)."""

    wants = "rnn"

    n_in: int = 0
    n_out: int = 0
    loss: str = "mcxent"
    has_bias: bool = True

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, in_type.timesteps)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return self._dense_init(rng, self.n_in, self.n_out, dtype)

    def _pre(self, params, x):
        y = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return y

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        return ForwardOut(self._act(self._pre(params, x)), state, mask)

    def score(self, params, state, x, labels, *, mask: Optional[Array] = None) -> Array:
        pre = self._pre(params, x)  # [mb, t, n_out]
        return get_loss(self.loss)(labels, pre, self.activation or "identity", mask)

    def score_examples(self, params, state, x, labels, *,
                       mask: Optional[Array] = None) -> Array:
        """[mb] scores: per-timestep loss summed over the sequence
        (reference scoreExamples on RNN output layers)."""
        pre = self._pre(params, x)
        from ...ops.losses import summed_per_example
        return summed_per_example(self.loss, labels, pre, self.activation, mask)


@register_layer
@dataclasses.dataclass
class LastTimeStep(Layer):
    """Wrapper: inner recurrent layer, emit only the last (masked) timestep
    (reference conf/graph/rnn/LastTimeStepVertex.java as a layer wrapper)."""

    layer: Optional[Layer] = None

    def infer_nin(self, in_type: InputType) -> None:
        self.layer.infer_nin(in_type)

    def output_type(self, in_type: InputType) -> InputType:
        inner = self.layer.output_type(in_type)
        return InputType.feed_forward(inner.size)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return self.layer.init_params(rng, in_type, dtype)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        out = self.layer.forward(params, state, x, train=train, rng=rng, mask=mask)
        ys = out.y  # [mb, t, f]
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask.astype(jnp.int32), axis=1) - 1, 0)  # [mb]
            y = ys[jnp.arange(ys.shape[0]), idx]
        else:
            y = ys[:, -1]
        return ForwardOut(y, out.state, None)

    def regularization_score(self, params):
        return self.layer.regularization_score(params)
