"""Pooling family — XLA reduce_window replaces the reference's
SubsamplingLayer (nn/layers/convolution/subsampling/SubsamplingLayer.java and
CudnnSubsamplingHelper).

PoolingType parity (nn/conf/layers/PoolingType): MAX, AVG, SUM, PNORM.
GlobalPoolingLayer parity (nn/layers/pooling/GlobalPoolingLayer.java):
pools CNN [mb,h,w,c]→[mb,c] or RNN [mb,t,f]→[mb,f], honoring per-timestep
masks via MaskedReductionUtil-equivalent masked reductions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer
from .convolution import _conv_out_size, _pair, _padding

Array = jax.Array


def _pool2d(x: Array, kind: str, kernel, stride, padding: str, pnorm: int = 2) -> Array:
    kh, kw = kernel
    window = (1, kh, kw, 1)
    strides = (1, stride[0], stride[1], 1)
    # NOTE: init values must be Python scalars — jax pattern-matches
    # reduce_window(max/add) to its differentiable primitives only then.
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, padding)
    if kind in ("avg", "sum"):
        y = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if kind == "sum":
            return y
        if padding == "VALID":
            return y / (kh * kw)
        counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window, strides, padding)
        return y / counts
    if kind == "pnorm":
        p = float(pnorm)
        y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
        return y ** (1.0 / p)
    raise ValueError(f"unknown pooling type {kind}")


@register_layer
@dataclasses.dataclass
class Subsampling2D(Layer):
    """2-D pooling (reference SubsamplingLayer conf)."""

    pooling: str = "max"
    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def __post_init__(self):
        self.kernel = _pair(self.kernel)
        self.stride = _pair(self.stride)

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        h = _conv_out_size(in_type.height, self.kernel[0], self.stride[0], self.convolution_mode)
        w = _conv_out_size(in_type.width, self.kernel[1], self.stride[1], self.convolution_mode)
        return InputType.convolutional(h, w, in_type.channels)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        y = _pool2d(x, self.pooling, self.kernel, self.stride, _padding(self.convolution_mode), self.pnorm)
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class Subsampling1D(Layer):
    """1-D pooling over [mb, t, f] (reference Subsampling1DLayer)."""

    pooling: str = "max"
    kernel: int = 2
    stride: int = 2
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        t = in_type.timesteps
        if t is not None:
            t = _conv_out_size(t, self.kernel, self.stride, self.convolution_mode)
        return InputType.recurrent(in_type.size, t)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x4 = x[:, :, None, :]  # [mb, t, 1, f]
        y = _pool2d(x4, self.pooling, (self.kernel, 1), (self.stride, 1),
                    _padding(self.convolution_mode), self.pnorm)
        return ForwardOut(y[:, :, 0, :], state, mask)


@register_layer
@dataclasses.dataclass
class GlobalPooling(Layer):
    """Global pooling over spatial/time dims with mask support
    (reference GlobalPoolingLayer + MaskedReductionUtil)."""

    pooling: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        if in_type.kind == "rnn":
            return InputType.feed_forward(in_type.size)
        if in_type.kind == "cnn":
            return InputType.feed_forward(in_type.channels)
        return in_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        if x.ndim == 4:        # [mb, h, w, c] → [mb, c]
            axes = (1, 2)
            m = None
        elif x.ndim == 3:      # [mb, t, f] → [mb, f]
            axes = (1,)
            m = mask            # [mb, t]
        else:
            raise ValueError(f"GlobalPooling expects rank 3/4, got {x.shape}")

        if m is not None:
            mx = m[..., None].astype(x.dtype)
            if self.pooling == "max":
                y = jnp.max(jnp.where(mx > 0, x, -jnp.inf), axis=axes)
            elif self.pooling == "sum":
                y = jnp.sum(x * mx, axis=axes)
            elif self.pooling == "avg":
                y = jnp.sum(x * mx, axis=axes) / jnp.maximum(jnp.sum(mx, axis=axes), 1.0)
            elif self.pooling == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum((jnp.abs(x) * mx) ** p, axis=axes) ** (1.0 / p)
            else:
                raise ValueError(self.pooling)
        else:
            if self.pooling == "max":
                y = jnp.max(x, axis=axes)
            elif self.pooling == "sum":
                y = jnp.sum(x, axis=axes)
            elif self.pooling == "avg":
                y = jnp.mean(x, axis=axes)
            elif self.pooling == "pnorm":
                p = float(self.pnorm)
                y = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
            else:
                raise ValueError(self.pooling)
        # mask is consumed by the reduction (reference: GlobalPooling clears it)
        return ForwardOut(y, state, None)
