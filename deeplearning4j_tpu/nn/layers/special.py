"""Special wrappers: FrozenLayer, CenterLossOutputLayer.

FrozenLayer — reference nn/layers/FrozenLayer.java (+ misc/FrozenLayer
conf): wraps any layer; params take no gradient.  Implemented with
``lax.stop_gradient`` on the inner params — the optimizer never sees
nonzero gradients, matching the reference's zero-filled gradient view.

CenterLossOutputLayer — reference nn/conf/layers/CenterLossOutputLayer.java:
softmax head + λ·‖f(x) − c_y‖² with per-class centers updated by moving
average (alpha); centers live in layer state, not params.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.losses import get_loss
from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer
from .feedforward import Dense

Array = jax.Array


@register_layer
@dataclasses.dataclass
class FrozenLayer(Layer):
    layer: Optional[Layer] = None

    def infer_nin(self, in_type: InputType) -> None:
        self.layer.infer_nin(in_type)

    def output_type(self, in_type: InputType) -> InputType:
        return self.layer.output_type(in_type)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return self.layer.init_params(rng, in_type, dtype)

    def init_state(self, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return self.layer.init_state(in_type, dtype)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        frozen = jax.tree_util.tree_map(lax.stop_gradient, params)
        # train=False inside: frozen layers run in inference mode (reference
        # FrozenLayer forces test-time behavior for dropout etc.)
        return self.layer.forward(frozen, state, x, train=False, rng=rng, mask=mask)

    def regularization_score(self, params):
        return jnp.zeros((), jnp.float32)

    def has_params(self) -> bool:
        return self.layer.has_params()


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(Dense):
    """Softmax + center loss (Wen et al. 2016), reference
    CenterLossOutputLayer: gradient check suite CNNGradientCheckTest covers
    it via lambda/alpha hyperparams."""

    loss: str = "mcxent"
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_state(self, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {"centers": jnp.zeros((self.n_out, self.n_in), dtype)}

    def score(self, params, state, x, labels, *, mask: Optional[Array] = None) -> Array:
        pre = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            pre = pre + params["b"].astype(x.dtype)
        base = get_loss(self.loss)(labels, pre, self.activation or "identity", mask)
        centers = state["centers"].astype(x.dtype)           # [C, n_in]
        assigned = labels @ centers                           # [mb, n_in]
        center_term = 0.5 * self.lambda_ * jnp.mean(jnp.sum((x - assigned) ** 2, axis=-1))
        return base + center_term

    def score_examples(self, params, state, x, labels, *,
                       mask: Optional[Array] = None) -> Array:
        pre = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            pre = pre + params["b"].astype(x.dtype)
        from ...ops.losses import summed_per_example
        pe = summed_per_example(self.loss, labels, pre, self.activation, mask)
        centers = state["centers"].astype(x.dtype)
        assigned = labels @ centers
        return pe + 0.5 * self.lambda_ * jnp.sum((x - assigned) ** 2, axis=-1)

    def update_centers(self, state, x, labels) -> Dict[str, Array]:
        """Moving-average center update (runs outside the gradient path)."""
        centers = state["centers"]
        counts = jnp.sum(labels, axis=0)[:, None]            # [C,1]
        sums = labels.T @ x.astype(centers.dtype)            # [C, n_in]
        means = sums / jnp.maximum(counts, 1.0)
        upd = jnp.where(counts > 0, centers + self.alpha * (means - centers), centers)
        return {"centers": upd}
