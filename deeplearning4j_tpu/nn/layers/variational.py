"""Variational autoencoder layer.

Reference: nn/conf/layers/variational/VariationalAutoencoder.java +
nn/layers/variational/VariationalAutoencoder.java (1,163 LoC): MLP encoder →
Gaussian q(z|x) → MLP decoder → reconstruction distribution
(Bernoulli or Gaussian); ELBO = E[log p(x|z)] - KL(q||p).  When stacked in a
network, ``forward`` emits the q(z|x) mean (matching the reference's
activate() in supervised mode); ``elbo_score`` is the pretrain objective.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ...ops.activations import get_activation
from ...ops.initializers import init_weight
from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


@register_layer
@dataclasses.dataclass
class VariationalAutoencoder(Layer):
    n_in: int = 0
    n_out: int = 0                       # latent size (reference nOut = nLatent)
    encoder_layer_sizes: List[int] = dataclasses.field(default_factory=lambda: [256])
    decoder_layer_sizes: List[int] = dataclasses.field(default_factory=lambda: [256])
    activation: str = "leakyrelu"        # hidden activation (reference pzxActivationFn separate)
    pzx_activation: str = "identity"
    reconstruction: str = "bernoulli"    # or "gaussian"
    num_samples: int = 1

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.flat_size()

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _mlp_init(self, rng, sizes, dtype):
        params = []
        keys = jax.random.split(rng, len(sizes) - 1)
        for k, (a, b) in zip(keys, zip(sizes[:-1], sizes[1:])):
            params.append({
                "W": init_weight(k, (a, b), self._winit(), a, b, dtype),
                "b": jnp.zeros((b,), dtype),
            })
        return params

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict:
        ke, km, kv, kd, ko = jax.random.split(rng, 5)
        enc_sizes = [self.n_in] + list(self.encoder_layer_sizes)
        dec_sizes = [self.n_out] + list(self.decoder_layer_sizes)
        eh = self.encoder_layer_sizes[-1]
        dh = self.decoder_layer_sizes[-1]
        out_size = self.n_in * (2 if self.reconstruction == "gaussian" else 1)
        return {
            "enc": self._mlp_init(ke, enc_sizes, dtype),
            "z_mean": {"W": init_weight(km, (eh, self.n_out), self._winit(), eh, self.n_out, dtype),
                       "b": jnp.zeros((self.n_out,), dtype)},
            "z_logvar": {"W": init_weight(kv, (eh, self.n_out), self._winit(), eh, self.n_out, dtype),
                         "b": jnp.zeros((self.n_out,), dtype)},
            "dec": self._mlp_init(kd, dec_sizes, dtype),
            "out": {"W": init_weight(ko, (dh, out_size), self._winit(), dh, out_size, dtype),
                    "b": jnp.zeros((out_size,), dtype)},
        }

    def _mlp(self, layers, x):
        act = get_activation(self.activation)
        for p in layers:
            x = act(x @ p["W"].astype(x.dtype) + p["b"].astype(x.dtype))
        return x

    def encode(self, params, x):
        h = self._mlp(params["enc"], x)
        pzx = get_activation(self.pzx_activation)
        mean = pzx(h @ params["z_mean"]["W"].astype(x.dtype) + params["z_mean"]["b"].astype(x.dtype))
        logvar = h @ params["z_logvar"]["W"].astype(x.dtype) + params["z_logvar"]["b"].astype(x.dtype)
        return mean, logvar

    def decode(self, params, z):
        h = self._mlp(params["dec"], z)
        return h @ params["out"]["W"].astype(z.dtype) + params["out"]["b"].astype(z.dtype)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = x.reshape((x.shape[0], -1))
        mean, _ = self.encode(params, x)
        return ForwardOut(mean, state, mask)

    def elbo_score(self, params, x, *, rng, num_samples: Optional[int] = None) -> Array:
        """Negative ELBO (to minimize), mean over minibatch."""
        x = x.reshape((x.shape[0], -1))
        mean, logvar = self.encode(params, x)
        ns = num_samples or self.num_samples
        keys = jax.random.split(rng, ns)

        def one_sample(k):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            return self._recon_log_lik(params, z, x)

        recon_ll = jnp.mean(jnp.stack([one_sample(k) for k in keys]), axis=0)
        kl = -0.5 * jnp.sum(1 + logvar - mean ** 2 - jnp.exp(logvar), axis=-1)
        return jnp.mean(kl - recon_ll)

    def reconstruction_score(self, params, x, *, rng=None, train=False) -> Array:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return self.elbo_score(params, x, rng=rng)

    def generate(self, params, z):
        """Decode latent samples to reconstruction-distribution params
        (reference generateAtMeanGivenZ)."""
        out = self.decode(params, z)
        if self.reconstruction == "bernoulli":
            return jax.nn.sigmoid(out)
        return out[:, :self.n_in]

    def _recon_log_lik(self, params, z, x):
        """log p(x|z) per example under the reconstruction distribution."""
        out = self.decode(params, z)
        if self.reconstruction == "bernoulli":
            ll = -(jnp.maximum(out, 0) - out * x
                   + jnp.log1p(jnp.exp(-jnp.abs(out))))
            return jnp.sum(ll, axis=-1)
        mu, lv = out[:, :self.n_in], out[:, self.n_in:]
        ll = -0.5 * (lv + jnp.log(2 * jnp.pi) + (x - mu) ** 2 / jnp.exp(lv))
        return jnp.sum(ll, axis=-1)

    def reconstruction_log_probability(self, params, x, *, rng,
                                       num_samples: int = 5) -> Array:
        """Importance-weighted estimate of log p(x) per example [mb]
        (reference VariationalAutoencoder.reconstructionLogProbability:977):

            log p(x) ≈ log (1/K) Σ_k  p(x|z_k) p(z_k) / q(z_k|x),
            z_k ~ q(z|x)

        — the IWAE bound (Burda et al. 2015), exact as K → ∞.  Higher is
        more probable; use as an anomaly/novelty score."""
        x = x.reshape((x.shape[0], -1))
        mean, logvar = self.encode(params, x)
        keys = jax.random.split(rng, num_samples)

        def log_w(k):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            log_pxz = self._recon_log_lik(params, z, x)
            log_pz = -0.5 * jnp.sum(z ** 2 + jnp.log(2 * jnp.pi), axis=-1)
            log_qzx = -0.5 * jnp.sum(
                logvar + jnp.log(2 * jnp.pi) + eps ** 2, axis=-1)
            return log_pxz + log_pz - log_qzx

        lw = jnp.stack([log_w(k) for k in keys])       # [K, mb]
        return jax.nn.logsumexp(lw, axis=0) - jnp.log(num_samples)

    def reconstruction_probability(self, params, x, *, rng,
                                   num_samples: int = 5) -> Array:
        """exp of reconstruction_log_probability (reference
        reconstructionProbability) — underflows to 0 for high-dim data;
        prefer the log form, as the reference javadoc also advises."""
        return jnp.exp(self.reconstruction_log_probability(
            params, x, rng=rng, num_samples=num_samples))
