"""Feed-forward layer family.

Parity targets in the reference:
  Dense              — nn/conf/layers/DenseLayer.java + nn/layers/feedforward/dense/DenseLayer.java
  OutputLayer        — nn/conf/layers/OutputLayer.java (+ BaseOutputLayer score math)
  LossLayer          — nn/conf/layers/LossLayer.java (no params, loss only)
  ActivationLayer    — nn/conf/layers/ActivationLayer.java
  DropoutLayer       — nn/conf/layers/DropoutLayer.java
  Embedding          — nn/conf/layers/EmbeddingLayer.java (index lookup ≡ one-hot matmul)
  ElementWiseMultiplication — nn/conf/layers/misc/ElementWiseMultiplicationLayer.java
  AutoEncoder        — nn/conf/layers/AutoEncoder.java (denoising autoencoder,
                       pretrain reconstruction; nn/layers/feedforward/autoencoder/AutoEncoder.java)
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ...ops.initializers import init_weight
from ...ops.losses import get_loss, summed_per_example
from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


def _flatten_ff(x: Array) -> Array:
    """Accept [mb, f] directly; collapse trailing dims of cnn_flat inputs."""
    if x.ndim == 2:
        return x
    return x.reshape((x.shape[0], -1))


@register_layer
@dataclasses.dataclass
class Dense(Layer):
    """Fully connected: y = act(x·W + b).  RNN inputs [mb,t,f] are handled
    time-distributed (the reference forces a preprocessor; we broadcast)."""

    wants = "ff"

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size if in_type.kind in ("ff", "rnn") else in_type.flat_size()

    def output_type(self, in_type: InputType) -> InputType:
        if in_type.kind == "rnn":
            return InputType.recurrent(self.n_out, in_type.timesteps)
        return InputType.feed_forward(self.n_out)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        p = self._dense_init(rng, self.n_in, self.n_out, dtype)
        if not self.has_bias:
            del p["b"]
        return p

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        y = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class OutputLayer(Dense):
    """Dense + loss head (reference OutputLayer extends BaseOutputLayer).

    ``loss`` names an ops.losses entry; score() fuses softmax/sigmoid with
    the loss in log-space.
    """

    loss: str = "mcxent"

    def score(self, params, state, x, labels, *, mask: Optional[Array] = None) -> Array:
        pre = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            pre = pre + params["b"].astype(x.dtype)
        return get_loss(self.loss)(labels, pre, self.activation or "identity", mask)

    def score_examples(self, params, state, x, labels, *,
                       mask: Optional[Array] = None) -> Array:
        """Per-example scores [mb] (reference scoreExamples semantics:
        loss summed over output features, NOT batch-reduced)."""
        pre = x @ params["W"].astype(x.dtype)
        if self.has_bias:
            pre = pre + params["b"].astype(x.dtype)
        return summed_per_example(self.loss, labels, pre, self.activation, mask)


@register_layer
@dataclasses.dataclass
class LossLayer(Layer):
    """Parameter-free loss head (reference LossLayer: 'loss only, no params')."""

    loss: str = "mse"

    def has_params(self) -> bool:
        return False

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(self._act(x), state, mask)

    def score(self, params, state, x, labels, *, mask: Optional[Array] = None) -> Array:
        return get_loss(self.loss)(labels, x, self.activation or "identity", mask)

    def score_examples(self, params, state, x, labels, *,
                       mask: Optional[Array] = None) -> Array:
        return summed_per_example(self.loss, labels, x, self.activation, mask)


@register_layer
@dataclasses.dataclass
class ActivationLayer(Layer):
    def has_params(self) -> bool:
        return False

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(self._act(x), state, mask)


@register_layer
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (reference DropoutLayer: identity at test time)."""

    def has_params(self) -> bool:
        return False

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(self._maybe_dropout(x, train, rng), state, mask)


@register_layer
@dataclasses.dataclass
class Embedding(Layer):
    """Index → vector lookup (reference EmbeddingLayer: 'equivalent to a
    DenseLayer with a one-hot input'; input is [mb, 1] int indices).

    Accepts int arrays [mb] or [mb, 1]; gather replaces the reference's
    one-hot matmul — XLA lowers gather efficiently on TPU.
    """

    n_in: int = 0   # vocab size
    n_out: int = 0  # embedding dim
    has_bias: bool = True

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        p = self._dense_init(rng, self.n_in, self.n_out, dtype)
        if not self.has_bias:
            del p["b"]
        return p

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class EmbeddingSequence(Embedding):
    """Sequence of indices [mb, t] → [mb, t, n_out] (reference
    EmbeddingSequenceLayer, added for RNN/text paths)."""

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, in_type.timesteps)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        idx = x.astype(jnp.int32)
        y = params["W"][idx]  # [mb, t, n_out]
        if self.has_bias:
            y = y + params["b"]
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class ElementWiseMultiplication(Layer):
    """y = act(x ⊙ w + b) (reference misc/ElementWiseMultiplicationLayer)."""

    n_in: int = 0

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {
            "W": jnp.ones((self.n_in,), dtype),
            "b": jnp.full((self.n_in,), self.bias_init, dtype),
        }

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        y = x * params["W"].astype(x.dtype) + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder with tied-ish params (reference AutoEncoder:
    params W, b (hidden), vb (visible); corruption level; reconstruction
    distribution is the layer loss).

    forward() yields the hidden code (as the reference's activate does);
    ``reconstruction_score`` gives the pretrain loss.
    """

    n_in: int = 0
    n_out: int = 0
    corruption_level: float = 0.3
    loss: str = "mse"

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        k1, _ = jax.random.split(rng)
        return {
            "W": init_weight(k1, (self.n_in, self.n_out), self._winit(), self.n_in, self.n_out, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.zeros((self.n_in,), dtype),
        }

    def encode(self, params, x):
        return self._act(x @ params["W"].astype(x.dtype) + params["b"].astype(x.dtype))

    def decode(self, params, h):
        return self._act(h @ params["W"].T.astype(h.dtype) + params["vb"].astype(h.dtype))

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        return ForwardOut(self.encode(params, x), state, mask)

    def reconstruction_score(self, params, x, *, rng=None, train=False) -> Array:
        xin = x
        if train and self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            xin = jnp.where(keep, x, 0.0).astype(x.dtype)
        recon = self.decode(params, self.encode(params, xin))
        return get_loss(self.loss)(x, recon, "identity")


@register_layer
@dataclasses.dataclass
class RBM(Layer):
    """Restricted Boltzmann Machine (reference nn/conf/layers/RBM.java +
    nn/layers/feedforward/rbm/RBM.java): binary-binary by default, CD-k
    pretraining via ``contrastive_divergence``; ``forward`` is propUp
    (the hidden probabilities), so an RBM stacks like any dense layer for
    supervised fine-tuning — the classic DBN recipe.

    Params: "W" [n_in, n_out], hidden bias "b", visible bias "vb"
    (PretrainParamInitializer: VISIBLE_BIAS_KEY)."""

    n_in: int = 0
    n_out: int = 0
    activation: str = "sigmoid"
    k: int = 1                  # CD-k steps

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size if in_type.kind in ("ff", "rnn") else in_type.flat_size()

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        k1, _ = jax.random.split(rng)
        return {
            "W": init_weight(k1, (self.n_in, self.n_out), self._winit(),
                             self.n_in, self.n_out, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),
        }

    def prop_up(self, params, v):
        """Hidden activation — honors the configured ``activation``
        (reference HiddenUnit; sigmoid = binary units, the CD default)."""
        return self._act(v @ params["W"].astype(v.dtype) + params["b"].astype(v.dtype))

    def prop_down(self, params, h):
        """Visible reconstruction — binary (sigmoid) visible units."""
        return jax.nn.sigmoid(h @ params["W"].T.astype(h.dtype) + params["vb"].astype(h.dtype))

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        return ForwardOut(self.prop_up(params, x), state, mask)

    def cd_gradients(self, params, v0, rng):
        """CD-k statistics as a GRADIENT dict (minimization convention, so
        the containers can drive it through the layer's real updater — the
        reference's RBM also routes its Gibbs statistics through the normal
        Solver/updater path).  Returns (grads, reconstruction_error).
        Requires binary (sigmoid) hidden units — Bernoulli sampling needs
        probabilities."""
        if (self.activation or "sigmoid") != "sigmoid":
            raise ValueError("contrastive divergence requires activation="
                             f"'sigmoid' (binary hidden units), got {self.activation!r}")
        k0, key = jax.random.split(rng)
        h_prob = self.prop_up(params, v0)
        h_sample = jax.random.bernoulli(k0, h_prob).astype(v0.dtype)
        v_neg, h_neg = v0, h_prob
        for _ in range(self.k):
            key, k1 = jax.random.split(key)
            v_neg = self.prop_down(params, h_sample)
            h_neg = self.prop_up(params, v_neg)
            h_sample = jax.random.bernoulli(k1, h_neg).astype(v0.dtype)
        mb = v0.shape[0]
        dW = (v0.T @ h_prob - v_neg.T @ h_neg) / mb
        db = jnp.mean(h_prob - h_neg, axis=0)
        dvb = jnp.mean(v0 - v_neg, axis=0)
        grads = {"W": -dW.astype(params["W"].dtype),
                 "b": -db.astype(params["b"].dtype),
                 "vb": -dvb.astype(params["vb"].dtype)}
        err = jnp.mean(jnp.sum((v0 - v_neg) ** 2, axis=1))
        return grads, err

    def contrastive_divergence(self, params, v0, rng, lr=0.1):
        """One plain-SGD CD-k update (convenience/back-compat form of
        ``cd_gradients``; ``lr`` may be a traced scalar).  Returns
        (new_params, reconstruction_error)."""
        grads, err = self.cd_gradients(params, v0, rng)
        new = {k: params[k] - lr * grads[k] for k in params}
        return new, err
