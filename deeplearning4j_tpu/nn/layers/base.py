"""Layer base: config-as-data + pure-function runtime in one class.

The reference splits every layer into a declarative config
(nn/conf/layers/*.java, JSON-serializable via Jackson) and a runtime impl
(nn/layers/*.java) with hand-written ``activate``/``backpropGradient``
(e.g. ConvolutionLayer.java:197-213 im2col+gemm).  Here one dataclass plays
both roles: fields are the JSON-serializable hyperparameters; ``forward`` is
a pure jax function (backward derived by autodiff); ``init_params`` replaces
the 13 ParamInitializer classes (nn/params/).

Param-name parity: weight key "W", bias key "b" (DefaultParamInitializer),
recurrent weights "RW" (LSTMParamInitializer RECURRENT_WEIGHT_KEY), BN
"gamma"/"beta" + state "mean"/"var".

Serde: every config dataclass (layers, updaters, preprocessors, vertices)
registers in one registry and round-trips through ``{"type": ClsName, ...}``
dicts — the equivalent of the reference's Jackson subtype registry
(nn/conf/serde/).
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.activations import get_activation
from ...ops.initializers import init_weight
from ..conf.inputs import InputType

Array = jax.Array

# ---------------------------------------------------------------------------
# serde registry (shared by layers, vertices, updaters, preprocessors)
# ---------------------------------------------------------------------------

CONFIG_REGISTRY: Dict[str, type] = {}

#: state-dict slot for activation-dependent auxiliary losses (e.g. the MoE
#: router's Switch load-balance term).  Layers write the CURRENT batch's
#: aux term here from forward(); the containers add every such slot to the
#: training objective (train only — eval scores stay pure data loss).
AUX_LOSS_KEY = "__aux_loss__"


def register_config(cls):
    """Class decorator: make a dataclass JSON round-trippable by type name."""
    CONFIG_REGISTRY[cls.__name__] = cls
    return cls


register_layer = register_config  # alias, reads better at layer definitions


def config_to_dict(obj: Any) -> Any:
    """Recursively encode a registered dataclass to plain JSON types."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d: Dict[str, Any] = {"type": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = config_to_dict(getattr(obj, f.name))
        return d
    if isinstance(obj, (list, tuple)):
        return [config_to_dict(v) for v in obj]
    if isinstance(obj, dict):
        return {k: config_to_dict(v) for k, v in obj.items()}
    if isinstance(obj, np.dtype):
        return str(obj)
    return obj


#: config types that live outside the eagerly-imported nn tree — imported
#: on first deserialization so saved models load in fresh processes
_LAZY_CONFIG_PROVIDERS = {
    "MoE": "deeplearning4j_tpu.parallel.moe",
    "TransformerBlock": "deeplearning4j_tpu.models.transformer",
    "PositionalEmbedding": "deeplearning4j_tpu.models.transformer",
}


def config_from_dict(d: Any) -> Any:
    """Inverse of config_to_dict."""
    if isinstance(d, dict) and "type" in d and d["type"] not in CONFIG_REGISTRY \
            and d["type"] in _LAZY_CONFIG_PROVIDERS:
        import importlib

        importlib.import_module(_LAZY_CONFIG_PROVIDERS[d["type"]])
    if isinstance(d, dict) and "type" in d and d["type"] in CONFIG_REGISTRY:
        cls = CONFIG_REGISTRY[d["type"]]
        fields = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: config_from_dict(v) for k, v in d.items() if k in fields}
        return cls(**kwargs)
    if isinstance(d, dict):
        return {k: config_from_dict(v) for k, v in d.items()}
    if isinstance(d, list):
        return [config_from_dict(v) for v in d]
    return d


def layer_to_dict(layer: "Layer") -> dict:
    return config_to_dict(layer)


def layer_from_dict(d: dict) -> "Layer":
    out = config_from_dict(d)
    if not isinstance(out, Layer):
        raise ValueError(f"not a layer dict: {d.get('type')}")
    return out


# ---------------------------------------------------------------------------
# forward result
# ---------------------------------------------------------------------------


class ForwardOut(NamedTuple):
    """Result of Layer.forward: activations, new non-trainable state, mask.

    ``mask`` threads per-timestep masks through the stack the way the
    reference's feedForwardMaskArray does (nn/graph/vertex/GraphVertex.java:142).
    ``carry`` is the recurrent hidden state a layer emits when driven with an
    explicit carry (TBPTT chunking / rnnTimeStep streaming — reference
    MultiLayerNetwork.doTruncatedBPTT():1386, rnnTimeStep():2636).
    """

    y: Array
    state: Dict[str, Array]
    mask: Optional[Array]
    carry: Any = None


# ---------------------------------------------------------------------------
# base layer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Layer:
    """Base hyperparameters shared by all layers (reference BaseLayer conf).

    ``dropout`` is *input* dropout, applied to the layer input during
    training (reference nn/conf/dropout/Dropout.java semantics: retain prob
    = 1 - dropout... DL4J's `dropOut(p)` is the *retain* probability in 0.x;
    here ``dropout`` is the DROP probability for clarity, documented).
    ``l1``/``l2`` apply to weight params only (DL4J default).
    """

    #: ``activation``/``weight_init`` default to None = "unset": the builder
    #: fills them from its global defaults (the reference's global-conf
    #: inheritance, NeuralNetConfiguration.Builder), else they resolve to
    #: "identity"/"xavier".  Layer subclasses with a real domain default
    #: (e.g. LSTM tanh) declare it explicitly and win over builder defaults.
    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: float = 0.0
    #: float = classic inverted dropout (drop prob), or an IDropout config
    #: (AlphaDropout/GaussianDropout/GaussianNoise, nn/conf/regularizers.py)
    dropout: Any = 0.0
    l1: float = 0.0
    l2: float = 0.0
    updater: Optional[Any] = None  # per-layer IUpdater override (nn/updaters)
    trainable: bool = True
    #: IWeightNoise (DropConnect/WeightNoise) applied to weight params on
    #: each training forward (reference nn/conf/weightnoise/)
    weight_noise: Optional[Any] = None
    #: IConstraints applied after every parameter update (reference
    #: nn/conf/constraint/, e.g. MaxNormConstraint)
    constraints: Optional[Any] = None

    #: expected input kind: None = any, else "ff" / "cnn" / "rnn".  Drives
    #: automatic preprocessor insertion (the reference's
    #: InputType.getPreProcessorForInputType pass).  ClassVar: not serialized.
    wants: ClassVar[Optional[str]] = None
    #: True for layers whose forward() accepts a ``carry`` kwarg (LSTM/RNN);
    #: enables TBPTT chunking and streaming inference.
    recurrent: ClassVar[bool] = False

    def init_carry(self, mb: int, dtype=jnp.float32):
        """Zero recurrent carry for batch size ``mb`` (None if stateless)."""
        return None

    # -- shape inference ---------------------------------------------------
    def output_type(self, in_type: InputType) -> InputType:
        return in_type

    def infer_nin(self, in_type: InputType) -> None:
        """Fill in n_in style fields from the incoming InputType (the
        equivalent of MultiLayerConfiguration's setNIn / InputType pass)."""

    # -- params/state ------------------------------------------------------
    def init_params(self, rng: Array, in_type: InputType, dtype=jnp.float32) -> Dict[str, Array]:
        return {}

    def init_state(self, in_type: InputType, dtype=jnp.float32) -> Dict[str, Array]:
        return {}

    # -- runtime -----------------------------------------------------------
    def forward(
        self,
        params: Dict[str, Array],
        state: Dict[str, Array],
        x: Array,
        *,
        train: bool = False,
        rng: Optional[Array] = None,
        mask: Optional[Array] = None,
    ) -> ForwardOut:
        raise NotImplementedError

    # -- shared helpers ----------------------------------------------------
    def _maybe_dropout(self, x: Array, train: bool, rng: Optional[Array]) -> Array:
        d = self.dropout
        if not train or d is None or (isinstance(d, (int, float)) and d <= 0.0):
            return x
        if rng is None:
            raise ValueError(f"layer {self.name}: dropout requires an rng key in training")
        from ..conf.regularizers import apply_dropout
        return apply_dropout(d, rng, x, train)

    def _act(self, x: Array) -> Array:
        return get_activation(self.activation or "identity")(x)

    def _winit(self) -> str:
        return self.weight_init or "xavier"

    def _dense_init(self, rng, n_in: int, n_out: int, dtype) -> Dict[str, Array]:
        wk, _ = jax.random.split(rng)
        return {
            "W": init_weight(wk, (n_in, n_out), self._winit(), n_in, n_out, dtype),
            "b": jnp.full((n_out,), self.bias_init, dtype),
        }

    def regularization_score(self, params: Dict[str, Array]) -> Array:
        """l1*|W| + 0.5*l2*W² over weight-class params (reference
        BaseLayer.calcL2/calcL1: biases excluded by default)."""
        if (self.l1 == 0.0 and self.l2 == 0.0) or not params:
            return jnp.zeros((), jnp.float32)
        leaves = [v for k, v in params.items()
                  if k not in ("b", "beta", "gamma", "mean", "var")]
        acc = jnp.promote_types(jnp.float32, leaves[0].dtype) if leaves else jnp.float32
        score = jnp.zeros((), acc)
        for v in leaves:
            va = v.astype(acc)
            if self.l1:
                score = score + self.l1 * jnp.sum(jnp.abs(va))
            if self.l2:
                score = score + 0.5 * self.l2 * jnp.sum(va * va)
        return score

    def has_params(self) -> bool:
        return True
