"""Convolution family — XLA conv lowering replaces the reference's two paths
(im2col+gemm: nn/layers/convolution/ConvolutionLayer.java:197-213, and the
cuDNN helper: deeplearning4j-cuda CudnnConvolutionHelper.java:54).

Native layout NHWC / kernels HWIO (TPU-preferred); the reference is NCHW /
[out,in,kh,kw].  ConvolutionMode parity (nn/conf/ConvolutionMode.java):
``same`` → SAME, ``truncate`` → VALID (floor), ``strict`` → VALID but
init-time error when sizes don't divide cleanly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.initializers import init_weight
from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_out_size(size: int, k: int, s: int, mode: str, dilation: int = 1) -> int:
    eff_k = (k - 1) * dilation + 1
    if mode == "same":
        return -(-size // s)
    out = (size - eff_k) // s + 1
    if mode == "strict" and (size - eff_k) % s != 0:
        raise ValueError(
            f"ConvolutionMode.Strict: size {size} kernel {k} stride {s} leaves remainder "
            f"(reference ConvolutionMode semantics)")
    return out


def _padding(mode: str) -> str:
    return "SAME" if mode == "same" else "VALID"


@register_layer
@dataclasses.dataclass
class Convolution2D(Layer):
    """2-D convolution (reference ConvolutionLayer conf).  Kernel HWIO."""

    wants = "cnn"

    n_in: int = 0   # input channels
    n_out: int = 0  # output channels
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def __post_init__(self):
        self.kernel = _pair(self.kernel)
        self.stride = _pair(self.stride)
        self.dilation = _pair(self.dilation)

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.channels

    def output_type(self, in_type: InputType) -> InputType:
        h = _conv_out_size(in_type.height, self.kernel[0], self.stride[0], self.convolution_mode, self.dilation[0])
        w = _conv_out_size(in_type.width, self.kernel[1], self.stride[1], self.convolution_mode, self.dilation[1])
        return InputType.convolutional(h, w, self.n_out)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        kh, kw = self.kernel
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        p = {"W": init_weight(rng, (kh, kw, self.n_in, self.n_out), self._winit(), fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def _conv(self, x, w):
        return lax.conv_general_dilated(
            x, w,
            window_strides=self.stride,
            padding=_padding(self.convolution_mode),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        y = self._conv(x, params["W"].astype(x.dtype))
        # NOTE: no checkpoint_name remat tag here — measured: the name
        # primitive blocks conv-epilogue fusion (~20% on LeNet) even with
        # no checkpoint policy active, and the save-only-conv-outputs
        # policy itself lost to XLA's default (docs/resnet_profile.md).
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class Convolution1D(Layer):
    """1-D (temporal) convolution over [mb, t, f] (reference Convolution1DLayer)."""

    n_in: int = 0
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    dilation: int = 1
    convolution_mode: str = "same"
    has_bias: bool = True

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_in == 0:
            self.n_in = in_type.size

    def output_type(self, in_type: InputType) -> InputType:
        t = in_type.timesteps
        if t is not None:
            t = _conv_out_size(t, self.kernel, self.stride, self.convolution_mode, self.dilation)
        return InputType.recurrent(self.n_out, t)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        fan_in = self.n_in * self.kernel
        fan_out = self.n_out * self.kernel
        p = {"W": init_weight(rng, (self.kernel, self.n_in, self.n_out), self._winit(), fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["W"].astype(x.dtype),
            window_strides=(self.stride,),
            padding=_padding(self.convolution_mode),
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class Deconvolution2D(Convolution2D):
    """Transposed convolution (reference Deconvolution2D conf)."""

    def output_type(self, in_type: InputType) -> InputType:
        if self.convolution_mode == "same":
            h = in_type.height * self.stride[0]
            w = in_type.width * self.stride[1]
        else:
            h = (in_type.height - 1) * self.stride[0] + (self.kernel[0] - 1) * self.dilation[0] + 1
            w = (in_type.width - 1) * self.stride[1] + (self.kernel[1] - 1) * self.dilation[1] + 1
        return InputType.convolutional(h, w, self.n_out)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        y = lax.conv_transpose(
            x, params["W"].astype(x.dtype),
            strides=self.stride,
            padding=_padding(self.convolution_mode),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class SeparableConvolution2D(Convolution2D):
    """Depthwise + pointwise conv (reference SeparableConvolution2D:
    depthWiseWeights [depthMult,in,kh,kw] + pointWiseWeights).  Here
    depthwise kernel is HWI(M) via feature_group_count=n_in."""

    depth_multiplier: int = 1

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        kh, kw = self.kernel
        k1, k2 = jax.random.split(rng)
        dm = self.depth_multiplier
        fan_in_d = kh * kw
        p = {
            "dW": init_weight(k1, (kh, kw, 1, self.n_in * dm), self._winit(), fan_in_d, fan_in_d * dm, dtype),
            "pW": init_weight(k2, (1, 1, self.n_in * dm, self.n_out), self._winit(), self.n_in * dm, self.n_out, dtype),
        }
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        y = lax.conv_general_dilated(
            x, params["dW"].astype(x.dtype),
            window_strides=self.stride,
            padding=_padding(self.convolution_mode),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in,
        )
        y = lax.conv_general_dilated(
            y, params["pW"].astype(x.dtype),
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            y = y + params["b"].astype(x.dtype)
        return ForwardOut(self._act(y), state, mask)


@register_layer
@dataclasses.dataclass
class ZeroPadding2D(Layer):
    """Spatial zero padding (reference ZeroPaddingLayer).  padding =
    (top, bottom, left, right)."""

    padding: Tuple[int, int, int, int] = (1, 1, 1, 1)

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        t, b, l, r = self.padding
        return InputType.convolutional(in_type.height + t + b, in_type.width + l + r, in_type.channels)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        t, b, l, r = self.padding
        y = jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class ZeroPadding1D(Layer):
    """Temporal zero padding (reference ZeroPadding1DLayer)."""

    padding: Tuple[int, int] = (1, 1)

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        t = in_type.timesteps
        if t is not None:
            t = t + self.padding[0] + self.padding[1]
        return InputType.recurrent(in_type.size, t)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        y = jnp.pad(x, ((0, 0), (self.padding[0], self.padding[1]), (0, 0)))
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class Cropping2D(Layer):
    """Spatial cropping (top, bottom, left, right)."""

    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        t, b, l, r = self.cropping
        return InputType.convolutional(in_type.height - t - b, in_type.width - l - r, in_type.channels)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        t, b, l, r = self.cropping
        h, w = x.shape[1], x.shape[2]
        return ForwardOut(x[:, t:h - b, l:w - r, :], state, mask)


@register_layer
@dataclasses.dataclass
class Upsampling2D(Layer):
    """Nearest-neighbor upsampling (reference Upsampling2D)."""

    size: Tuple[int, int] = (2, 2)

    def __post_init__(self):
        self.size = _pair(self.size)

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.convolutional(in_type.height * self.size[0], in_type.width * self.size[1], in_type.channels)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1), self.size[1], axis=2)
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class Upsampling1D(Layer):
    size: int = 2

    def has_params(self) -> bool:
        return False

    def output_type(self, in_type: InputType) -> InputType:
        t = in_type.timesteps
        return InputType.recurrent(in_type.size, None if t is None else t * self.size)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(jnp.repeat(x, self.size, axis=1), state, mask)
