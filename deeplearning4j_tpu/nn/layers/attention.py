"""Attention layers — designed fresh, TPU-first (no reference analog).

DL4J 0.9.2 has no attention layer anywhere (SURVEY.md §5 "Long-context":
its sequence story is TBPTT + masking).  These layers provide the modern
long-context path mandated by SURVEY §7-M5, built on
``ops.attention``: XLA einsum attention for masked/odd shapes, the pallas
flash kernel (``flash_mha``) for tile-aligned shapes, and ring attention
over the ``seq`` mesh axis (parallel/ring.py) for sequence parallelism.

Layout: layer I/O follows the framework's RNN convention [batch, time,
features]; heads are split/merged internally to [B, H, T, D].
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ...ops.attention import flash_mha, merge_heads, mha, split_heads
from ...ops.initializers import init_weight
from ..conf.inputs import InputType
from .base import Array, ForwardOut, Layer, register_layer


@register_layer
@dataclasses.dataclass
class SelfAttention(Layer):
    """Multi-head self-attention over a sequence.

    Projects input [B,T,nIn] to per-head q/k/v, attends (optionally
    causally), and projects back to n_out.  ``kernel="flash"`` uses the
    pallas blockwise kernel when shapes tile (falls back to XLA otherwise
    or when a sequence mask is present); ``kernel="xla"`` always uses the
    einsum path.  With ``project_out=False`` and n_out == n_heads *
    head_dim, the output projection is skipped (pure attention block).
    """

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 4
    head_dim: int = 0          # 0 → n_out // n_heads
    causal: bool = False
    kernel: str = "flash"      # "flash" | "xla"
    project_out: bool = True

    wants = "rnn"

    def _head_dim(self) -> int:
        if self.head_dim:
            return self.head_dim
        if self.n_out % self.n_heads:
            raise ValueError(
                f"n_out {self.n_out} not divisible by n_heads {self.n_heads}; "
                "set head_dim explicitly")
        return self.n_out // self.n_heads

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, in_type.timesteps)

    def infer_nin(self, in_type: InputType) -> None:
        if not self.n_in:
            self.n_in = in_type.size
        if not self.n_out:
            self.n_out = in_type.size

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        hd = self._head_dim()
        proj = self.n_heads * hd
        kq, kk, kv, ko = jax.random.split(rng, 4)
        params = {
            "Wq": init_weight(kq, (self.n_in, proj), self._winit(), self.n_in, proj, dtype),
            "Wk": init_weight(kk, (self.n_in, proj), self._winit(), self.n_in, proj, dtype),
            "Wv": init_weight(kv, (self.n_in, proj), self._winit(), self.n_in, proj, dtype),
        }
        if self.project_out:
            params["Wo"] = init_weight(ko, (proj, self.n_out), self._winit(),
                                       proj, self.n_out, dtype)
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        elif proj != self.n_out:
            raise ValueError(
                f"project_out=False requires n_heads*head_dim == n_out "
                f"({proj} != {self.n_out})")
        return params

    def _split_heads(self, x: Array) -> Array:
        return split_heads(x, self.n_heads)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        q = self._split_heads(x @ params["Wq"])     # [B,H,T,D]
        k = self._split_heads(x @ params["Wk"])
        v = self._split_heads(x @ params["Wv"])
        if self.kernel == "flash":
            # [B,T] sequence masks ride the kernel's key-padding input —
            # DL4J-style variable-length batches stay on the fused path
            out = flash_mha(q, k, v, self.causal, kmask=mask)
        elif mask is not None:
            out = mha(q, k, v, causal=self.causal,
                      mask=mask[:, None, None, :])
        else:
            out = mha(q, k, v, causal=self.causal)
        merged = merge_heads(out)
        if self.project_out:
            merged = merged @ params["Wo"] + params["b"]
        y = self._act(merged)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return ForwardOut(y, state, mask)


@register_layer
@dataclasses.dataclass
class LearnedSelfAttention(SelfAttention):
    """Self-attention with ``n_queries`` LEARNED query vectors: output is a
    fixed-length [B, n_queries, n_out] summary of a variable-length
    sequence (the attention analog of global pooling)."""

    n_queries: int = 1

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, self.n_queries)

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        if self.causal:
            # learned queries have no temporal position — causal masking
            # is undefined for them; reject rather than silently ignore
            raise ValueError("LearnedSelfAttention does not support causal=True")
        rq, rest = jax.random.split(rng)
        params = super().init_params(rest, in_type, dtype)
        del params["Wq"]  # queries are free parameters, not a projection
        hd = self._head_dim()
        params["Q"] = init_weight(rq, (self.n_queries, self.n_heads * hd),
                                  self._winit(), self.n_in, self.n_heads * hd,
                                  dtype)
        return params

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        x = self._maybe_dropout(x, train, rng)
        b = x.shape[0]
        q = jnp.broadcast_to(params["Q"], (b,) + params["Q"].shape)
        q = self._split_heads(q)                     # [B,H,nQ,D]
        k = self._split_heads(x @ params["Wk"])
        v = self._split_heads(x @ params["Wv"])
        if mask is not None:
            out = mha(q, k, v, mask=mask[:, None, None, :])
        elif self.kernel == "flash":
            out = flash_mha(q, k, v, False)
        else:
            out = mha(q, k, v)
        merged = merge_heads(out)
        if self.project_out:
            merged = merged @ params["Wo"] + params["b"]
        return ForwardOut(self._act(merged), state, None)
