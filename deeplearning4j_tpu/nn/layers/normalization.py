"""Normalization layers.

BatchNormalization — reference nn/layers/normalization/BatchNormalization.java
(+ CudnnBatchNormalizationHelper): per-feature affine with running mean/var
kept as non-trainable state ("global mean/var" updated with decay each fit
step).  LocalResponseNormalization — reference
nn/layers/normalization/LocalResponseNormalization.java (AlexNet-era LRN).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from ..conf.inputs import InputType
from .base import ForwardOut, Layer, register_layer

Array = jax.Array


@register_layer
@dataclasses.dataclass
class BatchNormalization(Layer):
    """BN over the feature axis: CNN [mb,h,w,c] normalizes per-channel,
    FF [mb,f] per-feature (matching reference axis semantics on its NCHW).

    ``decay`` matches the reference's running-average decay (default 0.9);
    state keys "mean"/"var" correspond to GLOBAL_MEAN/GLOBAL_VAR params in
    BatchNormalizationParamInitializer (kept as state here since they are
    not gradient-trained).
    """

    n_features: int = 0
    eps: float = 1e-5
    decay: float = 0.9
    lock_gamma_beta: bool = False

    def infer_nin(self, in_type: InputType) -> None:
        if self.n_features == 0:
            self.n_features = in_type.channels if in_type.kind == "cnn" else in_type.size

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.ones((self.n_features,), dtype),
            "beta": jnp.zeros((self.n_features,), dtype),
        }

    def init_state(self, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {
            "mean": jnp.zeros((self.n_features,), dtype),
            "var": jnp.ones((self.n_features,), dtype),
        }

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        axes = tuple(range(x.ndim - 1))  # all but the trailing feature/channel axis
        if train:
            acc = jnp.promote_types(x.dtype, jnp.float32)
            if jnp.dtype(x.dtype).itemsize < 4:
                # bf16/f16 compute: E[x²]−E[x]² with f32-ACCUMULATING
                # reductions.  jnp.var would upcast the whole activation
                # and materialize (x−mean)² in f32 (and again in the
                # transpose), doubling HBM traffic — the dominant cost of
                # ResNet BN on TPU (docs/resnet_profile.md; +6% step).
                # Caveat: this form loses the spread when |mean|/std ≳ 1e²
                # — but x itself carries an 8-bit mantissa here, so such
                # channels are already unresolvable in bf16; full-precision
                # robustness is what the f32 branch below is for.
                mean = jnp.mean(x, axis=axes, dtype=acc)
                mean2 = jnp.mean(lax.square(x), axis=axes, dtype=acc)
                var = jnp.maximum(mean2 - lax.square(mean), 0.0)
            else:
                # f32/f64 compute: two-pass jnp.var — numerically robust
                # (no cancellation for large-mean channels) and no dtype
                # upcast exists to cause extra traffic
                mean = jnp.mean(x, axis=axes)
                var = jnp.var(x, axis=axes)
            d = jnp.asarray(self.decay, state["mean"].dtype)
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean.astype(state["mean"].dtype),
                "var": d * state["var"] + (1 - d) * var.astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"].astype(x.dtype), state["var"].astype(x.dtype)
            new_state = state
        inv = lax.rsqrt(var.astype(x.dtype) + jnp.asarray(self.eps, x.dtype))
        y = (x - mean.astype(x.dtype)) * inv
        if not self.lock_gamma_beta:
            y = y * params["gamma"].astype(x.dtype) + params["beta"].astype(x.dtype)
        return ForwardOut(self._act(y), new_state, mask)

    def has_params(self) -> bool:
        return not self.lock_gamma_beta


@register_layer
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Cross-channel LRN: y = x / (k + α/n · Σ x²)^β over a sliding channel
    window (reference LocalResponseNormalization.java, defaults k=2, n=5,
    α=1e-4, β=0.75 per AlexNet)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self) -> bool:
        return False

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        # channels last: sliding-window sum of squares over channel axis
        sq = x * x
        half = self.n // 2
        padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
        window = lax.reduce_window(
            padded, 0.0, lax.add,
            (1, 1, 1, self.n), (1, 1, 1, 1), "VALID")
        denom = (self.k + (self.alpha / self.n) * window) ** self.beta
        return ForwardOut(x / denom, state, mask)


def layer_norm(x: Array, gamma: Array, beta: Array, eps: float = 1e-5) -> Array:
    """Normalize the last axis; shared by LayerNorm and TransformerBlock."""
    acc = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(acc)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y * gamma.astype(acc) + beta.astype(acc)).astype(x.dtype)


@register_layer
@dataclasses.dataclass
class LayerNorm(Layer):
    """Per-token normalization over the feature axis (no reference analog —
    DL4J 0.9.2 predates LayerNorm; required by the transformer path)."""

    n_features: int = 0
    eps: float = 1e-5

    def infer_nin(self, in_type: InputType) -> None:
        if not self.n_features:
            self.n_features = in_type.size

    def init_params(self, rng, in_type, dtype=jnp.float32) -> Dict[str, Array]:
        return {"gamma": jnp.ones((self.n_features,), dtype),
                "beta": jnp.zeros((self.n_features,), dtype)}

    def forward(self, params, state, x, *, train=False, rng=None, mask=None) -> ForwardOut:
        return ForwardOut(
            self._act(layer_norm(x, params["gamma"], params["beta"], self.eps)),
            state, mask)
