from .base import Layer, ForwardOut, register_layer, layer_from_dict, layer_to_dict
from .feedforward import (
    Dense,
    OutputLayer,
    LossLayer,
    ActivationLayer,
    DropoutLayer,
    Embedding,
    EmbeddingSequence,
    ElementWiseMultiplication,
    AutoEncoder,
    RBM,
)
from .convolution import (
    Convolution1D,
    Convolution2D,
    Deconvolution2D,
    SeparableConvolution2D,
    ZeroPadding1D,
    ZeroPadding2D,
    Cropping2D,
    Upsampling1D,
    Upsampling2D,
)
from .pooling import Subsampling1D, Subsampling2D, GlobalPooling
from .normalization import BatchNormalization, LocalResponseNormalization, LayerNorm
from .recurrent import LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn, RnnOutputLayer, Bidirectional, LastTimeStep
from .attention import SelfAttention, LearnedSelfAttention
from .variational import VariationalAutoencoder
from .objdetect import Yolo2OutputLayer
from .special import FrozenLayer, CenterLossOutputLayer
