"""Memory estimation reports.

Parity target: reference nn/conf/memory/ (MemoryReport,
LayerMemoryReport, NetworkMemoryReport — getMemoryReport(InputType) on
every layer config).  The TPU inversion is simpler and more honest:
params, optimizer state, and activations are the dominant HBM terms under
XLA (no workspaces / iterator scratch as in the reference), and gradient
memory ≈ param memory for the fused train step.  Estimates assume
rematerialization is OFF; XLA fusion typically does better.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class LayerMemoryReport:
    """Per-layer estimate (reference LayerMemoryReport.Builder fields)."""

    name: str
    layer_type: str
    param_count: int
    param_bytes: int
    updater_state_bytes: int
    activation_elements_per_example: int
    activation_bytes_per_example: int


@dataclasses.dataclass
class NetworkMemoryReport:
    """Whole-model estimate (reference NetworkMemoryReport)."""

    layers: List[LayerMemoryReport]
    minibatch: int
    param_dtype: str
    compute_dtype: str

    @property
    def total_param_bytes(self) -> int:
        return sum(l.param_bytes for l in self.layers)

    @property
    def total_updater_bytes(self) -> int:
        return sum(l.updater_state_bytes for l in self.layers)

    @property
    def total_activation_bytes(self) -> int:
        return self.minibatch * sum(l.activation_bytes_per_example for l in self.layers)

    def total_bytes(self, training: bool = True) -> int:
        """Fixed + per-minibatch total; training adds one gradient copy of
        the params (the fused step's peak)."""
        fixed = self.total_param_bytes + (self.total_updater_bytes if training else 0)
        grad = self.total_param_bytes if training else 0
        return fixed + grad + self.total_activation_bytes

    def __str__(self) -> str:
        lines = [
            f"NetworkMemoryReport (mb={self.minibatch}, params={self.param_dtype}, "
            f"compute={self.compute_dtype})",
            f"{'layer':<24}{'type':<22}{'params':>12}{'param MB':>10}{'act KB/ex':>12}",
        ]
        for l in self.layers:
            lines.append(
                f"{l.name or '?':<24}{l.layer_type:<22}{l.param_count:>12,}"
                f"{l.param_bytes / 2**20:>10.2f}"
                f"{l.activation_bytes_per_example / 2**10:>12.1f}")
        lines.append(
            f"TOTAL train ≈ {self.total_bytes(True) / 2**20:.1f} MB "
            f"(params {self.total_param_bytes / 2**20:.1f} + updater "
            f"{self.total_updater_bytes / 2**20:.1f} + grads "
            f"{self.total_param_bytes / 2**20:.1f} + activations "
            f"{self.total_activation_bytes / 2**20:.1f})")
        return "\n".join(lines)


def _updater_state_bytes(updater, pcount: int, param_elem_bytes: int) -> int:
    """Optimizer-state footprint: copies × per-element size.  Narrow
    moment storage (Adam moment_dtype="bfloat16") halves the per-element
    size — the report must price what is actually allocated."""
    md = getattr(updater, "moment_dtype", None)
    if md is not None:
        import jax.numpy as jnp
        param_elem_bytes = jnp.dtype(md).itemsize
    return pcount * param_elem_bytes * _updater_copies(updater)


def _updater_copies(updater) -> int:
    """Optimizer-state copies of the params (Adam/AdaMax/Nadam → 2,
    AMSGrad → 3 — m, v, AND the running max-v — momentum-family/AdaGrad/
    RmsProp → 1, Sgd/NoOp → 0)."""
    name = type(updater).__name__.lower()
    if name in ("adam", "adamax", "nadam"):
        return 2
    if name in ("amsgrad",):
        return 3
    if name in ("sgd", "noop"):
        return 0
    return 1


def _safe_elems(out_t) -> int:
    if out_t is None:
        return 0
    try:
        return out_t.flat_size()
    except ValueError:   # variable-length recurrent
        return out_t.size


def memory_report(net, minibatch: int = 32) -> NetworkMemoryReport:
    """Estimate memory for an initialized MultiLayerNetwork or
    ComputationGraph (reference MultiLayerConfiguration /
    ComputationGraphConfiguration .getMemoryReport)."""
    conf = net.conf
    pbytes = np.dtype(conf.param_dtype).itemsize
    abytes = np.dtype(conf.compute_dtype).itemsize
    reports: List[LayerMemoryReport] = []

    if hasattr(conf, "vertices"):  # ComputationGraph
        import jax

        for spec in conf.vertices:
            p = net.params.get(spec.name, {})
            pcount = sum(int(np.prod(a.shape))
                         for a in jax.tree_util.tree_leaves(p))
            layer = getattr(spec.vertex, "layer", None)
            upd = (layer.updater if layer is not None and layer.updater is not None
                   else conf.updater)
            act_elems = _safe_elems(net.vertex_out_types.get(spec.name))
            reports.append(LayerMemoryReport(
                name=spec.name,
                layer_type=(type(layer).__name__ if layer is not None
                            else type(spec.vertex).__name__),
                param_count=pcount,
                param_bytes=pcount * pbytes,
                updater_state_bytes=_updater_state_bytes(upd, pcount, pbytes),
                activation_elements_per_example=act_elems,
                activation_bytes_per_example=act_elems * abytes,
            ))
        return NetworkMemoryReport(reports, minibatch, conf.param_dtype,
                                   conf.compute_dtype)

    import jax

    for i, layer in enumerate(conf.layers):
        pcount = sum(int(np.prod(a.shape))
                     for a in jax.tree_util.tree_leaves(
                         net.params[i] if i < len(net.params) else {}))
        out_t = layer.output_type(net.input_types[i]) if net.input_types else None
        act_elems = _safe_elems(out_t)
        upd = layer.updater if layer.updater is not None else conf.updater
        reports.append(LayerMemoryReport(
            name=layer.name or f"layer_{i}",
            layer_type=type(layer).__name__,
            param_count=pcount,
            param_bytes=pcount * pbytes,
            updater_state_bytes=_updater_state_bytes(upd, pcount, pbytes),
            activation_elements_per_example=act_elems,
            activation_bytes_per_example=act_elems * abytes,
        ))
    return NetworkMemoryReport(reports, minibatch, conf.param_dtype,
                               conf.compute_dtype)
