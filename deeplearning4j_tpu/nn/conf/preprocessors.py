"""Input preprocessors — shape adapters between layer families.

Reference: nn/conf/preprocessor/ (CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor, etc.).  In the
reference these also implement backprop() to reverse the reshape; here the
reshapes are traced ops, so autodiff reverses them for free.

Native layouts: CNN = NHWC [mb,h,w,c]; RNN = [mb,t,f].
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..layers.base import register_config
from .inputs import InputType

Array = jax.Array


@dataclasses.dataclass
class Preprocessor:
    def apply(self, x: Array) -> Array:
        raise NotImplementedError

    def output_type(self, in_type: InputType) -> InputType:
        raise NotImplementedError


@register_config
@dataclasses.dataclass
class CnnToFeedForward(Preprocessor):
    """[mb,h,w,c] → [mb, h*w*c] (reference CnnToFeedForwardPreProcessor)."""

    def apply(self, x: Array) -> Array:
        return x.reshape((x.shape[0], -1))

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(in_type.flat_size())


@register_config
@dataclasses.dataclass
class FeedForwardToCnn(Preprocessor):
    """[mb, h*w*c] → [mb,h,w,c] (reference FeedForwardToCnnPreProcessor)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def apply(self, x: Array) -> Array:
        return x.reshape((x.shape[0], self.height, self.width, self.channels))

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@register_config
@dataclasses.dataclass
class RnnToFeedForward(Preprocessor):
    """[mb,t,f] → [mb*t, f] time-flattening (reference RnnToFeedForwardPreProcessor).

    NOTE: our Dense layers broadcast over [mb,t,f] directly, so this is only
    needed for explicit parity paths."""

    def apply(self, x: Array) -> Array:
        return x.reshape((-1, x.shape[-1]))

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(in_type.size)


@register_config
@dataclasses.dataclass
class FeedForwardToRnn(Preprocessor):
    """[mb*t, f] → [mb,t,f]."""

    timesteps: int = 0

    def apply(self, x: Array) -> Array:
        return x.reshape((-1, self.timesteps, x.shape[-1]))

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(in_type.size, self.timesteps)


@register_config
@dataclasses.dataclass
class CnnToRnn(Preprocessor):
    """[mb,h,w,c] → [mb, t=h*w? no: treat h as time? ] — the reference maps
    [mb,c,h,w] → [mb, c*h*w / t ...]; canonical use is video/audio frames.
    We adopt: time = height, features = width*channels."""

    def apply(self, x: Array) -> Array:
        mb, h, w, c = x.shape
        return x.reshape((mb, h, w * c))

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.recurrent(in_type.width * in_type.channels, in_type.height)


@register_config
@dataclasses.dataclass
class RnnToCnn(Preprocessor):
    """[mb,t,f] → [mb, t, f/c, c]: inverse of CnnToRnn."""

    channels: int = 1

    def apply(self, x: Array) -> Array:
        mb, t, f = x.shape
        return x.reshape((mb, t, f // self.channels, self.channels))

    def output_type(self, in_type: InputType) -> InputType:
        t = in_type.timesteps or 0
        return InputType.convolutional(t, in_type.size // self.channels, self.channels)


@register_config
@dataclasses.dataclass
class UnitVariance(Preprocessor):
    """Per-example unit variance (reference UnitVarianceProcessor)."""

    def apply(self, x: Array) -> Array:
        std = jnp.std(x.reshape((x.shape[0], -1)), axis=1)
        std = std.reshape((-1,) + (1,) * (x.ndim - 1))
        return x / jnp.maximum(std, 1e-8)

    def output_type(self, in_type: InputType) -> InputType:
        return in_type


@register_config
@dataclasses.dataclass
class ZeroMean(Preprocessor):
    """Per-example zero mean (reference ZeroMeanPrePreProcessor)."""

    unit_variance: bool = False

    def apply(self, x: Array) -> Array:
        flat = x.reshape((x.shape[0], -1))
        mean = jnp.mean(flat, axis=1).reshape((-1,) + (1,) * (x.ndim - 1))
        y = x - mean
        if self.unit_variance:
            std = jnp.std(flat, axis=1).reshape((-1,) + (1,) * (x.ndim - 1))
            y = y / jnp.maximum(std, 1e-8)
        return y

    def output_type(self, in_type: InputType) -> InputType:
        return in_type


@register_config
@dataclasses.dataclass
class Composable(Preprocessor):
    """Chain of preprocessors (reference ComposableInputPreProcessor)."""

    steps: list = dataclasses.field(default_factory=list)

    def apply(self, x: Array) -> Array:
        for s in self.steps:
            x = s.apply(x)
        return x

    def output_type(self, in_type: InputType) -> InputType:
        for s in self.steps:
            in_type = s.output_type(in_type)
        return in_type


@register_config
@dataclasses.dataclass
class BinomialSampling(Preprocessor):
    """Bernoulli-sample activations in [0,1] (reference
    BinomialSamplingPreProcessor — DBN-style stochastic binarization).

    During training the container passes its per-step rng (``wants_rng``),
    so every step draws FRESH noise; outside a training step (inference,
    standalone apply) the fixed ``seed`` gives a deterministic sample."""

    seed: int = 12345
    wants_rng = True  # ClassVar: container threads its per-step key in

    def apply(self, x: Array, rng: Optional[Array] = None) -> Array:
        key = rng if rng is not None else jax.random.PRNGKey(self.seed)
        return jax.random.bernoulli(key, jnp.clip(x, 0.0, 1.0)).astype(x.dtype)

    def output_type(self, in_type: InputType) -> InputType:
        return in_type
