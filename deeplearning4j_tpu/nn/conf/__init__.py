from .inputs import InputType
