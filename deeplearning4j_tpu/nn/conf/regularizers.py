"""Dropout variants, weight noise, and parameter constraints.

Parity targets in the reference:
  nn/conf/dropout/     Dropout, AlphaDropout, GaussianDropout, GaussianNoise
  nn/conf/weightnoise/ DropConnect, WeightNoise
  nn/conf/constraint/  MaxNormConstraint, MinMaxNormConstraint,
                       UnitNormConstraint, NonNegativeConstraint

Design: a layer's ``dropout`` field accepts a float (classic inverted
dropout, the common case) or one of the IDropout configs below; the
``weight_noise`` field holds an IWeightNoise applied to weight params each
training forward; ``constraints`` lists IConstraints applied after each
parameter update (reference BaseConstraint.applyConstraint on param tables
whose names match).  All are registered dataclasses, so layer JSON
round-trips carry them.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..layers.base import register_config

Array = jax.Array


# ---------------------------------------------------------------------------
# IDropout family (input dropout)
# ---------------------------------------------------------------------------


@register_config
@dataclasses.dataclass
class Dropout:
    """Classic inverted dropout (reference nn/conf/dropout/Dropout.java).
    ``p`` is the DROP probability."""

    p: float = 0.5

    def apply(self, rng: Array, x: Array, train: bool) -> Array:
        if not train or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@register_config
@dataclasses.dataclass
class SpatialDropout:
    """Channel-wise dropout (reference SpatialDropout.java, Tompson et al.
    2015): entire feature maps are dropped together.  Mask shape keeps the
    batch and trailing channel axis and broadcasts over the spatial/time
    axes between them — [mb,h,w,c] → mask [mb,1,1,c], [mb,t,f] →
    [mb,1,f] — so adjacent-pixel correlations can't leak through
    element-wise dropout.  ``p`` is the DROP probability."""

    p: float = 0.5

    def apply(self, rng: Array, x: Array, train: bool) -> Array:
        if not train or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


@register_config
@dataclasses.dataclass
class AlphaDropout:
    """SELU-compatible dropout (reference AlphaDropout.java, Klambauer et
    al. 2017): dropped units take α' = −λα, then an affine correction
    (a, b) restores zero mean / unit variance."""

    p: float = 0.5

    _LAMBDA = 1.0507009873554805
    _ALPHA = 1.6732632423543772

    def apply(self, rng: Array, x: Array, train: bool) -> Array:
        if not train or self.p <= 0.0:
            return x
        keep = 1.0 - self.p
        alpha_p = -self._LAMBDA * self._ALPHA
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@register_config
@dataclasses.dataclass
class GaussianDropout:
    """Multiplicative N(1, rate/(1−rate)) noise (reference
    GaussianDropout.java, Srivastava et al. 2014 §10)."""

    rate: float = 0.5

    def apply(self, rng: Array, x: Array, train: bool) -> Array:
        if not train or self.rate <= 0.0:
            return x
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(rng, x.shape, dtype=jnp.float32)
        return (x * noise.astype(x.dtype))


@register_config
@dataclasses.dataclass
class GaussianNoise:
    """Additive N(0, stddev²) noise (reference GaussianNoise.java)."""

    stddev: float = 0.1

    def apply(self, rng: Array, x: Array, train: bool) -> Array:
        if not train or self.stddev <= 0.0:
            return x
        return x + self.stddev * jax.random.normal(rng, x.shape).astype(x.dtype)


def apply_dropout(dropout, rng: Array, x: Array, train: bool) -> Array:
    """Dispatch a layer's ``dropout`` field: float → classic, config → its
    apply()."""
    if dropout is None:
        return x
    if isinstance(dropout, (int, float)):
        return Dropout(float(dropout)).apply(rng, x, train) if dropout > 0 else x
    return dropout.apply(rng, x, train)


# ---------------------------------------------------------------------------
# IWeightNoise family (applied to weight params per training forward)
# ---------------------------------------------------------------------------

#: param keys the noise/constraints treat as "weights" (biases and BN
#: statistics excluded, reference BaseConstraint.DEFAULT_PARAMS)
WEIGHT_KEYS_EXCLUDED = ("b", "vb", "hb", "beta", "gamma", "mean", "var")


def _is_weight(key: str) -> bool:
    return key not in WEIGHT_KEYS_EXCLUDED


@register_config
@dataclasses.dataclass
class DropConnect:
    """Per-weight Bernoulli masking (reference weightnoise/DropConnect.java,
    Wan et al. 2013).  ``p`` is the RETAIN probability, matching the
    reference's 'probability of keeping a weight'."""

    p: float = 0.5

    def apply(self, rng: Array, params: Dict[str, Array], train: bool) -> Dict[str, Array]:
        if not train:
            return params
        out = dict(params)
        for i, (k, v) in enumerate(sorted(params.items())):
            if _is_weight(k):
                mask = jax.random.bernoulli(jax.random.fold_in(rng, i), self.p, v.shape)
                out[k] = jnp.where(mask, v, 0.0).astype(v.dtype)
        return out


@register_config
@dataclasses.dataclass
class WeightNoise:
    """Additive or multiplicative gaussian weight noise (reference
    weightnoise/WeightNoise.java with a normal distribution)."""

    stddev: float = 0.01
    additive: bool = True
    mean: float = 0.0

    def apply(self, rng: Array, params: Dict[str, Array], train: bool) -> Dict[str, Array]:
        if not train:
            return params
        out = dict(params)
        for i, (k, v) in enumerate(sorted(params.items())):
            if _is_weight(k):
                noise = (self.mean + self.stddev * jax.random.normal(
                    jax.random.fold_in(rng, i), v.shape)).astype(v.dtype)
                out[k] = v + noise if self.additive else v * noise
        return out


def apply_weight_noise(noise, rng: Array, params: Dict[str, Array],
                       train: bool) -> Dict[str, Array]:
    if noise is None or not params:
        return params
    return noise.apply(rng, params, train)


def maybe_weight_noise(layer, params: Dict[str, Array], train: bool,
                       rng: Optional[Array]) -> Dict[str, Array]:
    """Container-side guard: apply a layer's weight_noise to its params
    before forward() during training (shared by MultiLayerNetwork and
    ComputationGraph so their RNG derivation stays identical)."""
    if not train or layer.weight_noise is None or rng is None or not params:
        return params
    return layer.weight_noise.apply(jax.random.fold_in(rng, 7), params, train)


# ---------------------------------------------------------------------------
# IConstraint family (applied after each parameter update)
# ---------------------------------------------------------------------------


def _norm_axes(v: Array) -> Tuple[int, ...]:
    """Norm over all axes but the last (output) axis — matches the
    reference's per-output-unit norms (BaseConstraint dimensions)."""
    return tuple(range(max(v.ndim - 1, 1)))


@register_config
@dataclasses.dataclass
class MaxNormConstraint:
    """Clip per-unit L2 norm to max_norm (reference MaxNormConstraint)."""

    max_norm: float = 2.0

    def apply(self, params: Dict[str, Array]) -> Dict[str, Array]:
        out = dict(params)
        for k, v in params.items():
            if _is_weight(k) and v.ndim >= 2:
                n = jnp.sqrt(jnp.sum(v * v, axis=_norm_axes(v), keepdims=True))
                out[k] = jnp.where(n > self.max_norm, v * (self.max_norm / jnp.maximum(n, 1e-12)), v)
        return out


@register_config
@dataclasses.dataclass
class MinMaxNormConstraint:
    """Scale per-unit norms into [min_norm, max_norm] with rate blending
    (reference MinMaxNormConstraint)."""

    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def apply(self, params: Dict[str, Array]) -> Dict[str, Array]:
        out = dict(params)
        for k, v in params.items():
            if _is_weight(k) and v.ndim >= 2:
                n = jnp.sqrt(jnp.sum(v * v, axis=_norm_axes(v), keepdims=True))
                clipped = jnp.clip(n, self.min_norm, self.max_norm)
                scale = 1.0 - self.rate + self.rate * clipped / jnp.maximum(n, 1e-12)
                out[k] = v * scale
        return out


@register_config
@dataclasses.dataclass
class UnitNormConstraint:
    """Force per-unit norm to 1 (reference UnitNormConstraint)."""

    def apply(self, params: Dict[str, Array]) -> Dict[str, Array]:
        out = dict(params)
        for k, v in params.items():
            if _is_weight(k) and v.ndim >= 2:
                n = jnp.sqrt(jnp.sum(v * v, axis=_norm_axes(v), keepdims=True))
                out[k] = v / jnp.maximum(n, 1e-12)
        return out


@register_config
@dataclasses.dataclass
class NonNegativeConstraint:
    """Clamp params at ≥ 0 (reference NonNegativeConstraint; applies to all
    params like the reference's default)."""

    def apply(self, params: Dict[str, Array]) -> Dict[str, Array]:
        return {k: jnp.maximum(v, 0.0) for k, v in params.items()}


def apply_constraints(constraints, params: Dict[str, Array]) -> Dict[str, Array]:
    if not constraints or not params:
        return params
    for c in constraints:
        params = c.apply(params)
    return params
