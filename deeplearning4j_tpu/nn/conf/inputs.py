"""InputType — shape inference between layers.

Parity with reference nn/conf/inputs/InputType.java:43-201 (feedForward,
recurrent, convolutional, convolutionalFlat).  Differences by design:

  - Convolutional activations are **NHWC** ``[mb, h, w, c]`` (TPU/XLA native
    layout), not the reference's NCHW.
  - Recurrent activations are **[mb, time, size]** (scan-friendly), not the
    reference's ``[mb, size, time]``.

These layouts keep XLA convolutions and ``lax.scan`` in their fast paths;
converters at the data boundary accept DL4J-layout arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    """Tagged shape descriptor: kind ∈ {ff, rnn, cnn, cnn_flat}."""

    kind: str
    size: int = 0                      # ff/rnn feature size
    timesteps: Optional[int] = None    # rnn (None = variable)
    height: int = 0
    width: int = 0
    channels: int = 0

    # -- constructors (parity with InputType.feedForward() etc.) --
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn_flat", height=int(height), width=int(width), channels=int(channels))

    # -- helpers --
    def flat_size(self) -> int:
        """Total per-example element count (InputType.arrayElementsPerExample)."""
        if self.kind == "ff":
            return self.size
        if self.kind == "rnn":
            if self.timesteps is None:
                raise ValueError("variable-length recurrent input has no flat size")
            return self.size * self.timesteps
        return self.height * self.width * self.channels

    def batch_shape(self, mb: int) -> Tuple[int, ...]:
        """Example array shape for minibatch size ``mb`` (native layouts)."""
        if self.kind == "ff" or self.kind == "cnn_flat":
            return (mb, self.flat_size()) if self.kind == "ff" else (
                mb, self.height * self.width * self.channels)
        if self.kind == "rnn":
            if self.timesteps is None:
                raise ValueError("variable timesteps: shape unknown")
            return (mb, self.timesteps, self.size)
        return (mb, self.height, self.width, self.channels)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)

    def __repr__(self) -> str:  # compact, DL4J-ish
        if self.kind == "ff":
            return f"InputType(ff,{self.size})"
        if self.kind == "rnn":
            return f"InputType(rnn,{self.size},t={self.timesteps})"
        tag = "cnn" if self.kind == "cnn" else "cnn_flat"
        return f"InputType({tag},h={self.height},w={self.width},c={self.channels})"
