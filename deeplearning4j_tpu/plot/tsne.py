"""t-SNE (van der Maaten & Hinton 2008) — exact, device-resident.

Parity target: reference plot/BarnesHutTsne.java (868 LoC: perplexity
binary search, early exaggeration, momentum schedule, gain adaptation)
+ plot/Tsne.java (the exact O(N²) variant).

TPU inversion: Barnes-Hut's quadtree exists to approximate the O(N²)
repulsive term on CPUs.  On TPU the full [N,N] affinity matrix IS the fast
path — one matmul per iteration — so the exact algorithm is used, matching
the reference's *exact* Tsne.java math with BarnesHutTsne.java's training
schedule (up to ~50K points before the [N,N] buffer outgrows HBM, far past
the reference's practical CPU range).  Gradient iterations run in a single
jit'd update with momentum + per-dimension gains.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _hbeta(d2_row: np.ndarray, beta: float):
    """Perplexity helper: P given precision beta (Tsne.java hBeta)."""
    p = np.exp(-d2_row * beta)
    s = p.sum()
    if s <= 0:
        return np.inf, np.zeros_like(p)
    h = np.log(s) + beta * (d2_row * p).sum() / s
    return h, p / s


def _binary_search_p(d2: np.ndarray, perplexity: float, tol: float = 1e-5,
                     max_tries: int = 50) -> np.ndarray:
    """Row-wise precision search to hit the target perplexity
    (BarnesHutTsne.java computeGaussianPerplexity)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n), np.float64)
    for i in range(n):
        row = np.delete(d2[i], i)
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        h, p = _hbeta(row, beta)
        for _ in range(max_tries):
            if abs(h - target) < tol:
                break
            if h > target:
                beta_min = beta
                beta = beta * 2.0 if beta_max == np.inf else (beta + beta_max) / 2.0
            else:
                beta_max = beta
                beta = beta / 2.0 if beta_min == -np.inf else (beta + beta_min) / 2.0
            h, p = _hbeta(row, beta)
        P[i, np.arange(n) != i] = p
    return P


@partial(jax.jit, donate_argnums=(1, 2, 3))
def _tsne_step(P: Array, Y: Array, velocity: Array, gains: Array,
               momentum: Array, lr: float):
    """One gradient iteration (Tsne.java gradient + BarnesHutTsne schedule):
    Q from Student-t kernel, gradient 4·Σ(p−q)q_num(yᵢ−yⱼ), gain-adapted
    momentum update, re-centering."""
    y2 = jnp.sum(Y * Y, axis=1)
    num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2.0 * (Y @ Y.T))  # [N,N]
    num = num * (1.0 - jnp.eye(Y.shape[0], dtype=Y.dtype))
    Q = num / jnp.maximum(jnp.sum(num), 1e-12)
    PQ = (P - jnp.maximum(Q, 1e-12)) * num                           # [N,N]
    # grad_i = 4 Σ_j PQ_ij (y_i − y_j)  → diag trick keeps it matmul-shaped
    grad = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ Y)
    # gains: grow when grad and velocity disagree (Tsne.java gains logic)
    same_sign = (grad > 0) == (velocity > 0)
    gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01, None)
    velocity = momentum * velocity - lr * gains * grad
    Y = Y + velocity
    Y = Y - jnp.mean(Y, axis=0, keepdims=True)
    kl = jnp.sum(jnp.where(P > 0, P * jnp.log(jnp.maximum(P, 1e-12)
                                              / jnp.maximum(Q, 1e-12)), 0.0))
    return Y, velocity, gains, kl


class Tsne:
    """Builder-parity surface (reference BarnesHutTsne.Builder):
    setMaxIter, perplexity, theta (ignored — exact), learningRate,
    useAdaGrad→gains, stopLyingIteration (early exaggeration end)."""

    def __init__(self,
                 n_components: int = 2,
                 perplexity: float = 30.0,
                 max_iter: int = 500,
                 learning_rate: float = 200.0,
                 early_exaggeration: float = 12.0,
                 stop_lying_iteration: int = 100,
                 initial_momentum: float = 0.5,
                 final_momentum: float = 0.8,
                 momentum_switch: int = 250,
                 seed: int = 12345):
        self.n_components = n_components
        self.perplexity = perplexity
        self.max_iter = max_iter
        self.lr = learning_rate
        self.early_exaggeration = early_exaggeration
        self.stop_lying_iteration = stop_lying_iteration
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.seed = seed
        self.kl_divergence_: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        if n < 4:
            raise ValueError("t-SNE needs at least 4 points")
        if self.perplexity >= (n - 1) / 3:
            raise ValueError(f"perplexity {self.perplexity} too large for N={n} "
                             "(need perplexity < (N-1)/3)")
        # symmetric affinities from the perplexity search
        d2 = np.sum(x * x, axis=1)[:, None] + np.sum(x * x, axis=1)[None, :] \
            - 2.0 * (x @ x.T)
        np.fill_diagonal(d2, 0.0)
        P = _binary_search_p(np.maximum(d2, 0.0), self.perplexity)
        P = (P + P.T) / (2.0 * n)
        P = np.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        Y = jnp.asarray(rng.normal(0.0, 1e-4, (n, self.n_components))
                        .astype(np.float32))
        vel = jnp.zeros_like(Y)
        gains = jnp.ones_like(Y)
        P_lying = jnp.asarray((P * self.early_exaggeration).astype(np.float32))
        P_true = jnp.asarray(P.astype(np.float32))
        kl = None
        for it in range(self.max_iter):
            Pj = P_lying if it < self.stop_lying_iteration else P_true
            mom = self.initial_momentum if it < self.momentum_switch \
                else self.final_momentum
            Y, vel, gains, kl = _tsne_step(Pj, Y, vel, gains,
                                           jnp.asarray(mom, jnp.float32), self.lr)
        self.kl_divergence_ = float(kl)
        return np.asarray(Y)
