"""t-SNE (van der Maaten & Hinton 2008) — exact, device-resident.

Parity target: reference plot/BarnesHutTsne.java (868 LoC: perplexity
binary search, early exaggeration, momentum schedule, gain adaptation)
+ plot/Tsne.java (the exact O(N²) variant).

TPU inversion: Barnes-Hut's quadtree exists to approximate the O(N²)
repulsive term on CPUs.  On TPU the full [N,N] affinity matrix IS the fast
path for small N — one matmul per iteration — so the exact algorithm is
used, matching the reference's *exact* Tsne.java math with
BarnesHutTsne.java's training schedule.

Large N (the BarnesHutTsne capability, round-4): the [N,N] buffer is never
materialized.  Input affinities go sparse over k-nearest neighbors (the
reference's VPTree KNN role, k = 3·perplexity, brute-force in [N,B] tiles
on the MXU) with a vectorized on-device perplexity bisection, and every
gradient iteration streams the EXACT all-pairs repulsive term in [N,B]
column blocks with an accumulated normalizer Z (flash-attention-style
online renormalization — no approximation, unlike Barnes-Hut's theta).
Peak memory is O(N·(B + k)), so N is HBM-unbounded; 500K points fit where
the dense path capped at ~50K.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _hbeta(d2_row: np.ndarray, beta: float):
    """Perplexity helper: P given precision beta (Tsne.java hBeta)."""
    p = np.exp(-d2_row * beta)
    s = p.sum()
    if s <= 0:
        return np.inf, np.zeros_like(p)
    h = np.log(s) + beta * (d2_row * p).sum() / s
    return h, p / s


def _binary_search_p(d2: np.ndarray, perplexity: float, tol: float = 1e-5,
                     max_tries: int = 50) -> np.ndarray:
    """Row-wise precision search to hit the target perplexity
    (BarnesHutTsne.java computeGaussianPerplexity)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    P = np.zeros((n, n), np.float64)
    for i in range(n):
        row = np.delete(d2[i], i)
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        h, p = _hbeta(row, beta)
        for _ in range(max_tries):
            if abs(h - target) < tol:
                break
            if h > target:
                beta_min = beta
                beta = beta * 2.0 if beta_max == np.inf else (beta + beta_max) / 2.0
            else:
                beta_max = beta
                beta = beta / 2.0 if beta_min == -np.inf else (beta + beta_min) / 2.0
            h, p = _hbeta(row, beta)
        P[i, np.arange(n) != i] = p
    return P


@partial(jax.jit, donate_argnums=(1, 2, 3))
def _tsne_step(P: Array, Y: Array, velocity: Array, gains: Array,
               momentum: Array, lr: float):
    """One gradient iteration (Tsne.java gradient + BarnesHutTsne schedule):
    Q from Student-t kernel, gradient 4·Σ(p−q)q_num(yᵢ−yⱼ), gain-adapted
    momentum update, re-centering."""
    y2 = jnp.sum(Y * Y, axis=1)
    num = 1.0 / (1.0 + y2[:, None] + y2[None, :] - 2.0 * (Y @ Y.T))  # [N,N]
    num = num * (1.0 - jnp.eye(Y.shape[0], dtype=Y.dtype))
    Q = num / jnp.maximum(jnp.sum(num), 1e-12)
    PQ = (P - jnp.maximum(Q, 1e-12)) * num                           # [N,N]
    # grad_i = 4 Σ_j PQ_ij (y_i − y_j)  → diag trick keeps it matmul-shaped
    grad = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ Y)
    # gains: grow when grad and velocity disagree (Tsne.java gains logic)
    same_sign = (grad > 0) == (velocity > 0)
    gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01, None)
    velocity = momentum * velocity - lr * gains * grad
    Y = Y + velocity
    Y = Y - jnp.mean(Y, axis=0, keepdims=True)
    kl = jnp.sum(jnp.where(P > 0, P * jnp.log(jnp.maximum(P, 1e-12)
                                              / jnp.maximum(Q, 1e-12)), 0.0))
    return Y, velocity, gains, kl


# ---------------------------------------------------------------------------
# chunked large-N path: sparse-KNN affinities + streamed exact repulsion
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnums=(3, 4))
def _knn_rows(xq: Array, row0: Array, x: Array, k: int, block: int):
    """k-NN of the row chunk ``xq`` against the full set ``x``, streaming
    candidate columns in [R,B] tiles (the VPTree's role, MXU-shaped).
    ``row0``: global index of xq's first row (self-match exclusion)."""
    r, n = xq.shape[0], x.shape[0]
    xq2 = jnp.sum(xq * xq, axis=1)
    x2 = jnp.sum(x * x, axis=1)
    pad = (-n) % block
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    x2p = jnp.pad(x2, (0, pad), constant_values=jnp.inf)
    n_blocks = xp.shape[0] // block

    def body(carry, b):
        best_d, best_i = carry                       # [R,k] running top-k
        xb = jax.lax.dynamic_slice(xp, (b * block, 0), (block, x.shape[1]))
        xb2 = jax.lax.dynamic_slice(x2p, (b * block,), (block,))
        d2 = xq2[:, None] + xb2[None, :] - 2.0 * (xq @ xb.T)   # [R,B]
        cols = b * block + jnp.arange(block)
        rows = row0 + jnp.arange(r)
        d2 = jnp.where(cols[None, :] == rows[:, None], jnp.inf, d2)
        d2 = jnp.where(cols[None, :] >= n, jnp.inf, d2)        # padding
        cat_d = jnp.concatenate([best_d, d2], axis=1)
        cat_i = jnp.concatenate([best_i, jnp.broadcast_to(
            cols[None, :], (r, block))], axis=1)
        nd, sel = jax.lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    init = (jnp.full((r, k), jnp.inf, x.dtype),
            jnp.zeros((r, k), jnp.int32))
    (d2k, idx), _ = jax.lax.scan(body, init, jnp.arange(n_blocks))
    return idx.astype(jnp.int32), jnp.maximum(d2k, 0.0)


def _knn_blocked(x: Array, k: int, block: int, row_chunk: int = 65536):
    """Brute-force k-NN: rows processed in host-level chunks (bounds the
    [R, k+B] sort buffers), columns streamed on device.  Returns
    (idx [N,k] int32, d2 [N,k] f32) — self excluded."""
    n = x.shape[0]
    if n <= row_chunk:
        return _knn_rows(x, jnp.int32(0), x, k, block)
    outs = []
    for r0 in range(0, n, row_chunk):
        r1 = min(r0 + row_chunk, n)
        outs.append(_knn_rows(x[r0:r1], jnp.int32(r0), x, k, block))
    return (jnp.concatenate([o[0] for o in outs]),
            jnp.concatenate([o[1] for o in outs]))


def _sparse_p_search(d2k: Array, perplexity: float, iters: int = 50):
    """Vectorized per-row precision bisection over the k-NN distances
    (all rows in parallel — the device form of Tsne.java's hBeta loop).
    Returns row-normalized P [N,k]."""
    target = jnp.log(perplexity)

    def h_of(beta):
        p = jnp.exp(-d2k * beta[:, None])
        s = jnp.maximum(jnp.sum(p, axis=1), 1e-30)
        h = jnp.log(s) + beta * jnp.sum(d2k * p, axis=1) / s
        return h, p / s[:, None]

    def body(carry, _):
        beta, lo, hi = carry
        h, _ = h_of(beta)
        too_high = h > target                       # entropy high → raise beta
        lo2 = jnp.where(too_high, beta, lo)
        hi2 = jnp.where(too_high, hi, beta)
        beta2 = jnp.where(too_high,
                          jnp.where(jnp.isinf(hi2), beta * 2.0, (beta + hi2) / 2),
                          jnp.where(jnp.isneginf(lo2), beta / 2.0, (beta + lo2) / 2))
        return (beta2, lo2, hi2), None

    n = d2k.shape[0]
    init = (jnp.ones((n,), d2k.dtype),
            jnp.full((n,), -jnp.inf, d2k.dtype),
            jnp.full((n,), jnp.inf, d2k.dtype))
    (beta, _, _), _ = jax.lax.scan(body, init, None, length=iters)
    _, p = h_of(beta)
    return p


def _symmetrize_sparse(idx: Array, p: Array, row_block: int = 4096):
    """P_sym[i,a] = (p_i[a] + p_{j→i}) / (2N) for j = idx[i,a], where
    p_{j→i} is j's affinity back to i if i is among j's neighbors (0
    otherwise) — symmetric VALUES on the directed-KNN support, in row
    blocks.  Used for KL reporting and the k=N−1 parity path; the gradient
    itself uses the both-endpoint edge scatter in _chunked_tsne_step,
    which realizes the full UNION support (a one-directional in-link still
    attracts both endpoints) without materializing it."""
    n, k = idx.shape
    pad = (-n) % row_block
    idx_p = jnp.pad(idx, ((0, pad), (0, 0)))
    p_p = jnp.pad(p, ((0, pad), (0, 0)))

    def body(_, b):
        rows = b * row_block + jnp.arange(row_block)
        my_idx = jax.lax.dynamic_slice(idx_p, (b * row_block, 0), (row_block, k))
        my_p = jax.lax.dynamic_slice(p_p, (b * row_block, 0), (row_block, k))
        nbr_idx = idx[my_idx]                       # [R, k, k]
        nbr_p = p[my_idx]                           # [R, k, k]
        match = nbr_idx == rows[:, None, None]      # does j point back at i?
        back = jnp.sum(jnp.where(match, nbr_p, 0.0), axis=2)
        return None, (my_p + back) / (2.0 * n)

    _, out = jax.lax.scan(body, None, jnp.arange(idx_p.shape[0] // row_block))
    return out.reshape(-1, k)[:n]


def _chunked_tsne_step(idx, P_cond, P_sym, Y, velocity, gains, momentum, lr,
                       block):
    """One exact gradient iteration with the repulsive term streamed in
    [N,B] column blocks.  grad_i = 4[Σ_j s_ij num_ij (y_i−y_j)
    − (Σ_j num²_ij (y_i−y_j)) / Z] with Z accumulated across blocks before
    the single division — bit-for-bit the dense math, never an [N,N]
    buffer.

    Attraction uses s_ij = (p_ij + p_ji)/(2N) over the UNION of the
    directed KNN supports, realized by scattering each directed edge
    (i→j, weight w = p_ij/2N) to BOTH endpoints: i accumulates its own
    out-edges plus every in-link, which sums to exactly Σ_j s_ij·… even
    for asymmetric pairs (a hub point j in many neighbor lists is pulled
    by all of them although its own k slots are full).  ``P_cond`` is the
    row-conditional affinity [N,k] (optionally early-exaggerated);
    ``P_sym`` the symmetric values for the KL diagnostic."""
    n, d = Y.shape
    y2 = jnp.sum(Y * Y, axis=1)
    pad = (-n) % block
    Yp = jnp.pad(Y, ((0, pad), (0, 0)))
    y2p = jnp.pad(y2, (0, pad))
    n_blocks = Yp.shape[0] // block

    def rep_block(carry, b):
        Z, S2, W = carry
        Yb = jax.lax.dynamic_slice(Yp, (b * block, 0), (block, d))
        yb2 = jax.lax.dynamic_slice(y2p, (b * block,), (block,))
        num = 1.0 / (1.0 + y2[:, None] + yb2[None, :] - 2.0 * (Y @ Yb.T))
        cols = b * block + jnp.arange(block)
        valid = (cols[None, :] != jnp.arange(n)[:, None]) & (cols[None, :] < n)
        num = jnp.where(valid, num, 0.0)
        Z = Z + jnp.sum(num)
        nsq = num * num
        S2 = S2 + jnp.sum(nsq, axis=1)
        W = W + nsq @ Yb
        return (Z, S2, W), None

    (Z, S2, W), _ = jax.lax.scan(
        rep_block, (jnp.zeros((), Y.dtype), jnp.zeros((n,), Y.dtype),
                    jnp.zeros((n, d), Y.dtype)), jnp.arange(n_blocks))
    Z = jnp.maximum(Z, 1e-12)
    rep = (S2[:, None] * Y - W) / Z                 # Σ num²(y_i−y_j)/Z

    # attractive term: both-endpoint scatter over the directed KNN edges
    # (see docstring — exact union-support symmetrization)
    Yn = Y[idx]                                     # [N, k, d]
    dif = Y[:, None, :] - Yn
    num_k = 1.0 / (1.0 + jnp.sum(dif * dif, axis=2))
    w = P_cond / (2.0 * n)
    f = (w * num_k)[:, :, None] * dif               # [N, k, d] edge forces
    attr = jnp.sum(f, axis=1)                       # … on the source ends
    attr = attr - jnp.zeros_like(Y).at[idx.reshape(-1)].add(
        f.reshape(-1, d))                           # reaction on targets
    grad = 4.0 * (attr - rep)

    same_sign = (grad > 0) == (velocity > 0)
    gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01, None)
    velocity = momentum * velocity - lr * gains * grad
    Y = Y + velocity
    Y = Y - jnp.mean(Y, axis=0, keepdims=True)
    # KL diagnostic over the directed support (one-directional in-links
    # contribute once instead of twice — reporting only, not the gradient)
    q = jnp.maximum(num_k / Z, 1e-12)
    kl = jnp.sum(jnp.where(P_sym > 0,
                           P_sym * jnp.log(jnp.maximum(P_sym, 1e-12) / q),
                           0.0))
    return Y, velocity, gains, kl


_chunked_step_jit = jax.jit(_chunked_tsne_step, donate_argnums=(3, 4, 5),
                            static_argnums=(8,))


class Tsne:
    """Builder-parity surface (reference BarnesHutTsne.Builder):
    setMaxIter, perplexity, theta (ignored — exact), learningRate,
    useAdaGrad→gains, stopLyingIteration (early exaggeration end).

    ``method``: "exact" (dense [N,N], the small-N fast path), "chunked"
    (sparse-KNN attraction + streamed exact repulsion, HBM-unbounded N),
    or "auto" (chunked above ``auto_chunk_threshold`` points).
    ``knn_k`` (chunked): neighbors for the sparse affinities; default
    3·perplexity (the reference BarnesHutTsne's choice), capped at N−1 —
    at k = N−1 chunked and exact affinities coincide (the parity test)."""

    def __init__(self,
                 n_components: int = 2,
                 perplexity: float = 30.0,
                 max_iter: int = 500,
                 learning_rate: float = 200.0,
                 early_exaggeration: float = 12.0,
                 stop_lying_iteration: int = 100,
                 initial_momentum: float = 0.5,
                 final_momentum: float = 0.8,
                 momentum_switch: int = 250,
                 seed: int = 12345,
                 method: str = "auto",
                 knn_k: Optional[int] = None,
                 block_size: int = 1024,
                 auto_chunk_threshold: int = 8192):
        self.n_components = n_components
        self.perplexity = perplexity
        self.max_iter = max_iter
        self.lr = learning_rate
        self.early_exaggeration = early_exaggeration
        self.stop_lying_iteration = stop_lying_iteration
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.seed = seed
        if method not in ("auto", "exact", "chunked"):
            raise ValueError(f"method must be auto|exact|chunked, got {method!r}")
        self.method = method
        self.knn_k = knn_k
        self.block_size = block_size
        self.auto_chunk_threshold = auto_chunk_threshold
        self.kl_divergence_: Optional[float] = None

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        if n < 4:
            raise ValueError("t-SNE needs at least 4 points")
        if self.perplexity >= (n - 1) / 3:
            raise ValueError(f"perplexity {self.perplexity} too large for N={n} "
                             "(need perplexity < (N-1)/3)")
        if self.method == "chunked" or (self.method == "auto"
                                        and n > self.auto_chunk_threshold):
            return self._fit_chunked(x.astype(np.float32))
        # symmetric affinities from the perplexity search
        d2 = np.sum(x * x, axis=1)[:, None] + np.sum(x * x, axis=1)[None, :] \
            - 2.0 * (x @ x.T)
        np.fill_diagonal(d2, 0.0)
        P = _binary_search_p(np.maximum(d2, 0.0), self.perplexity)
        P = (P + P.T) / (2.0 * n)
        P = np.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        Y = jnp.asarray(rng.normal(0.0, 1e-4, (n, self.n_components))
                        .astype(np.float32))
        vel = jnp.zeros_like(Y)
        gains = jnp.ones_like(Y)
        P_lying = jnp.asarray((P * self.early_exaggeration).astype(np.float32))
        P_true = jnp.asarray(P.astype(np.float32))
        kl = None
        for it in range(self.max_iter):
            Pj = P_lying if it < self.stop_lying_iteration else P_true
            mom = self.initial_momentum if it < self.momentum_switch \
                else self.final_momentum
            Y, vel, gains, kl = _tsne_step(Pj, Y, vel, gains,
                                           jnp.asarray(mom, jnp.float32), self.lr)
        self.kl_divergence_ = None if kl is None else float(kl)
        return np.asarray(Y)

    def _fit_chunked(self, x: np.ndarray) -> np.ndarray:
        """Large-N path: sparse-KNN affinities + streamed exact repulsion
        (see module docstring).  Peak memory O(N·(B + k))."""
        n = x.shape[0]
        k = self.knn_k if self.knn_k is not None else int(3 * self.perplexity)
        k = min(k, n - 1)
        if k < self.perplexity:
            # the per-row entropy bisection can never reach log(perplexity)
            # over k neighbors (max entropy = log k): P would silently
            # degenerate to uniform rows
            raise ValueError(f"knn_k={k} < perplexity={self.perplexity}: "
                             "need k >= perplexity (default 3*perplexity)")
        block = min(self.block_size, n)
        xd = jnp.asarray(x)
        # KNN wants LARGE column blocks (the top-k merge per scan step is
        # the cost; measured 4x faster at 8192 than 1024) while the
        # per-iteration repulsion block stays small (memory-bound)
        idx, d2k = _knn_blocked(xd, k, max(block, min(8192, n)))
        p_cond = _sparse_p_search(d2k, self.perplexity)
        P_sym = jnp.maximum(_symmetrize_sparse(idx, p_cond,
                                               row_block=min(4096, n)), 1e-12)

        rng = np.random.default_rng(self.seed)
        Y = jnp.asarray(rng.normal(0.0, 1e-4, (n, self.n_components))
                        .astype(np.float32))
        vel = jnp.zeros_like(Y)
        gains = jnp.ones_like(Y)
        P_lying = p_cond * self.early_exaggeration
        kl = None
        for it in range(self.max_iter):
            Pj = P_lying if it < self.stop_lying_iteration else p_cond
            mom = self.initial_momentum if it < self.momentum_switch \
                else self.final_momentum
            Y, vel, gains, kl = _chunked_step_jit(
                idx, Pj, P_sym, Y, vel, gains, jnp.asarray(mom, jnp.float32),
                self.lr, block)
        self.kl_divergence_ = None if kl is None else float(kl)
        return np.asarray(Y)
