"""Dimensionality reduction for visualization (replaces
deeplearning4j-core plot/: BarnesHutTsne + Tsne)."""

from .tsne import Tsne

__all__ = ["Tsne"]
