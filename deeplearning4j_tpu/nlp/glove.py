"""GloVe — global vectors from a weighted co-occurrence factorization.

Parity targets: reference models/glove/Glove.java (Builder: xMax, alpha,
learningRate, epochs, symmetric) + models/glove/AbstractCoOccurrences.java
(windowed 1/distance-weighted counting) + the AdaGrad element math in
GloveWeightLookupTable.

TPU inversion: the reference streams co-occurrence pairs through per-thread
AdaGrad updates; here the nonzero co-occurrence entries are shuffled into
fixed-size batches and each batch is one jit-compiled step — dense batched
gathers/matmuls for the loss, scatter-adds for the sparse AdaGrad update.
Loss (Pennington et al. 2014):
    J = Σ f(X_ij) (wᵢ·w̃ⱼ + bᵢ + b̃ⱼ − log X_ij)²,   f(x) = min(1, (x/xmax)^α)
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .sequencevectors import WordVectorsBase
from .tokenization import DefaultTokenizerFactory
from .vocab import VocabCache, build_vocab

logger = logging.getLogger("deeplearning4j_tpu")


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(W, Wc, b, bc, hW, hWc, hb, hbc, rows, cols, logx, fx, lr):
    """One AdaGrad batch over co-occurrence entries.

    W/Wc [V,D] center/context tables, b/bc [V] biases, h* AdaGrad
    accumulators.  rows/cols [B] word indices, logx [B] = log X_ij,
    fx [B] = f(X_ij) weights (0 for padding rows).
    """
    wi = W[rows]                      # [B,D]
    wj = Wc[cols]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols] - logx  # [B]
    fdiff = fx * diff                 # [B]
    loss = 0.5 * jnp.sum(fdiff * diff)

    gwi = fdiff[:, None] * wj         # [B,D]
    gwj = fdiff[:, None] * wi
    gbi = fdiff
    gbj = fdiff

    # AdaGrad: accumulate squared grads, scale update by 1/sqrt(hist)
    def upd(table, hist, idx, g):
        hist = hist.at[idx].add(g * g)
        step = lr * g / jnp.sqrt(jnp.maximum(hist[idx], 1e-12))
        return table.at[idx].add(-step), hist

    W, hW = upd(W, hW, rows, gwi)
    Wc, hWc = upd(Wc, hWc, cols, gwj)
    b, hb = upd(b, hb, rows, gbi)
    bc, hbc = upd(bc, hbc, cols, gbj)
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


class CoOccurrences:
    """Windowed co-occurrence counting with 1/distance weighting
    (reference AbstractCoOccurrences.java, symmetric window)."""

    def __init__(self, window: int = 15, symmetric: bool = True):
        self.window = window
        self.symmetric = symmetric

    def count(self, idx_corpus: Iterable[np.ndarray]) -> Dict[Tuple[int, int], float]:
        cooc: Dict[Tuple[int, int], float] = {}
        for sent in idx_corpus:
            n = len(sent)
            for pos in range(n):
                w = int(sent[pos])
                hi = min(n, pos + self.window + 1)
                for j in range(pos + 1, hi):
                    c = int(sent[j])
                    weight = 1.0 / (j - pos)
                    cooc[(w, c)] = cooc.get((w, c), 0.0) + weight
                    if self.symmetric:
                        cooc[(c, w)] = cooc.get((c, w), 0.0) + weight
        return cooc


class Glove(WordVectorsBase):
    """GloVe trainer (reference Glove.Builder surface)."""

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 15,
                 min_word_frequency: int = 1,
                 xmax: float = 100.0,
                 alpha: float = 0.75,
                 learning_rate: float = 0.05,
                 epochs: int = 25,
                 batch_size: int = 4096,
                 symmetric: bool = True,
                 seed: int = 12345,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.xmax = xmax
        self.alpha = alpha
        self.lr = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.symmetric = symmetric
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None
        self._norms = None

    def fit(self, sentences: Iterable) -> "Glove":
        corpus = [self.tokenizer.tokenize(s) if isinstance(s, str) else list(s)
                  for s in sentences]
        self.vocab = build_vocab(corpus, self.min_word_frequency)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — lower min_word_frequency?")
        V, D = len(self.vocab), self.layer_size
        idx_corpus = [np.asarray([self.vocab.index_of(t) for t in s
                                  if t in self.vocab], np.int32)
                      for s in corpus]

        cooc = CoOccurrences(self.window, self.symmetric).count(idx_corpus)
        if not cooc:
            raise ValueError("no co-occurrences — corpus too small?")
        entries = np.asarray([(i, j, x) for (i, j), x in cooc.items()], np.float64)
        rows_all = entries[:, 0].astype(np.int32)
        cols_all = entries[:, 1].astype(np.int32)
        xs = entries[:, 2]
        logx_all = np.log(xs).astype(np.float32)
        fx_all = np.minimum(1.0, (xs / self.xmax) ** self.alpha).astype(np.float32)
        N = len(rows_all)

        rng = np.random.default_rng(self.seed)
        scale = 0.5 / D
        W = jnp.asarray(((rng.random((V, D)) - 0.5) * 2 * scale).astype(np.float32))
        Wc = jnp.asarray(((rng.random((V, D)) - 0.5) * 2 * scale).astype(np.float32))
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        hW = jnp.ones((V, D), jnp.float32)   # GloVe convention: hist init 1
        hWc = jnp.ones((V, D), jnp.float32)
        hb = jnp.ones((V,), jnp.float32)
        hbc = jnp.ones((V,), jnp.float32)

        B = min(self.batch_size, max(64, N))
        lr_j = jnp.asarray(self.lr, jnp.float32)
        self.losses: List[float] = []
        for ep in range(self.epochs):
            perm = rng.permutation(N)
            ep_loss, nb = 0.0, 0
            for s in range(0, N, B):
                sel = perm[s:s + B]
                pad = B - len(sel)
                r = np.concatenate([rows_all[sel], np.zeros(pad, np.int32)])
                c = np.concatenate([cols_all[sel], np.zeros(pad, np.int32)])
                lx = np.concatenate([logx_all[sel], np.zeros(pad, np.float32)])
                fw = np.concatenate([fx_all[sel], np.zeros(pad, np.float32)])
                W, Wc, b, bc, hW, hWc, hb, hbc, loss = _glove_step(
                    W, Wc, b, bc, hW, hWc, hb, hbc,
                    jnp.asarray(r), jnp.asarray(c), jnp.asarray(lx),
                    jnp.asarray(fw), lr_j)
                ep_loss += float(loss)
                nb += 1
            self.losses.append(ep_loss / max(nb, 1))
        # standard GloVe: final embedding = W + context table
        self.syn0 = np.asarray(W) + np.asarray(Wc)
        self._norms = None
        return self
