"""NLP / embeddings (replaces deeplearning4j-nlp-parent, SURVEY.md §2.4).

The reference trains Word2Vec with Hogwild threads mutating shared syn0/syn1
tables through JNI AggregateSkipGram ops (SequenceVectors.java:292-296,
SkipGram.java:271-283).  Here training is the TPU-native formulation:
host-side window/negative sampling feeds a jit-compiled batched
negative-sampling objective — embedding gathers + batched dot products on
the MXU, one XLA program per step, no lock-free mutation needed.
"""

from .tokenization import (
    AggregatingSentenceIterator,
    BaseFormTokenizerFactory,
    CJKTokenizerFactory,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    LineSentenceIterator,
    PosFilterTokenizerFactory,
    get_tokenizer_factory,
    register_tokenizer_factory,
)
from .vocab import VocabCache, VocabWord, build_vocab, Huffman
from .word2vec import Word2Vec
from .sequencevectors import SequenceVectors, ParagraphVectors, WordVectorsBase
from .glove import Glove, CoOccurrences
from .distributed import DistributedWord2Vec
from .serializer import load_static_model, read_word_vectors, write_word_vectors
