"""Vocabulary + Huffman coding.

Parity targets: reference models/word2vec/wordstore/VocabConstructor.java:31
(buildJointVocabulary:167 — parallel counting, min-frequency filtering),
inmemory/AbstractCache (word↔index, counts), and Huffman.java:34 (code/point
assignment for hierarchical softmax).
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class VocabWord:
    word: str
    count: int
    index: int
    # hierarchical-softmax fields (reference VocabWord codes/points)
    codes: Optional[List[int]] = None
    points: Optional[List[int]] = None


class VocabCache:
    """In-memory vocab (reference AbstractCache): index ↔ word ↔ count."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}

    def add(self, word: str, count: int) -> VocabWord:
        vw = VocabWord(word, count, len(self.words))
        self.words.append(vw)
        self._by_word[word] = vw
        return vw

    def __contains__(self, word: str) -> bool:
        return word in self._by_word

    def get(self, word: str):
        """VocabWord for ``word`` or None — the single-probe lookup hot
        paths use (one hash instead of `in` + `index_of`)."""
        return self._by_word.get(word)

    def __len__(self) -> int:
        return len(self.words)

    def word_for(self, index: int) -> str:
        return self.words[index].word

    def index_of(self, word: str) -> int:
        return self._by_word[word].index

    def count_of(self, word: str) -> int:
        return self._by_word[word].count

    def total_count(self) -> int:
        return sum(w.count for w in self.words)

    def unigram_table(self, power: float = 0.75) -> np.ndarray:
        """Negative-sampling distribution ∝ count^0.75 (word2vec standard;
        reference builds the equivalent table natively)."""
        counts = np.asarray([w.count for w in self.words], np.float64) ** power
        return (counts / counts.sum()).astype(np.float64)


def build_vocab(token_stream: Iterable[List[str]], min_word_frequency: int = 5,
                max_vocab_size: Optional[int] = None) -> VocabCache:
    """Count words over tokenized sentences → frequency-sorted VocabCache
    (reference VocabConstructor.buildJointVocabulary)."""
    counter: Counter = Counter()
    for tokens in token_stream:
        counter.update(tokens)
    vocab = VocabCache()
    items = [(w, c) for w, c in counter.items() if c >= min_word_frequency]
    items.sort(key=lambda t: (-t[1], t[0]))
    if max_vocab_size:
        items = items[:max_vocab_size]
    for w, c in items:
        vocab.add(w, c)
    return vocab


class Huffman:
    """Huffman tree over word frequencies; assigns binary codes + inner-node
    points per word (reference Huffman.java:34 — used by hierarchical
    softmax).  Max code length 40 as in the reference."""

    MAX_CODE_LENGTH = 40

    def __init__(self, vocab: VocabCache):
        self.vocab = vocab
        self._build()

    def _build(self) -> None:
        n = len(self.vocab)
        if n == 0:
            return
        # heap of (count, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
        heap: List[Tuple[int, int, int]] = [
            (w.count, i, i) for i, w in enumerate(self.vocab.words)]
        heapq.heapify(heap)
        parent = {}
        binary = {}
        next_id = n
        while len(heap) > 1:
            c1, _, a = heapq.heappop(heap)
            c2, _, b = heapq.heappop(heap)
            parent[a], parent[b] = next_id, next_id
            binary[a], binary[b] = 0, 1
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = heap[0][2] if heap else None
        for i, w in enumerate(self.vocab.words):
            codes, points = [], []
            node = i
            while node != root and node in parent:
                codes.append(binary[node])
                node = parent[node]
                points.append(node - n)  # inner-node index
            codes.reverse()
            points.reverse()
            w.codes = codes[: self.MAX_CODE_LENGTH]
            w.points = points[: self.MAX_CODE_LENGTH]

    def max_code_length(self) -> int:
        return max((len(w.codes or []) for w in self.vocab.words), default=0)
