"""Distributed Word2Vec — data-parallel embedding training over a mesh.

Parity target: reference dl4j-spark-nlp (SparkWord2Vec /
Word2VecVariables: corpus sharded across executors, parameter averaging
of the word vectors each iteration) — the "Spark NLP" row of SURVEY §2.4.

TPU inversion: instead of Spark executors averaging parameters through
the driver, the PAIR BATCH is sharded over the mesh's data axis inside
``shard_map``; every shard computes UNSCALED scatter-add deltas plus
occurrence counts against the replicated tables, a ``psum`` merges both,
and the global occurrence-average is applied — mathematically identical
to the single-device update at any mesh size (numerically equal to
~1e-5; fp summation order differs), strictly stronger than Spark's
periodic parameter averaging, with the collective on ICI instead of the
driver network.  Multi-host: call parallel.distributed.initialize()
first and feed each host its corpus shard; the same program then spans
hosts.

Cost model: the psum moves DENSE [V, D] delta tables every flush —
O(V·D) collective traffic per batch, independent of batch size.  At ICI
bandwidth this is fine up to ~10⁵-word vocabularies / large batches;
beyond that, raise ``batch_size`` (fewer flushes) or fall back to
single-device Word2Vec (row-sparse collectives are the future upgrade
path here).

``DistributedWord2Vec(mesh=...)`` is a drop-in Word2Vec whose jitted
update runs sharded; with a 1-device mesh it reproduces the
single-device step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .sequencevectors import _sg_pair_grads
from .word2vec import Word2Vec


def _sg_raw_deltas(syn0, syn1, centers, contexts, negatives, valid, lr):
    """UNSCALED table deltas + occurrence counts for one pair shard.
    Summing (deltas, counts) across shards and dividing afterwards
    reproduces the single-device _sg_chunk occurrence-averaging
    independent of how pairs land on shards.  Gradient math shared with
    the local step via _sg_pair_grads."""
    dv, du_flat, flat_t, flat_tw = _sg_pair_grads(
        syn0, syn1, centers, contexts, negatives, valid, lr)
    d0 = jnp.zeros_like(syn0).at[centers].add(dv * valid[:, None])
    n0 = jnp.zeros((syn0.shape[0],), jnp.float32).at[centers].add(valid)
    d1 = jnp.zeros_like(syn1).at[flat_t].add(du_flat * flat_tw[:, None])
    n1 = jnp.zeros((syn1.shape[0],), jnp.float32).at[flat_t].add(flat_tw)
    return d0, n0, d1, n1


def make_dp_sg_step(mesh: Mesh, data_axis: str = "data"):
    """Build the sharded skip-gram step: pairs split over ``data_axis``,
    tables replicated; raw deltas AND occurrence counts psum, then the
    global occurrence-average is applied — bit-for-bit the single-device
    update semantics at any mesh size."""

    def shard_fn(syn0, syn1, centers, contexts, negatives, valid, lr):
        d0, n0, d1, n1 = _sg_raw_deltas(syn0, syn1, centers, contexts,
                                        negatives, valid, lr)
        d0 = jax.lax.psum(d0, data_axis)
        n0 = jax.lax.psum(n0, data_axis)
        d1 = jax.lax.psum(d1, data_axis)
        n1 = jax.lax.psum(n1, data_axis)
        syn0 = syn0 + d0 / jnp.maximum(n0, 1.0)[:, None].astype(syn0.dtype)
        syn1 = syn1 + d1 / jnp.maximum(n1, 1.0)[:, None].astype(syn1.dtype)
        return syn0, syn1

    sharded = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(data_axis), P(data_axis), P(data_axis),
                  P(data_axis), P()),
        out_specs=(P(), P()))
    return jax.jit(sharded, donate_argnums=(0, 1))


class DistributedWord2Vec(Word2Vec):
    """Word2Vec with the skip-gram update sharded over a mesh's data axis
    (reference SparkWord2Vec's role).  CBOW / hierarchical softmax fall
    back to the single-device step (parity with the reference, which
    distributes the skip-gram path)."""

    def __init__(self, mesh: Optional[Mesh] = None, data_axis: str = "data",
                 **kwargs):
        if kwargs.get("cbow") or kwargs.get("hierarchic_softmax"):
            raise NotImplementedError(
                "DistributedWord2Vec shards the skip-gram/negative-sampling "
                "path; use Word2Vec for CBOW/HS")
        super().__init__(**kwargs)
        if mesh is None:
            from ..parallel.mesh import build_mesh

            mesh = build_mesh({data_axis: len(jax.devices())})
        if data_axis not in mesh.shape:
            raise ValueError(f"mesh has no '{data_axis}' axis: {dict(mesh.shape)}")
        dp = mesh.shape[data_axis]
        if self.batch_size % dp:
            raise ValueError(f"batch_size {self.batch_size} not divisible by "
                             f"data axis size {dp}")
        self.mesh = mesh
        self.data_axis = data_axis
        self._dp_step = make_dp_sg_step(mesh, data_axis)
        # the sharded step has no multi-batch scan — dispatch one batch at a
        # time (chunks stays 1; see _sg_step's loud failure for chunks>1)
        self._device_batches = 1

    # SequenceVectors' flush calls _sg_neg_step via the module global; the
    # narrowest seam is overriding fit_sequences' step through this hook:
    def _sg_step(self, syn0, syn1, centers, contexts, negatives, valid, lr,
                 chunks=1):
        if chunks > 1:
            # micro-chunk scanning (DBOW label semantics) has no sharded
            # formulation here — fail loudly rather than silently average
            # consecutive label pairs away
            raise NotImplementedError(
                "DistributedWord2Vec does not support chunked sequential "
                "updates (chunks>1, used by DBOW label training)")
        return self._dp_step(syn0, syn1, centers, contexts, negatives,
                             valid, lr)
