"""Distributed Word2Vec — data-parallel embedding training over a mesh.

Parity target: reference dl4j-spark-nlp (SparkWord2Vec /
Word2VecVariables: corpus sharded across executors, parameter averaging
of the word vectors each iteration) — the "Spark NLP" row of SURVEY §2.4.

TPU inversion: instead of Spark executors averaging parameters through
the driver, the PAIR BATCH is sharded over the mesh's data axis inside
``shard_map``; every shard computes UNSCALED scatter-add deltas plus
occurrence counts against the replicated tables, a ``psum`` merges both,
and the global occurrence-average is applied — mathematically identical
to the single-device update at any mesh size (numerically equal to
~1e-5; fp summation order differs), strictly stronger than Spark's
periodic parameter averaging, with the collective on ICI instead of the
driver network.  Multi-host: call parallel.distributed.initialize()
first and feed each host its corpus shard; the same program then spans
hosts.

Cost model: collectives are ROW-SPARSE — each flush all_gathers the
per-pair gradient rows and indices, O(B·D·(2+K)) wire traffic per batch
independent of vocabulary size (the round-2 dense-[V,D]-psum cap is
gone; at B=4096, K=5, D=128 that's ~15MB/flush whether V is 10³ or
10⁷).  The scatter-add into the replicated tables happens identically
on every device from the gathered global pair set, preserving exact
single-device occurrence-averaging semantics.

``DistributedWord2Vec(mesh=...)`` is a drop-in Word2Vec whose jitted
update runs sharded; with a 1-device mesh it reproduces the
single-device step.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.jax_compat import shard_map
from .sequencevectors import _sg_pair_grads
from .word2vec import Word2Vec


def make_dp_sg_step(mesh: Mesh, data_axis: str = "data"):
    """Build the sharded skip-gram step: pairs split over ``data_axis``,
    tables replicated — with ROW-SPARSE collectives.

    Instead of psum'ing dense [V,D] delta tables (O(V·D) wire traffic per
    flush, the round-2 vocab cap), each shard all_gathers only its
    per-pair gradient ROWS and indices — O(B·D·(2+K)) traffic,
    independent of vocabulary size — and every device applies the
    identical global scatter-add with occurrence averaging.  Numerically
    this is the same sum-then-divide as the dense formulation (the
    scatter temp is local HBM, never communicated), so single-device
    semantics hold at any mesh size."""

    def shard_fn(syn0, syn1, centers, contexts, negatives, valid, lr):
        dv, du_flat, flat_t, flat_tw = _sg_pair_grads(
            syn0, syn1, centers, contexts, negatives, valid, lr)
        gather = lambda x: jax.lax.all_gather(x, data_axis, tiled=True)
        # pair-level rows+indices cross the wire, not [V,D] tables
        g_c = gather(centers)                        # [B]
        g_w = gather(valid)                          # [B]
        g_dv = gather(dv * valid[:, None])           # [B, D]
        g_t = gather(flat_t)                         # [B·(1+K)]
        g_tw = gather(flat_tw)                       # [B·(1+K)]
        g_du = gather(du_flat * flat_tw[:, None])    # [B·(1+K), D]
        n0 = jnp.zeros((syn0.shape[0],), jnp.float32).at[g_c].add(g_w)
        d0 = jnp.zeros_like(syn0).at[g_c].add(g_dv)
        n1 = jnp.zeros((syn1.shape[0],), jnp.float32).at[g_t].add(g_tw)
        d1 = jnp.zeros_like(syn1).at[g_t].add(g_du)
        syn0 = syn0 + d0 / jnp.maximum(n0, 1.0)[:, None].astype(syn0.dtype)
        syn1 = syn1 + d1 / jnp.maximum(n1, 1.0)[:, None].astype(syn1.dtype)
        return syn0, syn1

    # check_vma=False: the gathered pair set is identical on every device
    # (tiled all_gather), so the scatter-added tables ARE replicated — the
    # static varying-across-mesh inference just can't prove it; the
    # exact-parity tests (test_nlp_distributed.py) pin the semantics.
    sharded = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(), P(data_axis), P(data_axis), P(data_axis),
                  P(data_axis), P()),
        out_specs=(P(), P()), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0, 1))


class DistributedWord2Vec(Word2Vec):
    """Word2Vec with the skip-gram update sharded over a mesh's data axis
    (reference SparkWord2Vec's role).  CBOW / hierarchical softmax fall
    back to the single-device step (parity with the reference, which
    distributes the skip-gram path)."""

    def __init__(self, mesh: Optional[Mesh] = None, data_axis: str = "data",
                 **kwargs):
        if kwargs.get("cbow") or kwargs.get("hierarchic_softmax"):
            raise NotImplementedError(
                "DistributedWord2Vec shards the skip-gram/negative-sampling "
                "path; use Word2Vec for CBOW/HS")
        super().__init__(**kwargs)
        if mesh is None:
            from ..parallel.mesh import build_mesh

            mesh = build_mesh({data_axis: len(jax.devices())})
        if data_axis not in mesh.shape:
            raise ValueError(f"mesh has no '{data_axis}' axis: {dict(mesh.shape)}")
        dp = mesh.shape[data_axis]
        if self.batch_size % dp:
            raise ValueError(f"batch_size {self.batch_size} not divisible by "
                             f"data axis size {dp}")
        self.mesh = mesh
        self.data_axis = data_axis
        self._dp_step = make_dp_sg_step(mesh, data_axis)
        # the sharded step has no multi-batch scan — dispatch one batch at a
        # time (chunks stays 1; see _sg_step's loud failure for chunks>1)
        self._device_batches = 1

    # SequenceVectors' flush calls _sg_neg_step via the module global; the
    # narrowest seam is overriding fit_sequences' step through this hook:
    def _sg_step(self, syn0, syn1, centers, contexts, negatives, valid, lr,
                 chunks=1):
        if chunks > 1:
            # micro-chunk scanning (DBOW label semantics) has no sharded
            # formulation here — fail loudly rather than silently average
            # consecutive label pairs away
            raise NotImplementedError(
                "DistributedWord2Vec does not support chunked sequential "
                "updates (chunks>1, used by DBOW label training)")
        return self._dp_step(syn0, syn1, centers, contexts, negatives,
                             valid, lr)
