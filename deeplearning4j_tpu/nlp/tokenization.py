"""Tokenization — TokenizerFactory registry + preprocessors + CJK.

Parity targets: reference text/tokenization/ (DefaultTokenizerFactory
wraps a streaming whitespace tokenizer; CommonPreprocessor lowercases and
strips punctuation) and the CJK language packs —
deeplearning4j-nlp-chinese/.../ChineseTokenizer.java (word segmentation),
deeplearning4j-nlp-japanese (kuromoji), deeplearning4j-nlp-korean.

Zero-egress inversion of the language packs: their ~19.7K LoC are mostly
VENDORED DICTIONARIES + analyzer glue.  The capability — segmenting
unspaced CJK text into trainable tokens — is covered by
``CJKTokenizerFactory``: longest-match against a user-supplied dictionary
(the hook where a real lexicon slots in), falling back to overlapping
bigrams (the standard statistical-IR baseline for CJK) or single
characters.  The registry (``register_tokenizer_factory`` /
``get_tokenizer_factory``) mirrors the reference's pluggable
TokenizerFactory class-name configuration.
"""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence


class CommonPreprocessor:
    """Lowercase + strip punctuation (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[!\"#$%&'()*+,\-./:;<=>?@\[\\\]^_`{|}~«»“”‘’]")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class CasePreservingPreprocessor(CommonPreprocessor):
    """Strip punctuation but KEEP case — POS tagging needs capitalization
    (the NNP heuristic); used as the PosFilterTokenizerFactory default."""

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token)


class EndingPreProcessor:
    """Crude English stemmer (reference EndingPreProcessor: strips s/ed/ing/ly)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class DefaultTokenizerFactory:
    """Whitespace/regex tokenizer factory (reference DefaultTokenizerFactory).

    Preprocessing is memoized per distinct raw token: corpora are Zipfian,
    so the regex/lower work runs once per vocabulary entry instead of once
    per token occurrence (measured ~3× tokenizer throughput on the w2v
    bench corpus; the cache is capped to bound adversarial memory)."""

    _CACHE_CAP = 1 << 20

    def __init__(self, preprocessor=None):
        self._cache: Dict[str, str] = {}
        self.preprocessor = preprocessor or CommonPreprocessor()

    @property
    def preprocessor(self):
        return self._preprocessor

    @preprocessor.setter
    def preprocessor(self, value) -> None:
        # the memo cache holds the OLD preprocessor's outputs — swapping
        # preprocessors mid-stream must not serve stale results
        self._preprocessor = value
        self._cache.clear()

    def tokenize(self, sentence: str) -> List[str]:
        if self.preprocessor is None:
            return sentence.split()
        cache = self._cache
        toks = sentence.split()
        try:  # warm-cache fast path: direct hashing, no per-token branches
            return [p for p in [cache[t] for t in toks] if p]
        except KeyError:
            pass
        pre = self.preprocessor.pre_process
        out = []
        for t in toks:
            p = cache.get(t)
            if p is None:
                p = pre(t)
                if len(cache) < self._CACHE_CAP:
                    cache[t] = p
            if p:
                out.append(p)
        return out


def _is_cjk(ch: str) -> bool:
    """CJK Unified Ideographs (+ext A), Hiragana, Katakana, Hangul."""
    o = ord(ch)
    return (0x4E00 <= o <= 0x9FFF      # CJK Unified Ideographs
            or 0x3400 <= o <= 0x4DBF   # CJK Extension A
            or 0x3040 <= o <= 0x309F   # Hiragana
            or 0x30A0 <= o <= 0x30FF   # Katakana
            or 0xAC00 <= o <= 0xD7AF   # Hangul syllables
            or 0x1100 <= o <= 0x11FF)  # Hangul jamo


class CJKTokenizerFactory:
    """Segmenter for unspaced CJK text with a user-dictionary hook.

    ``mode`` selects the in-run algorithm:
      - "lattice" (kuromoji's algorithm class, reference
        deeplearning4j-nlp-japanese vendored ViterbiBuilder): build a word
        lattice over the run from dictionary entries + single-char
        fallback nodes and take the min-cost Viterbi path.  Dictionary
        words cost ``-log f(w)`` when ``user_dictionary`` is a
        {word: frequency} mapping (uniform when a plain sequence), so
        overlapping entries resolve globally — where greedy longest-match
        commits to 研究生|命, the lattice picks 研究|生命 when the
        frequencies say so.  Unmatched chars ride fallback nodes whose
        cost exceeds any dictionary word.
      - "bigram": greedy longest-match against the dictionary, unmatched
        spans become overlapping character bigrams (standard CJK IR
        baseline; a single leftover char becomes a unigram)
      - "char": greedy longest-match; unmatched spans one char per token
    Non-CJK spans (latin words, digits) tokenize by whitespace with the
    preprocessor applied, so mixed-script corpora work end-to-end.

    Dictionary entries may carry a POS tag — value ``(frequency, tag)``
    instead of a bare frequency — and ``tokenize_with_tags`` /
    ``tag`` expose them per token (the kuromoji lexicon's POS column,
    reference deeplearning4j-nlp-japanese).  The factory then plugs into
    ``PosFilterTokenizerFactory`` as BOTH base and tagger for
    POS-filtered CJK vectorization.
    """

    #: fallback unigram cost — higher than any realistic dictionary word
    #: (-log f with f normalized over the dictionary stays below ~20)
    _FALLBACK_COST = 25.0

    #: tag emitted for tokens with no dictionary POS (fallback chars,
    #: bigrams, unknown words) — kuromoji's unknown-word analog
    UNKNOWN_TAG = "X"

    def __init__(self, user_dictionary=None,
                 mode: str = "bigram", preprocessor=None):
        if mode not in ("bigram", "char", "lattice"):
            raise ValueError(
                f"mode must be 'bigram', 'char' or 'lattice', got {mode!r}")
        self.mode = mode
        self.preprocessor = preprocessor or CommonPreprocessor()
        # dictionary values: frequency, (frequency, pos_tag), or
        # (frequency, pos_tag, base_form) — the morphological surfaces the
        # reference's kuromoji dictionaries carry
        # (deeplearning4j-nlp-japanese vendored lexicon rows hold POS and
        # base-form columns next to the cost); tags are opaque strings
        # (名詞/動詞 for a Japanese lexicon, NN/JJ for an English one) and
        # base_form is the lemma a conjugated surface reduces to
        # (食べた → 食べる, kuromoji Token.getBaseForm)
        self._pos: Dict[str, str] = {}
        self._base: Dict[str, str] = {}
        if isinstance(user_dictionary, dict):
            freqs = {}
            for w, v in user_dictionary.items():
                if isinstance(v, (tuple, list)):
                    if len(v) not in (2, 3):
                        raise ValueError(
                            f"dictionary entry {w!r}: expected frequency, "
                            f"(frequency, pos_tag) or (frequency, pos_tag, "
                            f"base_form), got {v!r}")
                    freqs[w] = v[0]
                    self._pos[w] = str(v[1])
                    if len(v) == 3:
                        self._base[w] = str(v[2])
                else:
                    freqs[w] = v
            if any(c <= 0 for c in freqs.values()):
                raise ValueError("user_dictionary frequencies must be > 0")
            total = float(sum(freqs.values()))
            # works for raw counts AND probability-valued frequencies —
            # only the ratios matter to the Viterbi comparison
            self._costs = {w: -math.log(c / total)
                           for w, c in freqs.items()}
        else:
            # uniform frequencies; mild length bonus keeps longest-match
            # behavior for non-overlapping text
            self._costs = {w: 10.0 - 0.01 * len(w)
                           for w in (user_dictionary or ())}
        self.dictionary = set(self._costs)
        self._max_word = max((len(w) for w in self.dictionary), default=0)
        self._latin_tagger = None  # lazy RuleBasedPosTagger for mixed text

    def _segment_lattice(self, run: str) -> List[str]:
        """Min-cost Viterbi path through the word lattice."""
        n = len(run)
        best = [math.inf] * (n + 1)
        back: List[Optional[tuple]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] == math.inf:
                continue
            # fallback single-char edge keeps the lattice connected
            c = best[i] + self._FALLBACK_COST
            if c < best[i + 1]:
                best[i + 1] = c
                back[i + 1] = (i, run[i])
            for L in range(1, min(self._max_word, n - i) + 1):
                w = run[i:i + L]
                wc = self._costs.get(w)
                if wc is not None and best[i] + wc < best[i + L]:
                    best[i + L] = best[i] + wc
                    back[i + L] = (i, w)
        out: List[str] = []
        pos = n
        while pos > 0:
            prev, w = back[pos]
            out.append(w)
            pos = prev
        return out[::-1]

    def _segment_cjk(self, run: str) -> List[str]:
        if self.mode == "lattice":
            return self._segment_lattice(run)
        out: List[str] = []
        i, n = 0, len(run)
        pending_start = 0

        def flush_fallback(start: int, end: int) -> None:
            span = run[start:end]
            if not span:
                return
            if self.mode == "char" or len(span) == 1:
                out.extend(span)
            else:
                out.extend(span[j:j + 2] for j in range(len(span) - 1))

        while i < n:
            match = None
            if self.dictionary:
                for L in range(min(self._max_word, n - i), 0, -1):
                    if run[i:i + L] in self.dictionary:
                        match = run[i:i + L]
                        break
            if match:
                flush_fallback(pending_start, i)
                out.append(match)
                i += len(match)
                pending_start = i
            else:
                i += 1
        flush_fallback(pending_start, n)
        return out

    def tokenize(self, sentence: str) -> List[str]:
        tokens: List[str] = []
        buf: List[str] = []  # non-CJK accumulator

        def flush_non_cjk() -> None:
            if buf:
                for t in "".join(buf).split():
                    t = self.preprocessor.pre_process(t) if self.preprocessor else t
                    if t:
                        tokens.append(t)
                buf.clear()

        i = 0
        while i < len(sentence):
            if _is_cjk(sentence[i]):
                flush_non_cjk()
                j = i
                while j < len(sentence) and _is_cjk(sentence[j]):
                    j += 1
                tokens.extend(self._segment_cjk(sentence[i:j]))
                i = j
            else:
                buf.append(sentence[i])
                i += 1
        flush_non_cjk()
        return tokens

    def tag(self, tokens: Sequence[str]) -> List[str]:
        """POS tags for already-segmented tokens: dictionary entries carry
        their lexicon tag (kuromoji's per-token POS surface), unknown CJK
        tokens get UNKNOWN_TAG, and latin tokens in mixed-script text fall
        through to the rule-based English tagger.  This signature makes
        the factory directly usable as PosFilterTokenizerFactory's
        ``tagger`` (with itself as ``base``)."""
        out = []
        for t in tokens:
            tag = self._pos.get(t)
            if tag is not None:
                out.append(tag)
            elif t and _is_cjk(t[0]):
                out.append(self.UNKNOWN_TAG)
            else:
                if self._latin_tagger is None:
                    self._latin_tagger = RuleBasedPosTagger()
                out.append(self._latin_tagger.tag([t])[0])
        return out

    def tokenize_with_tags(self, sentence: str) -> List[tuple]:
        """(token, pos_tag) pairs — the lattice/segmenter output annotated
        with the dictionary's POS column (reference kuromoji
        Token.getPartOfSpeechLevel1)."""
        toks = self.tokenize(sentence)
        return list(zip(toks, self.tag(toks)))

    def base_form(self, token: str) -> str:
        """The dictionary lemma for a surface form, or the surface itself
        (reference kuromoji Token.getBaseForm: conjugated 食べた → 食べる)."""
        return self._base.get(token, token)

    def tokenize_with_morphology(self, sentence: str) -> List[tuple]:
        """(surface, pos_tag, base_form) triples — the full per-token
        morphological surface of the reference's Japanese analyzer."""
        toks = self.tokenize(sentence)
        return [(t, g, self.base_form(t))
                for t, g in zip(toks, self.tag(toks))]


# ---------------------------------------------------------------------------
# POS tagging hook (the deeplearning4j-nlp-uima PosUimaTokenizerFactory role)
# ---------------------------------------------------------------------------


class RuleBasedPosTagger:
    """Dependency-free English POS tagger: closed-class lookup + suffix
    heuristics (the pluggable default — swap in any ``tag(tokens)``
    callable for a real model).  Tags follow the Penn treebank names the
    reference's UIMA annotators emit."""

    _CLOSED = {
        "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
        "these": "DT", "those": "DT",
        "i": "PRP", "you": "PRP", "he": "PRP", "she": "PRP", "it": "PRP",
        "we": "PRP", "they": "PRP", "me": "PRP", "him": "PRP", "her": "PRP",
        "them": "PRP", "us": "PRP",
        "my": "PRP$", "your": "PRP$", "his": "PRP$", "its": "PRP$",
        "our": "PRP$", "their": "PRP$",
        "in": "IN", "on": "IN", "at": "IN", "by": "IN", "for": "IN",
        "with": "IN", "from": "IN", "of": "IN", "to": "TO", "as": "IN",
        "into": "IN", "over": "IN", "under": "IN",
        "and": "CC", "or": "CC", "but": "CC", "nor": "CC",
        "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD",
        "be": "VB", "been": "VBN", "being": "VBG", "am": "VBP",
        "have": "VBP", "has": "VBZ", "had": "VBD",
        "do": "VBP", "does": "VBZ", "did": "VBD",
        "will": "MD", "would": "MD", "can": "MD", "could": "MD",
        "shall": "MD", "should": "MD", "may": "MD", "might": "MD",
        "must": "MD",
        "not": "RB", "very": "RB", "quite": "RB", "too": "RB",
    }

    def tag(self, tokens: Sequence[str]) -> List[str]:
        out = []
        for t in tokens:
            low = t.lower()
            if low in self._CLOSED:
                out.append(self._CLOSED[low])
            elif low.replace(".", "", 1).replace(",", "").isdigit():
                out.append("CD")
            elif low.endswith("ly"):
                out.append("RB")
            elif low.endswith("ing") and len(low) > 4:
                out.append("VBG")
            elif low.endswith("ed") and len(low) > 3:
                out.append("VBD")
            elif low.endswith(("ous", "ful", "ive", "able", "ible", "al",
                               "ic")) and len(low) > 4:
                out.append("JJ")
            elif low.endswith("s") and not low.endswith(("ss", "us", "is")) \
                    and len(low) > 3:
                out.append("NNS")
            elif t[:1].isupper():
                out.append("NNP")
            else:
                out.append("NN")
        return out


class PosFilterTokenizerFactory:
    """Tokenize with ``base`` then keep only tokens whose POS tag is in
    ``allowed_tags`` (reference PosUimaTokenizerFactory: tokens outside the
    allowed set are stripped before vectorization).  ``tagger`` is any
    object with ``tag(tokens) -> tags`` — rule-based English default."""

    def __init__(self, allowed_tags: Sequence[str], base=None, tagger=None,
                 preprocessor=None):
        # default base preserves case: lowercasing before tagging would
        # make the NNP (proper noun) heuristic unreachable
        self.base = base or DefaultTokenizerFactory(
            preprocessor=preprocessor or CasePreservingPreprocessor())
        self.allowed = set(allowed_tags)
        self.tagger = tagger or RuleBasedPosTagger()

    def tokenize(self, sentence: str) -> List[str]:
        tokens = self.base.tokenize(sentence)
        tags = self.tagger.tag(tokens)
        return [t for t, g in zip(tokens, tags) if g in self.allowed]

    def tokenize_with_tags(self, sentence: str) -> List[tuple]:
        """(token, tag) pairs without filtering — the annotation surface."""
        tokens = self.base.tokenize(sentence)
        return list(zip(tokens, self.tagger.tag(tokens)))


class BaseFormTokenizerFactory:
    """Tokenize with ``base`` then replace each surface form by its
    dictionary lemma (reference kuromoji BaseFormFilter behavior: train
    vectors on 食べる regardless of which conjugation appeared).  ``base``
    is any factory with a ``base_form(token)`` method — the CJK factory
    with (frequency, pos_tag, base_form) dictionary entries."""

    def __init__(self, base):
        if not hasattr(base, "base_form"):
            raise ValueError("base factory must expose base_form(token) — "
                             "use CJKTokenizerFactory with (frequency, "
                             "pos_tag, base_form) dictionary entries")
        self.base = base

    def tokenize(self, sentence: str) -> List[str]:
        return [self.base.base_form(t) for t in self.base.tokenize(sentence)]


#: name → factory constructor (the reference configures TokenizerFactory
#: by class name; this registry is the same seam without reflection)
_TOKENIZER_FACTORIES: Dict[str, Callable[..., object]] = {}


def register_tokenizer_factory(name: str, ctor: Callable[..., object]) -> None:
    _TOKENIZER_FACTORIES[name.lower()] = ctor


def get_tokenizer_factory(name: str, **kwargs):
    """Build a registered tokenizer factory by name
    ('default', 'cjk', 'chinese', 'japanese', 'korean', ...)."""
    key = name.lower()
    if key not in _TOKENIZER_FACTORIES:
        raise ValueError(f"unknown tokenizer factory {name!r} "
                         f"(known: {sorted(_TOKENIZER_FACTORIES)})")
    return _TOKENIZER_FACTORIES[key](**kwargs)


register_tokenizer_factory("default", DefaultTokenizerFactory)
register_tokenizer_factory("cjk", CJKTokenizerFactory)
register_tokenizer_factory("pos", PosFilterTokenizerFactory)
register_tokenizer_factory("baseform", BaseFormTokenizerFactory)
# the language-specific names share the CJK segmenter; a real lexicon
# arrives via user_dictionary (the vendored-dictionary seam)
register_tokenizer_factory("chinese", CJKTokenizerFactory)
register_tokenizer_factory("japanese", CJKTokenizerFactory)
register_tokenizer_factory("korean", CJKTokenizerFactory)


class SentenceSegmenter:
    """Rule-based sentence boundary detection (the deeplearning4j-nlp-uima
    SentenceAnnotator role, dependency-free): splits on .!?… followed by
    whitespace + an uppercase/digit/CJK start, protecting common
    abbreviations and decimal numbers."""

    # always-protected abbreviations vs ones that are ordinary words at a
    # genuine sentence end ("she said no.", "the old st."): the latter
    # only protect when the next sentence starts with a digit ("No. 5")
    _ABBREV = {"mr", "mrs", "ms", "dr", "prof", "sr", "jr", "vs",
               "etc", "e.g", "i.e", "inc", "ltd", "co"}
    _ABBREV_NUM = {"no", "fig", "vol", "st", "p", "pp"}
    # CJK terminators split with NO following whitespace (real CJK prose
    # has none); latin terminators require it (protects decimals/initials)
    _BOUNDARY = re.compile(r"(?<=[。！？])\s*|(?<=[.!?…])\s+")

    def segment(self, text: str) -> List[str]:
        parts = self._BOUNDARY.split(text.strip())
        out: List[str] = []
        for p in parts:
            p = p.strip()
            if not p:
                continue
            if out:
                prev = out[-1]
                last_word = prev[:-1].rsplit(None, 1)[-1].lower() if " " in prev \
                    else prev[:-1].lower()
                # re-join: abbreviation before the split, or a lowercase
                # continuation (the boundary regex can't look back far)
                word = last_word.rstrip(".")
                abbrev = prev.endswith(".") and (
                    word in self._ABBREV
                    or (word in self._ABBREV_NUM and p[:1].isdigit()))
                if abbrev or p[:1].islower():
                    out[-1] = prev + " " + p
                    continue
            out.append(p)
        return out


class TextSentenceIterator:
    """Raw-text sentence iterator: SentenceSegmenter over whole documents
    (reference UimaSentenceIterator's role — feed documents, iterate
    sentences)."""

    def __init__(self, documents: Iterable[str], segmenter=None):
        self.documents = documents
        self.segmenter = segmenter or SentenceSegmenter()

    def __iter__(self) -> Iterable[str]:
        for doc in self.documents:
            yield from self.segmenter.segment(doc)


class LineSentenceIterator:
    """Sentence-per-line corpus iterator (reference BasicLineIterator)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterable[str]:
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class CollectionSentenceIterator:
    def __init__(self, sentences: List[str]):
        self.sentences = sentences

    def __iter__(self):
        return iter(self.sentences)


class AggregatingSentenceIterator:
    """Chain several sentence iterators (reference
    AggregatingSentenceIterator), with an optional per-sentence
    preprocessor (reference SentencePreProcessor)."""

    def __init__(self, *iterators, preprocessor: Optional[Callable[[str], str]] = None):
        self.iterators = list(iterators)
        self.preprocessor = preprocessor

    def __iter__(self):
        for it in self.iterators:
            for s in it:
                yield self.preprocessor(s) if self.preprocessor else s
