"""Tokenization — TokenizerFactory/Tokenizer + preprocessors.

Parity target: reference text/tokenization/ (DefaultTokenizerFactory wraps
a streaming whitespace tokenizer; CommonPreprocessor lowercases and strips
punctuation).  The CJK language packs (chinese/japanese/korean vendored
analyzers, 19,739 LoC) are out of scope for round 1 — the factory interface
accepts pluggable tokenizers so they can slot in.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional


class CommonPreprocessor:
    """Lowercase + strip punctuation (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[!\"#$%&'()*+,\-./:;<=>?@\[\\\]^_`{|}~«»“”‘’]")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class EndingPreProcessor:
    """Crude English stemmer (reference EndingPreProcessor: strips s/ed/ing/ly)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class DefaultTokenizerFactory:
    """Whitespace/regex tokenizer factory (reference DefaultTokenizerFactory)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor or CommonPreprocessor()

    def tokenize(self, sentence: str) -> List[str]:
        tokens = sentence.split()
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
        return [t for t in tokens if t]


class LineSentenceIterator:
    """Sentence-per-line corpus iterator (reference BasicLineIterator)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterable[str]:
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class CollectionSentenceIterator:
    def __init__(self, sentences: List[str]):
        self.sentences = sentences

    def __iter__(self):
        return iter(self.sentences)
