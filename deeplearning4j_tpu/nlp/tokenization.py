"""Tokenization — TokenizerFactory registry + preprocessors + CJK.

Parity targets: reference text/tokenization/ (DefaultTokenizerFactory
wraps a streaming whitespace tokenizer; CommonPreprocessor lowercases and
strips punctuation) and the CJK language packs —
deeplearning4j-nlp-chinese/.../ChineseTokenizer.java (word segmentation),
deeplearning4j-nlp-japanese (kuromoji), deeplearning4j-nlp-korean.

Zero-egress inversion of the language packs: their ~19.7K LoC are mostly
VENDORED DICTIONARIES + analyzer glue.  The capability — segmenting
unspaced CJK text into trainable tokens — is covered by
``CJKTokenizerFactory``: longest-match against a user-supplied dictionary
(the hook where a real lexicon slots in), falling back to overlapping
bigrams (the standard statistical-IR baseline for CJK) or single
characters.  The registry (``register_tokenizer_factory`` /
``get_tokenizer_factory``) mirrors the reference's pluggable
TokenizerFactory class-name configuration.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence


class CommonPreprocessor:
    """Lowercase + strip punctuation (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[!\"#$%&'()*+,\-./:;<=>?@\[\\\]^_`{|}~«»“”‘’]")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class EndingPreProcessor:
    """Crude English stemmer (reference EndingPreProcessor: strips s/ed/ing/ly)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class DefaultTokenizerFactory:
    """Whitespace/regex tokenizer factory (reference DefaultTokenizerFactory)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor or CommonPreprocessor()

    def tokenize(self, sentence: str) -> List[str]:
        tokens = sentence.split()
        if self.preprocessor is not None:
            tokens = [self.preprocessor.pre_process(t) for t in tokens]
        return [t for t in tokens if t]


def _is_cjk(ch: str) -> bool:
    """CJK Unified Ideographs (+ext A), Hiragana, Katakana, Hangul."""
    o = ord(ch)
    return (0x4E00 <= o <= 0x9FFF      # CJK Unified Ideographs
            or 0x3400 <= o <= 0x4DBF   # CJK Extension A
            or 0x3040 <= o <= 0x309F   # Hiragana
            or 0x30A0 <= o <= 0x30FF   # Katakana
            or 0xAC00 <= o <= 0xD7AF   # Hangul syllables
            or 0x1100 <= o <= 0x11FF)  # Hangul jamo


class CJKTokenizerFactory:
    """Segmenter for unspaced CJK text with a user-dictionary hook.

    Within a CJK run, greedy longest-match against ``user_dictionary``
    takes priority (ChineseTokenizer's lexicon role); unmatched spans fall
    back to ``mode``:
      - "bigram": overlapping character bigrams (standard CJK IR baseline;
        a single leftover char becomes a unigram)
      - "char": one token per character
    Non-CJK spans (latin words, digits) tokenize by whitespace with the
    preprocessor applied, so mixed-script corpora work end-to-end.
    """

    def __init__(self, user_dictionary: Optional[Sequence[str]] = None,
                 mode: str = "bigram", preprocessor=None):
        if mode not in ("bigram", "char"):
            raise ValueError(f"mode must be 'bigram' or 'char', got {mode!r}")
        self.mode = mode
        self.preprocessor = preprocessor or CommonPreprocessor()
        self.dictionary = set(user_dictionary or ())
        self._max_word = max((len(w) for w in self.dictionary), default=0)

    def _segment_cjk(self, run: str) -> List[str]:
        out: List[str] = []
        i, n = 0, len(run)
        pending_start = 0

        def flush_fallback(start: int, end: int) -> None:
            span = run[start:end]
            if not span:
                return
            if self.mode == "char" or len(span) == 1:
                out.extend(span)
            else:
                out.extend(span[j:j + 2] for j in range(len(span) - 1))

        while i < n:
            match = None
            if self.dictionary:
                for L in range(min(self._max_word, n - i), 0, -1):
                    if run[i:i + L] in self.dictionary:
                        match = run[i:i + L]
                        break
            if match:
                flush_fallback(pending_start, i)
                out.append(match)
                i += len(match)
                pending_start = i
            else:
                i += 1
        flush_fallback(pending_start, n)
        return out

    def tokenize(self, sentence: str) -> List[str]:
        tokens: List[str] = []
        buf: List[str] = []  # non-CJK accumulator

        def flush_non_cjk() -> None:
            if buf:
                for t in "".join(buf).split():
                    t = self.preprocessor.pre_process(t) if self.preprocessor else t
                    if t:
                        tokens.append(t)
                buf.clear()

        i = 0
        while i < len(sentence):
            if _is_cjk(sentence[i]):
                flush_non_cjk()
                j = i
                while j < len(sentence) and _is_cjk(sentence[j]):
                    j += 1
                tokens.extend(self._segment_cjk(sentence[i:j]))
                i = j
            else:
                buf.append(sentence[i])
                i += 1
        flush_non_cjk()
        return tokens


#: name → factory constructor (the reference configures TokenizerFactory
#: by class name; this registry is the same seam without reflection)
_TOKENIZER_FACTORIES: Dict[str, Callable[..., object]] = {}


def register_tokenizer_factory(name: str, ctor: Callable[..., object]) -> None:
    _TOKENIZER_FACTORIES[name.lower()] = ctor


def get_tokenizer_factory(name: str, **kwargs):
    """Build a registered tokenizer factory by name
    ('default', 'cjk', 'chinese', 'japanese', 'korean', ...)."""
    key = name.lower()
    if key not in _TOKENIZER_FACTORIES:
        raise ValueError(f"unknown tokenizer factory {name!r} "
                         f"(known: {sorted(_TOKENIZER_FACTORIES)})")
    return _TOKENIZER_FACTORIES[key](**kwargs)


register_tokenizer_factory("default", DefaultTokenizerFactory)
register_tokenizer_factory("cjk", CJKTokenizerFactory)
# the language-specific names share the CJK segmenter; a real lexicon
# arrives via user_dictionary (the vendored-dictionary seam)
register_tokenizer_factory("chinese", CJKTokenizerFactory)
register_tokenizer_factory("japanese", CJKTokenizerFactory)
register_tokenizer_factory("korean", CJKTokenizerFactory)


class LineSentenceIterator:
    """Sentence-per-line corpus iterator (reference BasicLineIterator)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterable[str]:
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class CollectionSentenceIterator:
    def __init__(self, sentences: List[str]):
        self.sentences = sentences

    def __iter__(self):
        return iter(self.sentences)


class AggregatingSentenceIterator:
    """Chain several sentence iterators (reference
    AggregatingSentenceIterator), with an optional per-sentence
    preprocessor (reference SentencePreProcessor)."""

    def __init__(self, *iterators, preprocessor: Optional[Callable[[str], str]] = None):
        self.iterators = list(iterators)
        self.preprocessor = preprocessor

    def __iter__(self):
        for it in self.iterators:
            for s in it:
                yield self.preprocessor(s) if self.preprocessor else s
