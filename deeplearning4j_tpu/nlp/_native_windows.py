"""ctypes binding for the C++ skip-gram window generator
(native/w2v_window.cpp) — same build-on-first-use scheme as
datasets/native_loader.py; falls back to the numpy pipeline when g++ is
unavailable.  Pair semantics match the numpy path (position-major
centers, ascending context offsets, per-center dynamic window,
sentence-bounded); only the dynamic-window RNG stream differs
(splitmix64 vs numpy PCG64) — both deterministic per seed.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils.native_build import build_and_load

_LIB = None
_LIB_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "w2v_window.cpp")


def load_window_lib() -> Optional[ctypes.CDLL]:
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB or None
        lib = build_and_load(_SRC, "libdl4jtpu_w2v.so")
        if lib is None:
            _LIB = False
            return None
        lib.dl4j_sg_windows.restype = ctypes.c_int64
        lib.dl4j_sg_windows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        _LIB = lib
        return lib


def sg_windows(tokens: np.ndarray, sids: np.ndarray, window: int,
               seed: int) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(centers, targets, center_positions) for the block, or None when the
    native library is unavailable (caller falls back to numpy)."""
    if window < 1:  # the C++ modulo would SIGFPE — fail in Python instead
        raise ValueError(f"window must be >= 1, got {window}")
    lib = load_window_lib()
    if lib is None:
        return None
    n = len(tokens)
    cap = n * 2 * window
    t = np.ascontiguousarray(tokens, np.int32)
    s = np.ascontiguousarray(sids, np.int32)
    centers = np.empty(cap, np.int32)
    targets = np.empty(cap, np.int32)
    pos = np.empty(cap, np.int64)
    k = lib.dl4j_sg_windows(
        t.ctypes.data, s.ctypes.data, n, window, np.uint64(seed),
        centers.ctypes.data, targets.ctypes.data, pos.ctypes.data)
    return centers[:k], targets[:k], pos[:k]
