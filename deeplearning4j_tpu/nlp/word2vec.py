"""Word2Vec — skip-gram / CBOW with negative sampling or hierarchical
softmax, TPU-native formulation.

Parity target: reference models/word2vec/Word2Vec.java (builder surface:
layerSize, windowSize, minWordFrequency, negativeSample, learningRate,
minLearningRate, subsampling, epochs) with SkipGram.java:224 iterateSample /
CBOW.java math.  The reference's Hogwild threads + JNI batched aggregates
(SequenceVectors.java:292,1126) become: host-side window + negative
sampling (numpy), device-side jit step applying the classic sparse updates
via scatter-add — update cost ∝ batch, not vocab.

Layering matches the reference: ``Word2Vec extends SequenceVectors`` — the
training engine and the jit-compiled update steps live in
nlp/sequencevectors.py; this class adds tokenization and word2vec's
defaults (min frequency 5, subsampling 1e-3).
"""

from __future__ import annotations

from typing import Iterable, List

from .sequencevectors import SequenceVectors

# re-exported for backward compatibility (tests/benchmarks import from here)
from .sequencevectors import (  # noqa: F401
    _cbow_chunk,
    _cbow_neg_step,
    _occurrence_scale,
    _sg_chunk,
    _sg_hs_step,
    _sg_neg_step,
)


class Word2Vec(SequenceVectors):
    """Builder-style Word2Vec (reference Word2Vec.Builder surface)."""

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 5,
                 negative: int = 5,
                 hierarchic_softmax: bool = False,
                 cbow: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 subsampling: float = 1e-3,
                 epochs: int = 1,
                 batch_size: int = 2048,
                 seed: int = 12345,
                 tokenizer_factory=None):
        from .tokenization import DefaultTokenizerFactory, get_tokenizer_factory

        super().__init__(
            layer_size=layer_size,
            window=window,
            min_word_frequency=min_word_frequency,
            negative=negative,
            hierarchic_softmax=hierarchic_softmax,
            cbow=cbow,
            learning_rate=learning_rate,
            min_learning_rate=min_learning_rate,
            subsampling=subsampling,
            epochs=epochs,
            batch_size=batch_size,
            seed=seed)
        if isinstance(tokenizer_factory, str):
            # registry names: 'default', 'cjk', 'chinese', 'japanese', ...
            tokenizer_factory = get_tokenizer_factory(tokenizer_factory)
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()

    def _tokenize_corpus(self, sentences: Iterable[str]) -> List[List[str]]:
        return [self.tokenizer.tokenize(s) for s in sentences]

    def fit(self, sentences: Iterable[str]) -> "Word2Vec":
        return self.fit_sequences(self._tokenize_corpus(sentences))
