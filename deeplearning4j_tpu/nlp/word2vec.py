"""Word2Vec — skip-gram / CBOW with negative sampling or hierarchical
softmax, TPU-native formulation.

Parity target: reference models/word2vec/Word2Vec.java (builder surface:
layerSize, windowSize, minWordFrequency, negativeSample, learningRate,
minLearningRate, subsampling, epochs) with SkipGram.java:224 iterateSample /
CBOW.java math.  The reference's Hogwild threads + JNI batched aggregates
(SequenceVectors.java:292,1126) become: host-side window + negative
sampling (numpy), device-side jit step applying the classic sparse updates
via scatter-add — update cost ∝ batch, not vocab.

Gradient math is the standard word2vec closed form (manual, not autodiff —
autodiff's dense [V,D] cotangents would waste HBM bandwidth on big vocabs).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tokenization import DefaultTokenizerFactory
from .vocab import Huffman, VocabCache, build_vocab

logger = logging.getLogger("deeplearning4j_tpu")


def _occurrence_scale(indices: jnp.ndarray, vocab_size: int,
                      weights: jnp.ndarray) -> jnp.ndarray:
    """weights/count(row) per entry: rows hit k times in one batch receive
    the AVERAGE of their k updates, not the sum.  A batch applies updates
    against stale table values, so summing k near-identical updates
    multiplies the effective lr by k and diverges on small vocabs; averaging
    recovers sequential-SGD magnitude (the Hogwild path's implicit behavior).

    `weights` is 1.0 for genuine entries and 0.0 for padding, so pad slots
    (which alias index 0 — the most frequent word) neither receive updates
    nor dilute the occurrence counts of real entries."""
    counts = jnp.zeros((vocab_size,), jnp.float32).at[indices].add(weights)
    return weights / jnp.maximum(counts[indices], 1.0)


@partial(jax.jit, donate_argnums=(0, 1))
def _sg_neg_step(syn0, syn1, centers, contexts, negatives, valid, lr):
    """Skip-gram negative-sampling sparse update.

    centers [B], contexts [B], negatives [B,K], valid [B] (0 = pad row).
    Classic updates (Mikolov 2013):
        for target t with label l:  g = (l - σ(v·u_t)) * lr
        v      += Σ g * u_t ;  u_t += g * v
    """
    v = syn0[centers]                         # [B,D]
    targets = jnp.concatenate([contexts[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]                         # [B,1+K,D]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u))
    g = (labels - score) * lr * valid[:, None]  # [B,1+K]
    dv = jnp.einsum("bk,bkd->bd", g, u)
    du = g[..., None] * v[:, None, :]         # [B,1+K,D]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1 = syn1.at[flat_t].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    return syn0, syn1


def _cbow_chunk(syn0, syn1, context_windows, window_mask, targets_pos,
                negatives, lr):
    """One CBOW negative-sampling micro-chunk: input = mean of context
    vectors; the full output-side gradient is added to EVERY context word,
    matching reference CBOW.java:104-209 (neu1e accumulated once, applied
    undivided per word).  Pad rows have an all-zero window_mask and
    contribute nothing."""
    ctx = syn0[context_windows]               # [B,W,D]
    m = window_mask[..., None]
    valid = (jnp.sum(window_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    denom = jnp.maximum(jnp.sum(window_mask, axis=1, keepdims=True), 1.0)
    h = jnp.sum(ctx * m, axis=1) / denom      # [B,D]
    targets = jnp.concatenate([targets_pos[:, None], negatives], axis=1)
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u))
    g = (labels - score) * lr * valid[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, u)       # full neu1e per context word
    du = g[..., None] * h[:, None, :]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    syn1 = syn1.at[flat_t].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    dctx = jnp.broadcast_to(dh[:, None, :], ctx.shape) * m
    flat_c = context_windows.reshape(-1)
    flat_cw = window_mask.reshape(-1)
    syn0 = syn0.at[flat_c].add(
        dctx.reshape(-1, dctx.shape[-1])
        * _occurrence_scale(flat_c, syn0.shape[0], flat_cw)[:, None])
    return syn0, syn1


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _cbow_neg_step(syn0, syn1, context_windows, window_mask, targets_pos,
                   negatives, lr, chunks=1):
    """CBOW step: lax.scan over `chunks` micro-chunks, each re-reading the
    freshly updated tables.  CBOW emits one row per center word (~2·window
    fewer rows than skip-gram), so whole-batch averaging starves it of
    effective sequential steps on small vocabs; chunked application restores
    the reference's sequential-SGD semantics while keeping batched matmuls."""
    if chunks <= 1:
        return _cbow_chunk(syn0, syn1, context_windows, window_mask,
                           targets_pos, negatives, lr)

    def body(tables, args):
        s0, s1 = tables
        c, m, t, n = args
        return _cbow_chunk(s0, s1, c, m, t, n, lr), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1),
        (split(context_windows), split(window_mask), split(targets_pos),
         split(negatives)))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def _sg_hs_step(syn0, syn1hs, centers, points, codes, code_mask, lr):
    """Skip-gram hierarchical softmax: walk the Huffman path
    (reference SkipGram iterateSample hierarchic-softmax branch).
    points/codes [B,L] padded, code_mask [B,L] (all-zero row = pad)."""
    v = syn0[centers]                          # [B,D]
    u = syn1hs[points]                         # [B,L,D]
    valid = (jnp.sum(code_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    score = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", v, u))
    # label = 1 - code (word2vec convention)
    g = ((1.0 - codes) - score) * lr * code_mask
    dv = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * v[:, None, :]
    flat_p = points.reshape(-1)
    flat_pw = code_mask.reshape(-1)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1hs = syn1hs.at[flat_p].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_p, syn1hs.shape[0], flat_pw)[:, None])
    return syn0, syn1hs


class Word2Vec:
    """Builder-style Word2Vec (reference Word2Vec.Builder surface)."""

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 5,
                 negative: int = 5,
                 hierarchic_softmax: bool = False,
                 cbow: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 subsampling: float = 1e-3,
                 epochs: int = 1,
                 batch_size: int = 2048,
                 seed: int = 12345,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.hs = hierarchic_softmax
        self.cbow = cbow
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.subsampling = subsampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------

    def _tokenize_corpus(self, sentences: Iterable[str]) -> List[List[str]]:
        return [self.tokenizer.tokenize(s) for s in sentences]

    def fit(self, sentences: Iterable[str]) -> "Word2Vec":
        corpus = self._tokenize_corpus(sentences)
        self.vocab = build_vocab(corpus, self.min_word_frequency)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — lower min_word_frequency?")
        rng = np.random.default_rng(self.seed)
        V, D = len(self.vocab), self.layer_size
        # word2vec init: syn0 ~ U(-0.5/D, 0.5/D), output tables zero
        syn0 = jnp.asarray(((rng.random((V, D)) - 0.5) / D).astype(np.float32))
        syn1 = jnp.zeros((V, D), jnp.float32)

        idx_corpus = [np.asarray([self.vocab.index_of(t) for t in s if t in self.vocab],
                                 np.int32)
                      for s in corpus]
        idx_corpus = [s for s in idx_corpus if len(s) > 1]
        unigram = self.vocab.unigram_table()
        counts = np.asarray([w.count for w in self.vocab.words], np.float64)
        total = counts.sum()
        keep_prob = np.ones(V)
        if self.subsampling > 0:
            f = counts / total
            keep_prob = np.minimum(1.0, np.sqrt(self.subsampling / f)
                                   + self.subsampling / f)

        huffman = None
        max_code = 0
        if self.hs:
            huffman = Huffman(self.vocab)
            max_code = max(huffman.max_code_length(), 1)

        total_words = sum(len(s) for s in idx_corpus) * self.epochs
        words_done = 0

        def current_lr():
            frac = words_done / max(total_words, 1)
            return max(self.min_lr, self.lr * (1.0 - frac))

        pairs_c: List[int] = []
        pairs_t: List[int] = []
        cbow_ctx: List[np.ndarray] = []

        def flush():
            nonlocal syn0, syn1, pairs_c, pairs_t, cbow_ctx
            if not pairs_c:
                return
            n = len(pairs_c)
            # pad to the fixed batch shape so XLA compiles once; pad rows are
            # masked out via `valid` (they never alias word 0's updates)
            pad = self.batch_size - n
            centers = np.asarray(pairs_c + [0] * pad, np.int32)
            targets = np.asarray(pairs_t + [0] * pad, np.int32)
            valid = np.zeros(self.batch_size, np.float32)
            valid[:n] = 1.0
            lr_j = jnp.asarray(current_lr(), jnp.float32)
            if self.hs:
                L = max_code
                pts = np.zeros((self.batch_size, L), np.int32)
                cds = np.zeros((self.batch_size, L), np.float32)
                msk = np.zeros((self.batch_size, L), np.float32)  # 0 rows for pad
                for i in range(n):
                    w = self.vocab.words[targets[i]]
                    l = min(len(w.points), L)
                    pts[i, :l] = w.points[:l]
                    cds[i, :l] = w.codes[:l]
                    msk[i, :l] = 1.0
                syn0, syn1 = _sg_hs_step(syn0, syn1, jnp.asarray(centers),
                                         jnp.asarray(pts), jnp.asarray(cds),
                                         jnp.asarray(msk), lr_j)
            elif self.cbow:
                W = 2 * self.window
                ctx = np.zeros((self.batch_size, W), np.int32)
                msk = np.zeros((self.batch_size, W), np.float32)  # 0 rows for pad
                for i, c in enumerate(cbow_ctx):
                    l = min(len(c), W)
                    ctx[i, :l] = c[:l]
                    msk[i, :l] = 1.0
                negs = rng.choice(len(unigram), size=(self.batch_size, self.negative),
                                  p=unigram).astype(np.int32)
                chunks = max(1, self.batch_size // 32)
                while self.batch_size % chunks:   # nearest divisor ≤ B/32
                    chunks -= 1
                syn0, syn1 = _cbow_neg_step(syn0, syn1, jnp.asarray(ctx),
                                            jnp.asarray(msk),
                                            jnp.asarray(targets), jnp.asarray(negs),
                                            lr_j, chunks)
            else:
                negs = rng.choice(len(unigram), size=(self.batch_size, self.negative),
                                  p=unigram).astype(np.int32)
                syn0, syn1 = _sg_neg_step(syn0, syn1, jnp.asarray(centers),
                                          jnp.asarray(targets), jnp.asarray(negs),
                                          jnp.asarray(valid), lr_j)
            pairs_c, pairs_t, cbow_ctx = [], [], []

        for _ in range(self.epochs):
            for sent in idx_corpus:
                if self.subsampling > 0:
                    keep = rng.random(len(sent)) < keep_prob[sent]
                    sent = sent[keep]
                words_done += len(sent)
                for pos, center in enumerate(sent):
                    b = rng.integers(1, self.window + 1)  # dynamic window
                    lo, hi = max(0, pos - b), min(len(sent), pos + b + 1)
                    context = [int(sent[j]) for j in range(lo, hi) if j != pos]
                    if not context:
                        continue
                    if self.cbow:
                        pairs_c.append(int(center))
                        pairs_t.append(int(center))
                        cbow_ctx.append(np.asarray(context, np.int32))
                        if len(pairs_c) >= self.batch_size:
                            flush()
                    else:
                        for t in context:
                            pairs_c.append(int(center))
                            pairs_t.append(t)
                            if len(pairs_c) >= self.batch_size:
                                flush()
        flush()
        self.syn0 = np.asarray(syn0)
        self._norms = None
        return self

    # ------------------------------------------------------------------
    # lookup API (reference WordVectors interface)
    # ------------------------------------------------------------------

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def _normed(self) -> np.ndarray:
        if self._norms is None:
            n = np.linalg.norm(self.syn0, axis=1, keepdims=True)
            self._norms = self.syn0 / np.maximum(n, 1e-9)
        return self._norms

    def similarity(self, a: str, b: str) -> float:
        va, vb = self._normed()[self.vocab.index_of(a)], self._normed()[self.vocab.index_of(b)]
        return float(va @ vb)

    def words_nearest(self, word: str, top_n: int = 10) -> List[str]:
        normed = self._normed()
        sims = normed @ normed[self.vocab.index_of(word)]
        sims[self.vocab.index_of(word)] = -np.inf
        idx = np.argpartition(-sims, min(top_n, len(sims) - 1))[:top_n]
        idx = idx[np.argsort(-sims[idx])]
        return [self.vocab.word_for(int(i)) for i in idx]
