"""Embedding serialization — word2vec C formats.

Parity target: reference models/embeddings/loader/WordVectorSerializer.java
(2,824 LoC): read/write the original word2vec C text and binary formats so
vectors interoperate with gensim/word2vec tooling, plus the framework's own
loader that reconstructs a queryable table.
"""

from __future__ import annotations

import struct
from typing import Dict, Tuple

import numpy as np


def write_word_vectors(model_or_pairs, path: str, binary: bool = False) -> None:
    """Write 'V D\\n' header + one word per line (text) or packed floats
    (binary), the word2vec C convention (WordVectorSerializer.writeWordVectors)."""
    if hasattr(model_or_pairs, "vocab"):
        vocab = model_or_pairs.vocab
        vectors = model_or_pairs.syn0
        items = [(vocab.word_for(i), vectors[i]) for i in range(len(vocab))]
    else:
        items = list(model_or_pairs.items())
    if not items:
        raise ValueError("no vectors to write")
    d = len(items[0][1])
    if binary:
        with open(path, "wb") as f:
            f.write(f"{len(items)} {d}\n".encode())
            for word, vec in items:
                f.write(word.encode("utf-8") + b" ")
                f.write(np.asarray(vec, np.float32).tobytes())
                f.write(b"\n")
    else:
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{len(items)} {d}\n")
            for word, vec in items:
                f.write(word + " " + " ".join(f"{x:.6f}" for x in vec) + "\n")


def read_word_vectors(path: str, binary: bool = False) -> Dict[str, np.ndarray]:
    """Inverse of write_word_vectors (WordVectorSerializer.loadTxtVectors /
    readBinaryModel)."""
    out: Dict[str, np.ndarray] = {}
    if binary:
        with open(path, "rb") as f:
            header = f.readline().split()
            n, d = int(header[0]), int(header[1])
            for _ in range(n):
                word = bytearray()
                while True:
                    ch = f.read(1)
                    if ch == b" ":
                        break
                    if ch == b"":
                        raise ValueError("truncated binary vectors file")
                    word.extend(ch)
                vec = np.frombuffer(f.read(4 * d), dtype=np.float32)
                out[word.decode("utf-8")] = np.array(vec)
                nl = f.read(1)
                if nl not in (b"\n", b""):
                    f.seek(-1, 1)
        return out
    with open(path, "r", encoding="utf-8") as f:
        header = f.readline().split()
        n, d = int(header[0]), int(header[1])
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < d + 1:
                continue
            out[parts[0]] = np.asarray([float(x) for x in parts[1:d + 1]], np.float32)
    return out


def load_static_model(path: str, binary: bool = False):
    """Saved vectors → a queryable read-only WordVectors table with the
    full lookup API (similarity / words_nearest / words_nearest_vector) —
    the reference's WordVectorSerializer.loadStaticModel: embeddings
    usable for inference without the trainer."""
    from .sequencevectors import WordVectorsBase
    from .vocab import VocabCache

    pairs = read_word_vectors(path, binary=binary)
    if not pairs:
        raise ValueError(f"{path}: no vectors found")

    model = WordVectorsBase()
    vocab = VocabCache()
    rows = []
    for word, vec in pairs.items():
        vocab.add(word, 1)
        rows.append(np.asarray(vec, np.float32))
    model.vocab = vocab
    model.syn0 = np.stack(rows)
    model._norms = None
    return model
