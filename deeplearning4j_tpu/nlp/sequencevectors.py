"""SequenceVectors — the generic embedding trainer the word2vec family
shares.

Parity target: reference models/sequencevectors/SequenceVectors.java:49,192
(the abstract trainer over SequenceElements that Word2Vec, ParagraphVectors
and DeepWalk all extend) + elements-learning/sequence-learning algorithm
split (embeddings/learning/impl/elements/*, sequence/*).

TPU inversion (same as nlp/word2vec.py): the reference's Hogwild thread
pool over sentences becomes host-side window/negative sampling feeding
jit-compiled batched scatter-add updates.  The *sequence label* concept
(DL4J's `trainSequencesRepresentation` — doc vectors, node vectors) is
implemented by extending the input table with one row per label:
  rows [0, V)      — element (word) vectors
  rows [V, V+L)    — sequence-label vectors (paragraph/doc ids)
Labels participate as *inputs* only (syn0 side); prediction targets are
always elements, so the output tables/negative sampling never see them.

Training modes map to the reference's learning algorithms:
  - elements + skip-gram  = SkipGram.java
  - elements + cbow       = CBOW.java
  - labels   + dbow       = DBOW.java  (label predicts each window word)
  - labels   + dm         = DM.java    (label joins the averaged context)
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, Hashable, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import Huffman, VocabCache, build_vocab

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# jit-compiled sparse update steps (shared by Word2Vec / ParagraphVectors /
# DeepWalk; see module docstring for the batching-vs-sequential rationale)
# ---------------------------------------------------------------------------

def _build_alias_table(p: np.ndarray):
    """Walker alias-method tables for an arbitrary discrete distribution:
    returns (prob [n], alias [n]); sample with  i ~ U{0..n-1}, u ~ U[0,1),
    result = i if u < prob[i] else alias[i].  O(n) build, O(1) draws."""
    n = len(p)
    prob = np.asarray(p, np.float64) * n
    alias = np.zeros(n, np.int64)
    small = list(np.where(prob < 1.0)[0])
    large = list(np.where(prob >= 1.0)[0])
    while small and large:
        s, l = small.pop(), large.pop()
        alias[s] = l
        prob[l] -= 1.0 - prob[s]
        (small if prob[l] < 1.0 else large).append(l)
    # leftovers are 1.0 up to float error
    for i in small + large:
        prob[i] = 1.0
    return prob, alias


@partial(jax.jit, static_argnums=(3, 4))
def _device_negs(base_key, counters, tables, n_neg: int, rows: int):
    """Sample negatives ON DEVICE via the alias tables: one (rows, n_neg)
    draw per batch counter, keyed by fold_in(base, counter) so the draw for
    batch i is a pure function of i — identical whether batches dispatch
    alone or stacked, and at any mesh size.  Keeps ~20 bytes/pair of
    negative indices off the (slow, ~50MB/s on a tunnelled TPU) host→device
    link."""
    nprob, nalias = tables
    vocab = nprob.shape[0]

    def one(i):
        k1, k2 = jax.random.split(jax.random.fold_in(base_key, i))
        idx = jax.random.randint(k1, (rows, n_neg), 0, vocab)
        u = jax.random.uniform(k2, (rows, n_neg))
        return jnp.where(u < nprob[idx], idx, nalias[idx]).astype(jnp.int32)

    return jax.vmap(one)(counters).reshape(-1, n_neg)


@partial(jax.jit, static_argnums=(0,))
def _valid_mask(n: int, n_valid):
    """[n] float mask with the first n_valid entries 1 — built on device so
    the padded-tail mask costs a scalar upload, not n floats."""
    return (jnp.arange(n) < n_valid).astype(jnp.float32)


def _occurrence_scale(indices: jnp.ndarray, vocab_size: int,
                      weights: jnp.ndarray) -> jnp.ndarray:
    """weights/count(row) per entry: rows hit k times in one batch receive
    the AVERAGE of their k updates, not the sum.  A batch applies updates
    against stale table values, so summing k near-identical updates
    multiplies the effective lr by k and diverges on small vocabs; averaging
    recovers sequential-SGD magnitude (the Hogwild path's implicit behavior).

    `weights` is 1.0 for genuine entries and 0.0 for padding, so pad slots
    (which alias index 0 — the most frequent word) neither receive updates
    nor dilute the occurrence counts of real entries."""
    counts = jnp.zeros((vocab_size,), jnp.float32).at[indices].add(weights)
    return weights / jnp.maximum(counts[indices], 1.0)


def _sg_pair_grads(syn0, syn1, centers, contexts, negatives, valid, lr):
    """Shared skip-gram pair gradients (Mikolov 2013):
        for target t with label l:  g = (l − σ(v·u_t)) · lr
    → (dv [B,D], du_flat [B·(1+K),D], flat_t, flat_tw).  Single source of
    truth for the local step (_sg_chunk) and the mesh-sharded step
    (nlp/distributed.py)."""
    v = syn0[centers]                         # [B,D]
    targets = jnp.concatenate([contexts[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]                         # [B,1+K,D]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u))
    g = (labels - score) * lr * valid[:, None]  # [B,1+K]
    dv = jnp.einsum("bk,bkd->bd", g, u)
    du = g[..., None] * v[:, None, :]         # [B,1+K,D]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    return dv, du.reshape(-1, du.shape[-1]), flat_t, flat_tw


def _sg_chunk(syn0, syn1, centers, contexts, negatives, valid, lr):
    """Skip-gram negative-sampling sparse update (one micro-chunk).
    centers [B], contexts [B], negatives [B,K], valid [B] (0 = pad row)."""
    dv, du_flat, flat_t, flat_tw = _sg_pair_grads(
        syn0, syn1, centers, contexts, negatives, valid, lr)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1 = syn1.at[flat_t].add(
        du_flat * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    return syn0, syn1


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _sg_neg_step(syn0, syn1, centers, contexts, negatives, valid, lr, chunks=1):
    """Skip-gram step; ``chunks`` > 1 scans micro-chunks that each re-read
    the freshly updated tables.  Two users of the chunked path:
      - DBOW label training: a label's pairs are CONSECUTIVE — one batch
        would average them into a single effective update
        (see _occurrence_scale), so micro-chunks restore sequentiality.
      - dispatch amortization: the host stacks several LR-annotated batches
        into one device call (``lr`` may be a [chunks] vector, one entry per
        micro-chunk) — on a remote-TPU link this cuts per-step dispatch
        latency by the stacking factor while keeping per-batch semantics
        bit-identical to separate calls.
    """
    if chunks <= 1:
        return _sg_chunk(syn0, syn1, centers, contexts, negatives, valid, lr)

    lr_vec = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(lr, syn0.dtype), (-1,)), (chunks,))

    def body(tables, args):
        s0, s1 = tables
        c, t, n, v, l = args
        return _sg_chunk(s0, s1, c, t, n, v, l), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1),
        (split(centers), split(contexts), split(negatives), split(valid),
         lr_vec))
    return syn0, syn1


def _cbow_chunk(syn0, syn1, context_windows, window_mask, targets_pos,
                negatives, lr):
    """One CBOW negative-sampling micro-chunk: input = mean of context
    vectors; the full output-side gradient is added to EVERY context word,
    matching reference CBOW.java:104-209 (neu1e accumulated once, applied
    undivided per word).  Pad rows have an all-zero window_mask and
    contribute nothing."""
    ctx = syn0[context_windows]               # [B,W,D]
    m = window_mask[..., None]
    valid = (jnp.sum(window_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    denom = jnp.maximum(jnp.sum(window_mask, axis=1, keepdims=True), 1.0)
    h = jnp.sum(ctx * m, axis=1) / denom      # [B,D]
    targets = jnp.concatenate([targets_pos[:, None], negatives], axis=1)
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u))
    g = (labels - score) * lr * valid[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, u)       # full neu1e per context word
    du = g[..., None] * h[:, None, :]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    syn1 = syn1.at[flat_t].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    dctx = jnp.broadcast_to(dh[:, None, :], ctx.shape) * m
    flat_c = context_windows.reshape(-1)
    flat_cw = window_mask.reshape(-1)
    syn0 = syn0.at[flat_c].add(
        dctx.reshape(-1, dctx.shape[-1])
        * _occurrence_scale(flat_c, syn0.shape[0], flat_cw)[:, None])
    return syn0, syn1


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _cbow_neg_step(syn0, syn1, context_windows, window_mask, targets_pos,
                   negatives, lr, chunks=1):
    """CBOW step: lax.scan over `chunks` micro-chunks, each re-reading the
    freshly updated tables.  CBOW emits one row per center word (~2·window
    fewer rows than skip-gram), so whole-batch averaging starves it of
    effective sequential steps on small vocabs; chunked application restores
    the reference's sequential-SGD semantics while keeping batched matmuls."""
    if chunks <= 1:
        return _cbow_chunk(syn0, syn1, context_windows, window_mask,
                           targets_pos, negatives, lr)

    lr_vec = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(lr, syn0.dtype), (-1,)), (chunks,))

    def body(tables, args):
        s0, s1 = tables
        c, m, t, n, l = args
        return _cbow_chunk(s0, s1, c, m, t, n, l), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1),
        (split(context_windows), split(window_mask), split(targets_pos),
         split(negatives), lr_vec))
    return syn0, syn1


def _sg_hs_chunk(syn0, syn1hs, centers, points, codes, code_mask, lr):
    """Skip-gram hierarchical softmax (one micro-chunk): walk the Huffman
    path (reference SkipGram iterateSample hierarchic-softmax branch).
    points/codes [B,L] padded, code_mask [B,L] (all-zero row = pad)."""
    v = syn0[centers]                          # [B,D]
    u = syn1hs[points]                         # [B,L,D]
    valid = (jnp.sum(code_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    score = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", v, u))
    # label = 1 - code (word2vec convention)
    g = ((1.0 - codes) - score) * lr * code_mask
    dv = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * v[:, None, :]
    flat_p = points.reshape(-1)
    flat_pw = code_mask.reshape(-1)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1hs = syn1hs.at[flat_p].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_p, syn1hs.shape[0], flat_pw)[:, None])
    return syn0, syn1hs


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _sg_hs_step(syn0, syn1hs, centers, points, codes, code_mask, lr, chunks=1):
    """HS step with the same micro-chunk scan as _sg_neg_step — required for
    DBOW labels, whose consecutive pairs would otherwise average into one
    effective update per batch."""
    if chunks <= 1:
        return _sg_hs_chunk(syn0, syn1hs, centers, points, codes, code_mask, lr)

    lr_vec = jnp.broadcast_to(
        jnp.reshape(jnp.asarray(lr, syn0.dtype), (-1,)), (chunks,))

    def body(tables, args):
        s0, s1 = tables
        c, p, cd, m, l = args
        return _sg_hs_chunk(s0, s1, c, p, cd, m, l), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1hs), _ = jax.lax.scan(
        body, (syn0, syn1hs),
        (split(centers), split(points), split(codes), split(code_mask),
         lr_vec))
    return syn0, syn1hs


class _LazyTable:
    """Descriptor: a device-resident table exported to a MUTABLE host
    np.ndarray on first access (pending/host attribute pair).  One
    implementation for syn0/syn1 (and any future table)."""

    def __init__(self, pending_attr: str, host_attr: str,
                 clears_norms: bool = False):
        self._pending = pending_attr
        self._host = host_attr
        self._clears_norms = clears_norms

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        host = getattr(obj, self._host, None)
        pending = getattr(obj, self._pending, None)
        if host is None and pending is not None:
            # np.array (not asarray): jax device views are read-only; the
            # contract is a mutable host table
            host = np.array(pending)
            setattr(obj, self._host, host)
            setattr(obj, self._pending, None)
        return host

    def __set__(self, obj, value) -> None:
        setattr(obj, self._pending, None)
        if value is None:
            host = None
        else:
            # jax device arrays view as read-only numpy; the contract is a
            # genuine MUTABLE host table, so copy when the view isn't
            # writable (writable arrays pass through uncopied)
            host = np.asarray(value)
            if not host.flags.writeable:
                host = np.array(host)
        setattr(obj, self._host, host)
        if self._clears_norms:
            obj._norms = None


class WordVectorsBase:
    """Lookup API shared by every embedding model (reference
    models/embeddings/wordvectors/WordVectors.java interface)."""

    vocab: Optional[VocabCache]
    syn0: Optional[np.ndarray]

    def has_word(self, word) -> bool:
        return self.vocab is not None and word in self.vocab

    def word_vector(self, word) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def _normed(self) -> np.ndarray:
        # restrict to element rows [0, V): label-trained models carry extra
        # label rows in syn0 that must not leak into word-space searches
        if getattr(self, "_norms", None) is None:
            table = self.syn0[:len(self.vocab)]
            n = np.linalg.norm(table, axis=1, keepdims=True)
            self._norms = table / np.maximum(n, 1e-9)
        return self._norms

    def similarity(self, a, b) -> float:
        na = self._normed()[self.vocab.index_of(a)]
        nb = self._normed()[self.vocab.index_of(b)]
        return float(na @ nb)

    def words_nearest(self, word, top_n: int = 10) -> List:
        normed = self._normed()
        sims = normed @ normed[self.vocab.index_of(word)]
        sims[self.vocab.index_of(word)] = -np.inf
        idx = np.argpartition(-sims, min(top_n, len(sims) - 1))[:top_n]
        idx = idx[np.argsort(-sims[idx])]
        return [self.vocab.word_for(int(i)) for i in idx]

    def words_nearest_vector(self, vec: np.ndarray, top_n: int = 10) -> List:
        normed = self._normed()
        v = np.asarray(vec, np.float32)
        v = v / max(np.linalg.norm(v), 1e-9)
        sims = normed @ v
        idx = np.argpartition(-sims, min(top_n, len(sims) - 1))[:top_n]
        idx = idx[np.argsort(-sims[idx])]
        return [self.vocab.word_for(int(i)) for i in idx]


@partial(jax.jit, donate_argnums=(0,))
def _infer_sg_step(vec, syn1, targets, negatives, valid, lr):
    """One inference pass for a single frozen-table vector (reference
    ParagraphVectors.inferVector:391 — same update, tables locked).
    vec [D], targets [B], negatives [B,K], valid [B]."""
    t = jnp.concatenate([targets[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(t.shape, vec.dtype).at[:, 0].set(1.0)
    u = syn1[t]                                                 # [B,1+K,D]
    score = jax.nn.sigmoid(jnp.einsum("d,bkd->bk", vec, u))
    g = (labels - score) * lr * valid[:, None]
    return vec + jnp.einsum("bk,bkd->d", g, u) / jnp.maximum(jnp.sum(valid), 1.0)


@partial(jax.jit, donate_argnums=(0,))
def _infer_dm_step(vec, syn0, syn1, ctx, ctx_mask, targets, negatives, valid, lr):
    """DM inference: h = mean(frozen context vectors ++ vec); only ``vec``
    moves.  ctx [B,W] indices into syn0, ctx_mask [B,W]."""
    c = syn0[ctx] * ctx_mask[..., None]                     # [B,W,D]
    denom = jnp.sum(ctx_mask, axis=1, keepdims=True) + 1.0  # + the doc vector
    h = (jnp.sum(c, axis=1) + vec[None, :]) / denom         # [B,D]
    t = jnp.concatenate([targets[:, None], negatives], axis=1)
    labels = jnp.zeros(t.shape, vec.dtype).at[:, 0].set(1.0)
    u = syn1[t]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u))
    g = (labels - score) * lr * valid[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, u) / denom             # ∂h/∂vec = 1/denom
    return vec + jnp.sum(dh, axis=0) / jnp.maximum(jnp.sum(valid), 1.0)


class SequenceVectors(WordVectorsBase):
    """Generic embedding trainer over element sequences (reference
    SequenceVectors.Builder surface: layerSize, windowSize, negative,
    useHierarchicSoftmax, learningRate, epochs, trainElementsRepresentation,
    trainSequencesRepresentation)."""

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 1,
                 negative: int = 5,
                 hierarchic_softmax: bool = False,
                 cbow: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 subsampling: float = 0.0,
                 epochs: int = 1,
                 batch_size: int = 2048,
                 seed: int = 12345,
                 train_elements: bool = True,
                 train_sequences: bool = False,
                 dm: bool = True):
        self.layer_size = layer_size
        if window < 1:
            # validated up front: the numpy path would raise from
            # rng.integers(1, 1) and the C++ generator would SIGFPE on a
            # modulo-by-zero — neither is an acceptable failure mode
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.hs = hierarchic_softmax
        self.cbow = cbow
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.subsampling = subsampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        if cbow and hierarchic_softmax:
            raise NotImplementedError(
                "CBOW + hierarchical softmax is not implemented — use CBOW "
                "with negative sampling, or skip-gram with HS")
        if train_sequences and dm and hierarchic_softmax:
            raise NotImplementedError(
                "DM + hierarchical softmax is not implemented — use DM with "
                "negative sampling, or DBOW with HS")
        self.train_elements = train_elements
        self.train_sequences = train_sequences
        self.dm = dm
        self.vocab: Optional[VocabCache] = None
        self._syn0_pending = None   # device arrays awaiting lazy readback
        self._syn0_host: Optional[np.ndarray] = None
        self._syn1_pending = None
        self._syn1_host: Optional[np.ndarray] = None
        self.label_index: Dict[Hashable, int] = {}
        self._norms = None
        # batches stacked per device dispatch (amortizes remote-TPU dispatch
        # latency; per-batch LR/semantics preserved via the per-chunk lr
        # vector in _sg_neg_step).  Subclasses whose step can't scan multiple
        # batches (DistributedWord2Vec) set this to 1.
        self._device_batches = 16

    # ------------------------------------------------------------------

    # Tables stay device-resident after fit (the framework-wide
    # convention — MLN/CG params never eagerly export either) and
    # materialize as genuine MUTABLE host arrays on first access: each
    # eager readback costs ~200ms of tunnel latency on the bench chip.
    syn0 = _LazyTable("_syn0_pending", "_syn0_host", clears_norms=True)
    syn1 = _LazyTable("_syn1_pending", "_syn1_host")

    def _sg_step(self, syn0, syn1, centers, contexts, negatives, valid, lr,
                 chunks=1):
        """Skip-gram update seam — DistributedWord2Vec overrides this with
        the mesh-sharded step (nlp/distributed.py)."""
        return _sg_neg_step(syn0, syn1, centers, contexts, negatives, valid,
                            lr, chunks)

    def fit_sequences(self,
                      sequences: Sequence[Sequence[Hashable]],
                      labels: Optional[Sequence[Hashable]] = None) -> "SequenceVectors":
        """Train on pre-tokenized element sequences.  ``labels``, when given,
        attaches one trainable label row per sequence (DM/DBOW per ``dm``)."""
        if labels is not None and len(labels) != len(sequences):
            raise ValueError(f"{len(labels)} labels for {len(sequences)} sequences")
        if labels is None and self.train_sequences:
            raise ValueError("train_sequences=True requires labels")
        if labels is not None and not self.train_sequences:
            raise ValueError("labels were given but train_sequences=False — "
                             "label vectors would never be trained")

        self.vocab = build_vocab(sequences, self.min_word_frequency)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — lower min_word_frequency?")
        V, D = len(self.vocab), self.layer_size
        self.label_index = {}
        if labels is not None:
            for lb in labels:
                if lb not in self.label_index:
                    self.label_index[lb] = V + len(self.label_index)
        L = len(self.label_index)

        rng = np.random.default_rng(self.seed)
        # word2vec init: inputs ~ U(-0.5/D, 0.5/D), output tables zero
        syn0 = jnp.asarray(((rng.random((V + L, D)) - 0.5) / D).astype(np.float32))
        syn1 = jnp.zeros((V + L, D), jnp.float32)

        idx_corpus: List[np.ndarray] = []
        seq_label_idx: List[Optional[int]] = []
        index_get = self.vocab.get  # one hash probe per token
        for si, s in enumerate(sequences):
            ids = np.asarray([vw.index for vw in map(index_get, s)
                              if vw is not None], np.int32)
            if len(ids) < 1:
                continue
            idx_corpus.append(ids)
            seq_label_idx.append(self.label_index[labels[si]] if labels is not None
                                 else None)
        if labels is not None:
            trained = {l for l in seq_label_idx if l is not None}
            untrained = [lb for lb, li in self.label_index.items()
                         if li not in trained]
            if untrained:
                logger.warning(
                    "%d label(s) have no in-vocabulary tokens and keep their "
                    "random init (e.g. %s) — their vectors are meaningless",
                    len(untrained), untrained[:3])

        unigram = self.vocab.unigram_table()
        counts = np.asarray([w.count for w in self.vocab.words], np.float64)
        total = counts.sum()
        keep_prob = np.ones(V)
        if self.subsampling > 0:
            f = counts / total
            keep_prob = np.minimum(1.0, np.sqrt(self.subsampling / f)
                                   + self.subsampling / f)

        huffman = None
        max_code = 0
        if self.hs:
            huffman = Huffman(self.vocab)
            max_code = max(huffman.max_code_length(), 1)

        total_words = sum(len(s) for s in idx_corpus) * self.epochs
        words_done = 0

        def lr_at(done) -> float:
            """Linear LR decay at a words-done watermark (word2vec.c)."""
            frac = float(done) / max(total_words, 1)
            return max(self.min_lr, self.lr * (1.0 - frac))

        def current_lr():
            return lr_at(words_done)

        def chunk_divisor(target_chunk: int) -> int:
            """Largest divisor of batch_size giving chunks of ≥ target size."""
            chunks = max(1, self.batch_size // target_chunk)
            while self.batch_size % chunks:
                chunks -= 1
            return chunks

        # DBOW emits a label's pairs CONSECUTIVELY — scan micro-chunks so
        # they apply (near-)sequentially instead of being averaged away by
        # _occurrence_scale (see _sg_neg_step docstring)
        dbow = self.train_sequences and not self.dm

        # Vectorized window generation.  The reference walks sentences one
        # token at a time per Hogwild thread (SkipGram.java:271-283); a
        # Python translation of that loop caps the host at ~20K words/s with
        # the TPU idle.  The walk is data-parallel: every center's candidate
        # contexts live at fixed offsets [-W..-1, 1..W]; masking |off| ≤ b
        # (the per-center dynamic window draw) and the sentence bounds yields
        # the exact sequential pair stream — position-major, offsets in
        # increasing j — in one numpy pass per sentence.
        offs = np.concatenate([np.arange(-self.window, 0),
                               np.arange(1, self.window + 1)])

        if self.hs:
            # vocab-indexed Huffman tables so flush() can gather per-target
            # paths instead of looping: row i = word i's (points, codes, len)
            hs_pts = np.zeros((V, max_code), np.int32)
            hs_cds = np.zeros((V, max_code), np.float32)
            hs_msk = np.zeros((V, max_code), np.float32)
            for i, w in enumerate(self.vocab.words):
                l = min(len(w.points), max_code)
                hs_pts[i, :l] = w.points[:l]
                hs_cds[i, :l] = w.codes[:l]
                hs_msk[i, :l] = 1.0

        # negative sampling: Walker alias table over unigram^0.75 — O(1)
        # per draw (the reference's 10⁸-slot UnigramTable without the
        # memory).  Tables live on device; draws happen there too
        # (_device_negs), keyed by global batch index so results are
        # invariant to _device_batches and mesh size (the
        # DistributedWord2Vec parity tests rely on this).
        a_prob, a_alias = _build_alias_table(unigram)
        neg_tables = (jnp.asarray(a_prob.astype(np.float32)),
                      jnp.asarray(a_alias.astype(np.int32)))
        neg_key = jax.random.PRNGKey(np.random.SeedSequence(
            [self.seed, 977]).generate_state(1)[0])
        batch_counter = 0  # global batch index across the whole fit

        def flush_multi(centers, targets, n_valid, lrs,
                        ctx=None, cmask=None) -> None:
            """One device dispatch covering ``len(lrs)`` stacked batches
            (arrays are [n_b·batch_size] row-major; the first ``n_valid``
            rows are genuine, the rest masked padding).  Per-batch LR rides
            the scan's per-chunk lr vector, so semantics match n_b separate
            flushes exactly."""
            nonlocal syn0, syn1, batch_counter
            n_b = len(lrs)
            inner = chunk_divisor(32) if (ctx is not None and not self.hs) \
                else (chunk_divisor(16) if dbow else 1)
            chunks = n_b * inner
            if chunks > 1:
                lr_arg = jnp.asarray(
                    np.repeat(np.asarray(lrs, np.float32), inner))
            else:
                lr_arg = jnp.asarray(lrs[0], jnp.float32)
            if self.hs:
                valid = np.zeros(len(centers), np.float32)
                valid[:n_valid] = 1.0
                pts = hs_pts[targets]
                cds = hs_cds[targets]
                msk = hs_msk[targets] * valid[:, None]
                syn0, syn1 = _sg_hs_step(syn0, syn1, jnp.asarray(centers),
                                         jnp.asarray(pts), jnp.asarray(cds),
                                         jnp.asarray(msk), lr_arg, chunks)
                return
            counters = jnp.asarray(
                np.arange(batch_counter, batch_counter + n_b, dtype=np.uint32))
            batch_counter += n_b
            negs = _device_negs(neg_key, counters, neg_tables,
                                self.negative, self.batch_size)
            if ctx is not None:
                syn0, syn1 = _cbow_neg_step(syn0, syn1, jnp.asarray(ctx),
                                            jnp.asarray(cmask),
                                            jnp.asarray(targets),
                                            negs, lr_arg, chunks)
            else:
                # one stacked upload: per-array puts pay ~10ms latency each
                # on a tunnelled TPU, and bandwidth there is ~50MB/s
                ct = jnp.asarray(np.stack([centers, targets]))
                valid = _valid_mask(len(centers), jnp.asarray(n_valid, jnp.int32))
                syn0, syn1 = self._sg_step(syn0, syn1, ct[0], ct[1],
                                           negs, valid, lr_arg, chunks)

        # pending pair chunks, drained ``k_super`` exact batches per device
        # call; batch boundaries and per-batch LR match the sequential
        # stream (pend_lr snapshots current_lr at each boundary crossing)
        pend_c: List[np.ndarray] = []
        pend_t: List[np.ndarray] = []
        pend_x: List[np.ndarray] = []
        pend_m: List[np.ndarray] = []
        pend_lr: List[float] = []
        pend_n = 0
        k_super = max(1, int(self._device_batches))

        def drain(final: bool = False) -> None:
            nonlocal pend_c, pend_t, pend_x, pend_m, pend_lr, pend_n
            bs = self.batch_size
            if pend_n == 0 or (pend_n < bs * k_super and not final):
                return
            c = np.concatenate(pend_c)
            t = np.concatenate(pend_t)
            x = np.concatenate(pend_x) if pend_x else None
            m = np.concatenate(pend_m) if pend_m else None
            lrs = list(pend_lr)
            orig_len = len(c)  # genuine pairs, before tail padding
            tail = orig_len - (orig_len // bs) * bs
            if final and tail:
                # pad the tail to a full masked batch and take it too
                pad = bs - tail
                c = np.concatenate([c, np.zeros(pad, np.int32)])
                t = np.concatenate([t, np.zeros(pad, np.int32)])
                if x is not None:
                    x = np.concatenate([x, np.zeros((pad, x.shape[1]), np.int32)])
                    m = np.concatenate([m, np.zeros((pad, m.shape[1]), np.float32)])
                lrs.append(current_lr())
            n_batches = len(c) // bs if final else (len(c) // bs) // k_super * k_super
            for g in range(0, n_batches, k_super):
                gb = min(k_super, n_batches - g)
                s = slice(g * bs, (g + gb) * bs)
                n_valid = max(0, min(orig_len - g * bs, gb * bs))
                flush_multi(c[s], t[s], n_valid, lrs[g:g + gb],
                            None if x is None else x[s],
                            None if m is None else m[s])
            rem = slice(n_batches * bs, len(c) if not final else n_batches * bs)
            kept = c[rem]
            pend_c = [kept] if len(kept) else []
            pend_t = [t[rem]] if len(kept) else []
            pend_x = [x[rem]] if (x is not None and len(kept)) else []
            pend_m = [m[rem]] if (m is not None and len(kept)) else []
            pend_lr = lrs[n_batches:]
            pend_n = len(kept)

        def push(c, t, x=None, m=None, wdone=None) -> None:
            """Queue a pair chunk.  ``wdone`` (per-pair words-done counts)
            drives per-batch LR at word granularity; without it the batch
            takes the LR of the current words_done watermark."""
            nonlocal pend_n
            if len(c) == 0:
                return
            start = pend_n
            pend_c.append(np.ascontiguousarray(c, np.int32))
            pend_t.append(np.ascontiguousarray(t, np.int32))
            if x is not None:
                pend_x.append(np.ascontiguousarray(x, np.int32))
                pend_m.append(np.ascontiguousarray(m, np.float32))
            pend_n += len(c)
            while len(pend_lr) < pend_n // self.batch_size:
                bidx = (len(pend_lr) + 1) * self.batch_size - 1 - start
                pend_lr.append(lr_at(wdone[bidx]) if wdone is not None
                               else current_lr())
            drain()

        use_cbow_path = self.cbow or (labels is not None and self.dm
                                      and self.train_sequences)

        # Flatten the corpus once: per-sentence numpy calls cost ~40µs each
        # in fixed overhead, which at DL4J-corpus scale re-creates the host
        # bottleneck the vectorization exists to remove.  Window masks use
        # sentence-id equality, so one pass handles every sentence at once;
        # blocks are cut at sentence boundaries to bound peak memory.
        flat_lens = np.asarray([len(s) for s in idx_corpus], np.int64)
        flat_tokens = (np.concatenate(idx_corpus) if idx_corpus
                       else np.zeros(0, np.int32))
        flat_sids = np.repeat(np.arange(len(idx_corpus)), flat_lens)
        has_labels = labels is not None
        flat_labs = (np.repeat(np.asarray(
            [(-1 if l is None else l) for l in seq_label_idx], np.int32),
            flat_lens) if has_labels else None)
        BLOCK = 1 << 18  # ~256K tokens → ≤ ~1.5M pairs in flight

        for epoch_i in range(self.epochs):
            if self.subsampling > 0:
                # dedicated per-epoch stream (NOT the shared `rng`): the
                # native window generator skips the numpy dynamic-window
                # draws, so tying subsampling to `rng` would give epoch≥2
                # different masks depending on whether g++ was available —
                # an environment-dependent reproducibility gap.  Only the
                # window-RNG stream itself may differ between the two
                # paths (documented in _native_windows.py).
                sub_rng = np.random.default_rng(np.random.SeedSequence(
                    [self.seed, 77003, epoch_i]))
                keepm = sub_rng.random(len(flat_tokens)) < keep_prob[flat_tokens]
                toks = flat_tokens[keepm]
                sids = flat_sids[keepm]
                labs = flat_labs[keepm] if has_labels else None
            else:
                toks, sids, labs = flat_tokens, flat_sids, flat_labs
            N = len(toks)
            startpos = 0
            while startpos < N:
                cap = min(startpos + BLOCK, N)
                if cap < N:
                    # cut before the sentence containing position cap
                    cut = int(np.searchsorted(sids, sids[cap - 1], side="left"))
                    if cut <= startpos:  # single sentence > BLOCK: take it whole
                        cut = int(np.searchsorted(sids, sids[cap - 1], side="right"))
                else:
                    cut = N
                bt = toks[startpos:cut]
                bsid = sids[startpos:cut]
                blab = None if labs is None else labs[startpos:cut]
                Lb = len(bt)
                if (not use_cbow_path and not has_labels
                        and self.train_elements):
                    # plain skip-gram: the C++ pair generator replaces the
                    # whole [Lb,2W] numpy mask pipeline (VERDICT r3 #7 —
                    # window generation in the native loader; ~10× this
                    # loop's host cost, GIL-free)
                    from ._native_windows import sg_windows
                    # epoch in the seed: every pass re-draws its dynamic
                    # windows (the numpy path's persistent-rng behavior)
                    native = sg_windows(
                        bt, bsid, self.window,
                        np.random.SeedSequence(
                            [self.seed, 31337, epoch_i,
                             startpos]).generate_state(1)[0])
                    if native is not None:
                        ncen, ntgt, npos = native
                        push(ncen, ntgt,
                             wdone=words_done + startpos + 1 + npos)
                        startpos = cut
                        continue
                b = rng.integers(1, self.window + 1, size=Lb)  # dynamic window
                j = np.arange(Lb)[:, None] + offs[None, :]     # [Lb, 2W]
                jc = np.clip(j, 0, Lb - 1)
                inwin = ((j >= 0) & (j < Lb)
                         & (np.abs(offs)[None, :] <= b[:, None])
                         & (bsid[jc] == bsid[:, None]))
                ctx_ids = bt[jc]                               # [Lb, 2W]
                # words-done after each center (for word-granular LR decay)
                wd = words_done + startpos + 1 + np.arange(Lb, dtype=np.int64)
                if use_cbow_path:
                    if has_labels and self.dm:
                        # DM: the label joins every averaged window
                        ctx_full = np.concatenate([ctx_ids, blab[:, None]], 1)
                        mask_full = np.concatenate(
                            [inwin, (blab >= 0)[:, None]], 1)
                    else:
                        ctx_full, mask_full = ctx_ids, inwin
                    rows = mask_full.any(axis=1)  # skip empty-context centers
                    push(bt[rows], bt[rows], ctx_full[rows],
                         mask_full[rows].astype(np.float32), wd[rows])
                else:
                    cen = np.broadcast_to(bt[:, None], inwin.shape)
                    tgt = ctx_ids
                    vmat = inwin if self.train_elements else np.zeros_like(inwin)
                    if has_labels and not self.dm:
                        # DBOW: after each center's window pairs, the label
                        # predicts the center (DBOW.java pair order)
                        cen = np.concatenate([cen, blab[:, None]], axis=1)
                        tgt = np.concatenate([tgt, bt[:, None]], axis=1)
                        vmat = np.concatenate(
                            [vmat, (blab >= 0)[:, None]], axis=1)
                    keep_m = vmat.ravel()
                    wexp = np.broadcast_to(wd[:, None], vmat.shape).ravel()[keep_m]
                    push(cen.ravel()[keep_m], tgt.ravel()[keep_m], wdone=wexp)
                startpos = cut
            words_done += N
        drain(final=True)
        # both tables defer their device→host readback to first access
        # (the syn0/syn1 properties); training is complete device-side
        self._syn0_pending = syn0
        self._syn0_host = None
        self._syn1_pending = syn1
        self._syn1_host = None
        self._norms = None
        return self

    # ------------------------------------------------------------------
    # label (sequence) vectors
    # ------------------------------------------------------------------

    def sequence_vector(self, label: Hashable) -> np.ndarray:
        """Trained vector of a sequence label (doc vector)."""
        return self.syn0[self.label_index[label]]

    def infer_vector(self, tokens: Sequence[Hashable], steps: int = 200,
                     learning_rate: Optional[float] = None,
                     seed: int = 0) -> np.ndarray:
        """Train a fresh vector for an unseen sequence with all tables
        frozen (reference ParagraphVectors.inferVector:391)."""
        if self.syn0 is None:
            raise ValueError("fit before infer")
        if self.hs:
            raise NotImplementedError(
                "infer_vector for hierarchical-softmax models is not "
                "implemented (syn1 holds Huffman inner-node vectors, not word "
                "outputs) — train with negative sampling to use inference")
        ids = np.asarray([self.vocab.index_of(t) for t in tokens
                          if t in self.vocab], np.int32)
        if len(ids) == 0:
            raise ValueError("no known tokens in sequence")
        rng = np.random.default_rng(seed)
        D = self.layer_size
        lr = np.float32(learning_rate if learning_rate is not None else self.lr)
        vec = jnp.asarray(((rng.random(D) - 0.5) / D).astype(np.float32))
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self.syn1)
        unigram = self.vocab.unigram_table()
        # pad to a power-of-two bucket: one XLA compile per bucket, not per
        # distinct document length
        B = 1 << max(4, int(np.ceil(np.log2(len(ids)))))
        pad = B - len(ids)
        targets = jnp.asarray(np.concatenate([ids, np.zeros(pad, np.int32)]))
        valid = jnp.asarray(np.concatenate([np.ones(len(ids), np.float32),
                                            np.zeros(pad, np.float32)]))
        if self.dm:
            W = 2 * self.window
            ctx = np.zeros((B, W), np.int32)
            msk = np.zeros((B, W), np.float32)
            for pos in range(len(ids)):
                lo, hi = max(0, pos - self.window), min(len(ids), pos + self.window + 1)
                c = [int(ids[j]) for j in range(lo, hi) if j != pos]
                l = min(len(c), W)
                ctx[pos, :l] = c[:l]
                msk[pos, :l] = 1.0
            ctx_j, msk_j = jnp.asarray(ctx), jnp.asarray(msk)
        for it in range(steps):
            cur = jnp.asarray(max(float(lr) * (1.0 - it / steps), self.min_lr),
                              jnp.float32)
            negs = jnp.asarray(rng.choice(len(unigram), size=(B, self.negative),
                                          p=unigram).astype(np.int32))
            if self.dm:
                vec = _infer_dm_step(vec, syn0, syn1, ctx_j, msk_j, targets,
                                     negs, valid, cur)
            else:
                vec = _infer_sg_step(vec, syn1, targets, negs, valid, cur)
        return np.asarray(vec)


class ParagraphVectors(SequenceVectors):
    """Doc2vec (reference models/paragraphvectors/ParagraphVectors.java):
    PV-DM (``dm=True``, default — DL4J's default DM learner) or PV-DBOW
    (``dm=False``).  Labels are document ids; ``infer_vector`` embeds unseen
    documents against the frozen tables."""

    def __init__(self, dm: bool = True, train_elements: bool = True,
                 **kwargs):
        # word vectors co-train by default (reference trainElementsVectors
        # defaults true); pure doc→word DBOW collapses doc vectors to a
        # near-rank-1 subspace because syn1 gets no word-word structure
        kwargs.setdefault("min_word_frequency", 1)
        super().__init__(train_elements=train_elements, train_sequences=True,
                         dm=dm, **kwargs)
        self.tokenizer = None

    def fit(self, documents: Iterable, labels: Optional[Sequence[Hashable]] = None
            ) -> "ParagraphVectors":
        """Train on documents: strings (tokenized on whitespace via the
        default tokenizer) or pre-tokenized lists."""
        from .tokenization import DefaultTokenizerFactory
        docs = list(documents)
        if docs and isinstance(docs[0], str):
            tk = DefaultTokenizerFactory()
            seqs = [tk.tokenize(d) for d in docs]
        else:
            seqs = [list(d) for d in docs]
        if labels is None:
            labels = [f"DOC_{i}" for i in range(len(seqs))]
        return self.fit_sequences(seqs, labels=labels)

    # doc-flavored aliases (reference API names)
    def doc_vector(self, label: Hashable) -> np.ndarray:
        return self.sequence_vector(label)

    def infer(self, text) -> np.ndarray:
        if isinstance(text, str):
            from .tokenization import DefaultTokenizerFactory
            text = DefaultTokenizerFactory().tokenize(text)
        return self.infer_vector(text)

    def nearest_labels(self, vec_or_text, top_n: int = 5) -> List:
        """Labels whose doc vectors are closest to a vector / inferred text
        (reference predictSeveral / nearestLabels)."""
        if isinstance(vec_or_text, (str, list)):
            v = self.infer(vec_or_text)
        else:
            v = np.asarray(vec_or_text, np.float32)
        v = v / max(np.linalg.norm(v), 1e-9)
        out = []
        for lb, idx in self.label_index.items():
            dv = self.syn0[idx]
            dv = dv / max(np.linalg.norm(dv), 1e-9)
            out.append((float(dv @ v), lb))
        out.sort(reverse=True)
        return [lb for _, lb in out[:top_n]]
