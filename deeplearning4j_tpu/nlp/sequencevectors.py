"""SequenceVectors — the generic embedding trainer the word2vec family
shares.

Parity target: reference models/sequencevectors/SequenceVectors.java:49,192
(the abstract trainer over SequenceElements that Word2Vec, ParagraphVectors
and DeepWalk all extend) + elements-learning/sequence-learning algorithm
split (embeddings/learning/impl/elements/*, sequence/*).

TPU inversion (same as nlp/word2vec.py): the reference's Hogwild thread
pool over sentences becomes host-side window/negative sampling feeding
jit-compiled batched scatter-add updates.  The *sequence label* concept
(DL4J's `trainSequencesRepresentation` — doc vectors, node vectors) is
implemented by extending the input table with one row per label:
  rows [0, V)      — element (word) vectors
  rows [V, V+L)    — sequence-label vectors (paragraph/doc ids)
Labels participate as *inputs* only (syn0 side); prediction targets are
always elements, so the output tables/negative sampling never see them.

Training modes map to the reference's learning algorithms:
  - elements + skip-gram  = SkipGram.java
  - elements + cbow       = CBOW.java
  - labels   + dbow       = DBOW.java  (label predicts each window word)
  - labels   + dm         = DM.java    (label joins the averaged context)
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, Hashable, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import Huffman, VocabCache, build_vocab

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# jit-compiled sparse update steps (shared by Word2Vec / ParagraphVectors /
# DeepWalk; see module docstring for the batching-vs-sequential rationale)
# ---------------------------------------------------------------------------

def _occurrence_scale(indices: jnp.ndarray, vocab_size: int,
                      weights: jnp.ndarray) -> jnp.ndarray:
    """weights/count(row) per entry: rows hit k times in one batch receive
    the AVERAGE of their k updates, not the sum.  A batch applies updates
    against stale table values, so summing k near-identical updates
    multiplies the effective lr by k and diverges on small vocabs; averaging
    recovers sequential-SGD magnitude (the Hogwild path's implicit behavior).

    `weights` is 1.0 for genuine entries and 0.0 for padding, so pad slots
    (which alias index 0 — the most frequent word) neither receive updates
    nor dilute the occurrence counts of real entries."""
    counts = jnp.zeros((vocab_size,), jnp.float32).at[indices].add(weights)
    return weights / jnp.maximum(counts[indices], 1.0)


def _sg_pair_grads(syn0, syn1, centers, contexts, negatives, valid, lr):
    """Shared skip-gram pair gradients (Mikolov 2013):
        for target t with label l:  g = (l − σ(v·u_t)) · lr
    → (dv [B,D], du_flat [B·(1+K),D], flat_t, flat_tw).  Single source of
    truth for the local step (_sg_chunk) and the mesh-sharded step
    (nlp/distributed.py)."""
    v = syn0[centers]                         # [B,D]
    targets = jnp.concatenate([contexts[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]                         # [B,1+K,D]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u))
    g = (labels - score) * lr * valid[:, None]  # [B,1+K]
    dv = jnp.einsum("bk,bkd->bd", g, u)
    du = g[..., None] * v[:, None, :]         # [B,1+K,D]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    return dv, du.reshape(-1, du.shape[-1]), flat_t, flat_tw


def _sg_chunk(syn0, syn1, centers, contexts, negatives, valid, lr):
    """Skip-gram negative-sampling sparse update (one micro-chunk).
    centers [B], contexts [B], negatives [B,K], valid [B] (0 = pad row)."""
    dv, du_flat, flat_t, flat_tw = _sg_pair_grads(
        syn0, syn1, centers, contexts, negatives, valid, lr)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1 = syn1.at[flat_t].add(
        du_flat * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    return syn0, syn1


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _sg_neg_step(syn0, syn1, centers, contexts, negatives, valid, lr, chunks=1):
    """Skip-gram step; ``chunks`` > 1 scans micro-chunks that each re-read
    the freshly updated tables.  Word2Vec uses chunks=1 (rows recur across
    batches anyway); sequence-label training (DBOW) needs chunking because a
    label's pairs are CONSECUTIVE — one batch would average them into a
    single effective update (see _occurrence_scale)."""
    if chunks <= 1:
        return _sg_chunk(syn0, syn1, centers, contexts, negatives, valid, lr)

    def body(tables, args):
        s0, s1 = tables
        c, t, n, v = args
        return _sg_chunk(s0, s1, c, t, n, v, lr), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1),
        (split(centers), split(contexts), split(negatives), split(valid)))
    return syn0, syn1


def _cbow_chunk(syn0, syn1, context_windows, window_mask, targets_pos,
                negatives, lr):
    """One CBOW negative-sampling micro-chunk: input = mean of context
    vectors; the full output-side gradient is added to EVERY context word,
    matching reference CBOW.java:104-209 (neu1e accumulated once, applied
    undivided per word).  Pad rows have an all-zero window_mask and
    contribute nothing."""
    ctx = syn0[context_windows]               # [B,W,D]
    m = window_mask[..., None]
    valid = (jnp.sum(window_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    denom = jnp.maximum(jnp.sum(window_mask, axis=1, keepdims=True), 1.0)
    h = jnp.sum(ctx * m, axis=1) / denom      # [B,D]
    targets = jnp.concatenate([targets_pos[:, None], negatives], axis=1)
    labels = jnp.zeros(targets.shape, syn0.dtype).at[:, 0].set(1.0)
    u = syn1[targets]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u))
    g = (labels - score) * lr * valid[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, u)       # full neu1e per context word
    du = g[..., None] * h[:, None, :]
    flat_t = targets.reshape(-1)
    flat_tw = jnp.broadcast_to(valid[:, None], targets.shape).reshape(-1)
    syn1 = syn1.at[flat_t].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_t, syn1.shape[0], flat_tw)[:, None])
    dctx = jnp.broadcast_to(dh[:, None, :], ctx.shape) * m
    flat_c = context_windows.reshape(-1)
    flat_cw = window_mask.reshape(-1)
    syn0 = syn0.at[flat_c].add(
        dctx.reshape(-1, dctx.shape[-1])
        * _occurrence_scale(flat_c, syn0.shape[0], flat_cw)[:, None])
    return syn0, syn1


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _cbow_neg_step(syn0, syn1, context_windows, window_mask, targets_pos,
                   negatives, lr, chunks=1):
    """CBOW step: lax.scan over `chunks` micro-chunks, each re-reading the
    freshly updated tables.  CBOW emits one row per center word (~2·window
    fewer rows than skip-gram), so whole-batch averaging starves it of
    effective sequential steps on small vocabs; chunked application restores
    the reference's sequential-SGD semantics while keeping batched matmuls."""
    if chunks <= 1:
        return _cbow_chunk(syn0, syn1, context_windows, window_mask,
                           targets_pos, negatives, lr)

    def body(tables, args):
        s0, s1 = tables
        c, m, t, n = args
        return _cbow_chunk(s0, s1, c, m, t, n, lr), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1),
        (split(context_windows), split(window_mask), split(targets_pos),
         split(negatives)))
    return syn0, syn1


def _sg_hs_chunk(syn0, syn1hs, centers, points, codes, code_mask, lr):
    """Skip-gram hierarchical softmax (one micro-chunk): walk the Huffman
    path (reference SkipGram iterateSample hierarchic-softmax branch).
    points/codes [B,L] padded, code_mask [B,L] (all-zero row = pad)."""
    v = syn0[centers]                          # [B,D]
    u = syn1hs[points]                         # [B,L,D]
    valid = (jnp.sum(code_mask, axis=1) > 0).astype(syn0.dtype)  # [B]
    score = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", v, u))
    # label = 1 - code (word2vec convention)
    g = ((1.0 - codes) - score) * lr * code_mask
    dv = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * v[:, None, :]
    flat_p = points.reshape(-1)
    flat_pw = code_mask.reshape(-1)
    syn0 = syn0.at[centers].add(
        dv * _occurrence_scale(centers, syn0.shape[0], valid)[:, None])
    syn1hs = syn1hs.at[flat_p].add(
        du.reshape(-1, du.shape[-1])
        * _occurrence_scale(flat_p, syn1hs.shape[0], flat_pw)[:, None])
    return syn0, syn1hs


@partial(jax.jit, static_argnums=(7,), donate_argnums=(0, 1))
def _sg_hs_step(syn0, syn1hs, centers, points, codes, code_mask, lr, chunks=1):
    """HS step with the same micro-chunk scan as _sg_neg_step — required for
    DBOW labels, whose consecutive pairs would otherwise average into one
    effective update per batch."""
    if chunks <= 1:
        return _sg_hs_chunk(syn0, syn1hs, centers, points, codes, code_mask, lr)

    def body(tables, args):
        s0, s1 = tables
        c, p, cd, m = args
        return _sg_hs_chunk(s0, s1, c, p, cd, m, lr), None

    def split(a):
        return a.reshape(chunks, a.shape[0] // chunks, *a.shape[1:])

    (syn0, syn1hs), _ = jax.lax.scan(
        body, (syn0, syn1hs),
        (split(centers), split(points), split(codes), split(code_mask)))
    return syn0, syn1hs


class WordVectorsBase:
    """Lookup API shared by every embedding model (reference
    models/embeddings/wordvectors/WordVectors.java interface)."""

    vocab: Optional[VocabCache]
    syn0: Optional[np.ndarray]

    def has_word(self, word) -> bool:
        return self.vocab is not None and word in self.vocab

    def word_vector(self, word) -> np.ndarray:
        return self.syn0[self.vocab.index_of(word)]

    def _normed(self) -> np.ndarray:
        # restrict to element rows [0, V): label-trained models carry extra
        # label rows in syn0 that must not leak into word-space searches
        if getattr(self, "_norms", None) is None:
            table = self.syn0[:len(self.vocab)]
            n = np.linalg.norm(table, axis=1, keepdims=True)
            self._norms = table / np.maximum(n, 1e-9)
        return self._norms

    def similarity(self, a, b) -> float:
        na = self._normed()[self.vocab.index_of(a)]
        nb = self._normed()[self.vocab.index_of(b)]
        return float(na @ nb)

    def words_nearest(self, word, top_n: int = 10) -> List:
        normed = self._normed()
        sims = normed @ normed[self.vocab.index_of(word)]
        sims[self.vocab.index_of(word)] = -np.inf
        idx = np.argpartition(-sims, min(top_n, len(sims) - 1))[:top_n]
        idx = idx[np.argsort(-sims[idx])]
        return [self.vocab.word_for(int(i)) for i in idx]

    def words_nearest_vector(self, vec: np.ndarray, top_n: int = 10) -> List:
        normed = self._normed()
        v = np.asarray(vec, np.float32)
        v = v / max(np.linalg.norm(v), 1e-9)
        sims = normed @ v
        idx = np.argpartition(-sims, min(top_n, len(sims) - 1))[:top_n]
        idx = idx[np.argsort(-sims[idx])]
        return [self.vocab.word_for(int(i)) for i in idx]


@partial(jax.jit, donate_argnums=(0,))
def _infer_sg_step(vec, syn1, targets, negatives, valid, lr):
    """One inference pass for a single frozen-table vector (reference
    ParagraphVectors.inferVector:391 — same update, tables locked).
    vec [D], targets [B], negatives [B,K], valid [B]."""
    t = jnp.concatenate([targets[:, None], negatives], axis=1)  # [B,1+K]
    labels = jnp.zeros(t.shape, vec.dtype).at[:, 0].set(1.0)
    u = syn1[t]                                                 # [B,1+K,D]
    score = jax.nn.sigmoid(jnp.einsum("d,bkd->bk", vec, u))
    g = (labels - score) * lr * valid[:, None]
    return vec + jnp.einsum("bk,bkd->d", g, u) / jnp.maximum(jnp.sum(valid), 1.0)


@partial(jax.jit, donate_argnums=(0,))
def _infer_dm_step(vec, syn0, syn1, ctx, ctx_mask, targets, negatives, valid, lr):
    """DM inference: h = mean(frozen context vectors ++ vec); only ``vec``
    moves.  ctx [B,W] indices into syn0, ctx_mask [B,W]."""
    c = syn0[ctx] * ctx_mask[..., None]                     # [B,W,D]
    denom = jnp.sum(ctx_mask, axis=1, keepdims=True) + 1.0  # + the doc vector
    h = (jnp.sum(c, axis=1) + vec[None, :]) / denom         # [B,D]
    t = jnp.concatenate([targets[:, None], negatives], axis=1)
    labels = jnp.zeros(t.shape, vec.dtype).at[:, 0].set(1.0)
    u = syn1[t]
    score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u))
    g = (labels - score) * lr * valid[:, None]
    dh = jnp.einsum("bk,bkd->bd", g, u) / denom             # ∂h/∂vec = 1/denom
    return vec + jnp.sum(dh, axis=0) / jnp.maximum(jnp.sum(valid), 1.0)


class SequenceVectors(WordVectorsBase):
    """Generic embedding trainer over element sequences (reference
    SequenceVectors.Builder surface: layerSize, windowSize, negative,
    useHierarchicSoftmax, learningRate, epochs, trainElementsRepresentation,
    trainSequencesRepresentation)."""

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: int = 1,
                 negative: int = 5,
                 hierarchic_softmax: bool = False,
                 cbow: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 subsampling: float = 0.0,
                 epochs: int = 1,
                 batch_size: int = 2048,
                 seed: int = 12345,
                 train_elements: bool = True,
                 train_sequences: bool = False,
                 dm: bool = True):
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.hs = hierarchic_softmax
        self.cbow = cbow
        self.lr = learning_rate
        self.min_lr = min_learning_rate
        self.subsampling = subsampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        if cbow and hierarchic_softmax:
            raise NotImplementedError(
                "CBOW + hierarchical softmax is not implemented — use CBOW "
                "with negative sampling, or skip-gram with HS")
        if train_sequences and dm and hierarchic_softmax:
            raise NotImplementedError(
                "DM + hierarchical softmax is not implemented — use DM with "
                "negative sampling, or DBOW with HS")
        self.train_elements = train_elements
        self.train_sequences = train_sequences
        self.dm = dm
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None
        self.syn1: Optional[np.ndarray] = None
        self.label_index: Dict[Hashable, int] = {}
        self._norms = None

    # ------------------------------------------------------------------

    def _sg_step(self, syn0, syn1, centers, contexts, negatives, valid, lr,
                 chunks=1):
        """Skip-gram update seam — DistributedWord2Vec overrides this with
        the mesh-sharded step (nlp/distributed.py)."""
        return _sg_neg_step(syn0, syn1, centers, contexts, negatives, valid,
                            lr, chunks)

    def fit_sequences(self,
                      sequences: Sequence[Sequence[Hashable]],
                      labels: Optional[Sequence[Hashable]] = None) -> "SequenceVectors":
        """Train on pre-tokenized element sequences.  ``labels``, when given,
        attaches one trainable label row per sequence (DM/DBOW per ``dm``)."""
        if labels is not None and len(labels) != len(sequences):
            raise ValueError(f"{len(labels)} labels for {len(sequences)} sequences")
        if labels is None and self.train_sequences:
            raise ValueError("train_sequences=True requires labels")
        if labels is not None and not self.train_sequences:
            raise ValueError("labels were given but train_sequences=False — "
                             "label vectors would never be trained")

        self.vocab = build_vocab(sequences, self.min_word_frequency)
        if len(self.vocab) == 0:
            raise ValueError("empty vocabulary — lower min_word_frequency?")
        V, D = len(self.vocab), self.layer_size
        self.label_index = {}
        if labels is not None:
            for lb in labels:
                if lb not in self.label_index:
                    self.label_index[lb] = V + len(self.label_index)
        L = len(self.label_index)

        rng = np.random.default_rng(self.seed)
        # word2vec init: inputs ~ U(-0.5/D, 0.5/D), output tables zero
        syn0 = jnp.asarray(((rng.random((V + L, D)) - 0.5) / D).astype(np.float32))
        syn1 = jnp.zeros((V + L, D), jnp.float32)

        idx_corpus: List[np.ndarray] = []
        seq_label_idx: List[Optional[int]] = []
        for si, s in enumerate(sequences):
            ids = np.asarray([self.vocab.index_of(t) for t in s if t in self.vocab],
                             np.int32)
            if len(ids) < 1:
                continue
            idx_corpus.append(ids)
            seq_label_idx.append(self.label_index[labels[si]] if labels is not None
                                 else None)
        if labels is not None:
            trained = {l for l in seq_label_idx if l is not None}
            untrained = [lb for lb, li in self.label_index.items()
                         if li not in trained]
            if untrained:
                logger.warning(
                    "%d label(s) have no in-vocabulary tokens and keep their "
                    "random init (e.g. %s) — their vectors are meaningless",
                    len(untrained), untrained[:3])

        unigram = self.vocab.unigram_table()
        counts = np.asarray([w.count for w in self.vocab.words], np.float64)
        total = counts.sum()
        keep_prob = np.ones(V)
        if self.subsampling > 0:
            f = counts / total
            keep_prob = np.minimum(1.0, np.sqrt(self.subsampling / f)
                                   + self.subsampling / f)

        huffman = None
        max_code = 0
        if self.hs:
            huffman = Huffman(self.vocab)
            max_code = max(huffman.max_code_length(), 1)

        total_words = sum(len(s) for s in idx_corpus) * self.epochs
        words_done = 0

        def current_lr():
            frac = words_done / max(total_words, 1)
            return max(self.min_lr, self.lr * (1.0 - frac))

        # batched pair buffers (see word2vec.py flush() for the padding rules)
        pairs_c: List[int] = []
        pairs_t: List[int] = []
        cbow_ctx: List[np.ndarray] = []
        # DM window width: contexts + optionally the label slot
        W_ctx = 2 * self.window + (1 if (labels is not None and self.dm) else 0)

        def chunk_divisor(target_chunk: int) -> int:
            """Largest divisor of batch_size giving chunks of ≥ target size."""
            chunks = max(1, self.batch_size // target_chunk)
            while self.batch_size % chunks:
                chunks -= 1
            return chunks

        # DBOW emits a label's pairs CONSECUTIVELY — scan micro-chunks so
        # they apply (near-)sequentially instead of being averaged away by
        # _occurrence_scale (see _sg_neg_step docstring)
        dbow = self.train_sequences and not self.dm

        def flush():
            nonlocal syn0, syn1, pairs_c, pairs_t, cbow_ctx
            if not pairs_c:
                return
            n = len(pairs_c)
            pad = self.batch_size - n
            centers = np.asarray(pairs_c + [0] * pad, np.int32)
            targets = np.asarray(pairs_t + [0] * pad, np.int32)
            valid = np.zeros(self.batch_size, np.float32)
            valid[:n] = 1.0
            lr_j = jnp.asarray(current_lr(), jnp.float32)
            if self.hs:
                Lc = max_code
                pts = np.zeros((self.batch_size, Lc), np.int32)
                cds = np.zeros((self.batch_size, Lc), np.float32)
                msk = np.zeros((self.batch_size, Lc), np.float32)
                for i in range(n):
                    w = self.vocab.words[targets[i]]
                    l = min(len(w.points), Lc)
                    pts[i, :l] = w.points[:l]
                    cds[i, :l] = w.codes[:l]
                    msk[i, :l] = 1.0
                syn0, syn1 = _sg_hs_step(syn0, syn1, jnp.asarray(centers),
                                         jnp.asarray(pts), jnp.asarray(cds),
                                         jnp.asarray(msk), lr_j,
                                         chunk_divisor(16) if dbow else 1)
            elif cbow_ctx:
                ctx = np.zeros((self.batch_size, W_ctx), np.int32)
                msk = np.zeros((self.batch_size, W_ctx), np.float32)
                for i, c in enumerate(cbow_ctx):
                    l = min(len(c), W_ctx)
                    ctx[i, :l] = c[:l]
                    msk[i, :l] = 1.0
                negs = rng.choice(len(unigram), size=(self.batch_size, self.negative),
                                  p=unigram).astype(np.int32)
                syn0, syn1 = _cbow_neg_step(syn0, syn1, jnp.asarray(ctx),
                                            jnp.asarray(msk),
                                            jnp.asarray(targets), jnp.asarray(negs),
                                            lr_j, chunk_divisor(32))
            else:
                negs = rng.choice(len(unigram), size=(self.batch_size, self.negative),
                                  p=unigram).astype(np.int32)
                syn0, syn1 = self._sg_step(syn0, syn1, jnp.asarray(centers),
                                           jnp.asarray(targets), jnp.asarray(negs),
                                           jnp.asarray(valid), lr_j,
                                           chunk_divisor(16) if dbow else 1)
            pairs_c, pairs_t, cbow_ctx = [], [], []

        use_cbow_path = self.cbow or (labels is not None and self.dm
                                      and self.train_sequences)

        for _ in range(self.epochs):
            for sent, lbl in zip(idx_corpus, seq_label_idx):
                if self.subsampling > 0:
                    keep = rng.random(len(sent)) < keep_prob[sent]
                    sent = sent[keep]
                words_done += len(sent)
                for pos, center in enumerate(sent):
                    b = rng.integers(1, self.window + 1)  # dynamic window
                    lo, hi = max(0, pos - b), min(len(sent), pos + b + 1)
                    context = [int(sent[j]) for j in range(lo, hi) if j != pos]
                    if use_cbow_path:
                        ctx = list(context)
                        if lbl is not None and self.train_sequences and self.dm:
                            ctx.append(lbl)  # DM: label joins the window
                        if not ctx:
                            continue
                        pairs_c.append(int(center))
                        pairs_t.append(int(center))
                        cbow_ctx.append(np.asarray(ctx, np.int32))
                        if len(pairs_c) >= self.batch_size:
                            flush()
                    else:
                        if self.train_elements:
                            for t in context:
                                pairs_c.append(int(center))
                                pairs_t.append(t)
                                if len(pairs_c) >= self.batch_size:
                                    flush()
                        if lbl is not None and self.train_sequences and not self.dm:
                            # DBOW: the label predicts each word of the window
                            pairs_c.append(lbl)
                            pairs_t.append(int(center))
                            if len(pairs_c) >= self.batch_size:
                                flush()
        flush()
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        self._norms = None
        return self

    # ------------------------------------------------------------------
    # label (sequence) vectors
    # ------------------------------------------------------------------

    def sequence_vector(self, label: Hashable) -> np.ndarray:
        """Trained vector of a sequence label (doc vector)."""
        return self.syn0[self.label_index[label]]

    def infer_vector(self, tokens: Sequence[Hashable], steps: int = 200,
                     learning_rate: Optional[float] = None,
                     seed: int = 0) -> np.ndarray:
        """Train a fresh vector for an unseen sequence with all tables
        frozen (reference ParagraphVectors.inferVector:391)."""
        if self.syn0 is None:
            raise ValueError("fit before infer")
        if self.hs:
            raise NotImplementedError(
                "infer_vector for hierarchical-softmax models is not "
                "implemented (syn1 holds Huffman inner-node vectors, not word "
                "outputs) — train with negative sampling to use inference")
        ids = np.asarray([self.vocab.index_of(t) for t in tokens
                          if t in self.vocab], np.int32)
        if len(ids) == 0:
            raise ValueError("no known tokens in sequence")
        rng = np.random.default_rng(seed)
        D = self.layer_size
        lr = np.float32(learning_rate if learning_rate is not None else self.lr)
        vec = jnp.asarray(((rng.random(D) - 0.5) / D).astype(np.float32))
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self.syn1)
        unigram = self.vocab.unigram_table()
        # pad to a power-of-two bucket: one XLA compile per bucket, not per
        # distinct document length
        B = 1 << max(4, int(np.ceil(np.log2(len(ids)))))
        pad = B - len(ids)
        targets = jnp.asarray(np.concatenate([ids, np.zeros(pad, np.int32)]))
        valid = jnp.asarray(np.concatenate([np.ones(len(ids), np.float32),
                                            np.zeros(pad, np.float32)]))
        if self.dm:
            W = 2 * self.window
            ctx = np.zeros((B, W), np.int32)
            msk = np.zeros((B, W), np.float32)
            for pos in range(len(ids)):
                lo, hi = max(0, pos - self.window), min(len(ids), pos + self.window + 1)
                c = [int(ids[j]) for j in range(lo, hi) if j != pos]
                l = min(len(c), W)
                ctx[pos, :l] = c[:l]
                msk[pos, :l] = 1.0
            ctx_j, msk_j = jnp.asarray(ctx), jnp.asarray(msk)
        for it in range(steps):
            cur = jnp.asarray(max(float(lr) * (1.0 - it / steps), self.min_lr),
                              jnp.float32)
            negs = jnp.asarray(rng.choice(len(unigram), size=(B, self.negative),
                                          p=unigram).astype(np.int32))
            if self.dm:
                vec = _infer_dm_step(vec, syn0, syn1, ctx_j, msk_j, targets,
                                     negs, valid, cur)
            else:
                vec = _infer_sg_step(vec, syn1, targets, negs, valid, cur)
        return np.asarray(vec)


class ParagraphVectors(SequenceVectors):
    """Doc2vec (reference models/paragraphvectors/ParagraphVectors.java):
    PV-DM (``dm=True``, default — DL4J's default DM learner) or PV-DBOW
    (``dm=False``).  Labels are document ids; ``infer_vector`` embeds unseen
    documents against the frozen tables."""

    def __init__(self, dm: bool = True, train_elements: bool = True,
                 **kwargs):
        # word vectors co-train by default (reference trainElementsVectors
        # defaults true); pure doc→word DBOW collapses doc vectors to a
        # near-rank-1 subspace because syn1 gets no word-word structure
        kwargs.setdefault("min_word_frequency", 1)
        super().__init__(train_elements=train_elements, train_sequences=True,
                         dm=dm, **kwargs)
        self.tokenizer = None

    def fit(self, documents: Iterable, labels: Optional[Sequence[Hashable]] = None
            ) -> "ParagraphVectors":
        """Train on documents: strings (tokenized on whitespace via the
        default tokenizer) or pre-tokenized lists."""
        from .tokenization import DefaultTokenizerFactory
        docs = list(documents)
        if docs and isinstance(docs[0], str):
            tk = DefaultTokenizerFactory()
            seqs = [tk.tokenize(d) for d in docs]
        else:
            seqs = [list(d) for d in docs]
        if labels is None:
            labels = [f"DOC_{i}" for i in range(len(seqs))]
        return self.fit_sequences(seqs, labels=labels)

    # doc-flavored aliases (reference API names)
    def doc_vector(self, label: Hashable) -> np.ndarray:
        return self.sequence_vector(label)

    def infer(self, text) -> np.ndarray:
        if isinstance(text, str):
            from .tokenization import DefaultTokenizerFactory
            text = DefaultTokenizerFactory().tokenize(text)
        return self.infer_vector(text)

    def nearest_labels(self, vec_or_text, top_n: int = 5) -> List:
        """Labels whose doc vectors are closest to a vector / inferred text
        (reference predictSeveral / nearestLabels)."""
        if isinstance(vec_or_text, (str, list)):
            v = self.infer(vec_or_text)
        else:
            v = np.asarray(vec_or_text, np.float32)
        v = v / max(np.linalg.norm(v), 1e-9)
        out = []
        for lb, idx in self.label_index.items():
            dv = self.syn0[idx]
            dv = dv / max(np.linalg.norm(dv), 1e-9)
            out.append((float(dv @ v), lb))
        out.sort(reverse=True)
        return [lb for _, lb in out[:top_n]]
