"""Keras model import (HDF5) — parity with the reference's
deeplearning4j-modelimport module (KerasModelImport.java:41-269)."""

from .keras import (
    KerasModelImport,
    Hdf5Archive,
    import_keras_sequential_model_and_weights,
    import_keras_model_and_weights,
)

__all__ = [
    "KerasModelImport",
    "Hdf5Archive",
    "import_keras_sequential_model_and_weights",
    "import_keras_model_and_weights",
]
