"""Keras HDF5 model import.

Parity targets in the reference (deeplearning4j-modelimport):
  KerasModelImport.java:41-269   — entry points (Sequential / functional)
  Hdf5Archive.java:22-24         — HDF5 traversal (h5py here)
  KerasModel.java / KerasSequentialModel.java — config parsing
  keras/layers/*                 — per-layer mappers (30+ classes)
  KerasLayerUtils.java           — activation / init name translation

TPU-first inversion: the reference must permute every conv kernel from
Keras's HWIO to its own NCHW-oriented layout and flip data formats
(KerasConvolutionUtils). This framework is natively NHWC/HWIO (see
nn/conf/inputs.py), the same layout Keras uses with channels_last — so
weights map over *without* transposition; only the LSTM gate order differs
(Keras [i,f,c,o] vs our fused [i,f,o,g] kernels, see nn/layers/recurrent.py).

Supports the Keras 2.x save format (the `model_config` root attribute plus
a `model_weights` group; files with weight groups at the file root are also
handled) and the Keras 1.x Sequential config-list format.  Architecture
import requires a full-model file — `save_weights`-only files carry no
`model_config` and are rejected with a clear error.  `channels_first`
models are rejected explicitly, mirroring the reference's
unsupported-config errors (InvalidKerasConfigurationException).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..nn.conf.inputs import InputType
from ..nn.conf.preprocessors import CnnToFeedForward
from ..nn.graph import (
    ComputationGraph,
    ElementWiseVertex,
    GraphBuilder,
    LastTimeStepVertex,
    MergeVertex,
    PreprocessorVertex,
)
from ..nn.layers import (
    ActivationLayer,
    BatchNormalization,
    Convolution1D,
    Convolution2D,
    Cropping2D,
    Deconvolution2D,
    Dense,
    DropoutLayer,
    EmbeddingSequence,
    GlobalPooling,
    LSTM,
    LastTimeStep,
    LayerNorm,
    LossLayer,
    OutputLayer,
    SeparableConvolution2D,
    SimpleRnn,
    Subsampling1D,
    Subsampling2D,
    Upsampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
)
from ..nn.layers.base import Layer
from ..nn.multilayer import MultiLayerConfiguration, MultiLayerNetwork


class InvalidKerasConfigurationException(ValueError):
    """Mirror of the reference's exceptions/InvalidKerasConfigurationException."""


# ---------------------------------------------------------------------------
# HDF5 traversal (Hdf5Archive.java parity, via h5py)
# ---------------------------------------------------------------------------


def _to_str(v: Any) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8")
    return str(v)


class Hdf5Archive:
    """Thin h5py wrapper matching the reference's Hdf5Archive surface:
    read root/group attributes as JSON or strings, list + read datasets."""

    def __init__(self, path: str):
        import h5py

        self._f = h5py.File(path, "r")

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "Hdf5Archive":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def has_attr(self, name: str, group: Optional[str] = None) -> bool:
        g = self._f[group] if group else self._f
        return name in g.attrs

    def read_attr_as_string(self, name: str, group: Optional[str] = None) -> str:
        g = self._f[group] if group else self._f
        return _to_str(g.attrs[name])

    def read_attr_as_json(self, name: str, group: Optional[str] = None) -> Any:
        return json.loads(self.read_attr_as_string(name, group))

    def read_string_list_attr(self, name: str, group: Optional[str] = None) -> List[str]:
        g = self._f[group] if group else self._f
        return [_to_str(v) for v in g.attrs[name]]

    def group(self, path: str):
        return self._f[path]

    def has_group(self, path: str) -> bool:
        return path in self._f


# ---------------------------------------------------------------------------
# name translation (KerasLayerUtils / KerasActivationUtils parity)
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "linear": "identity",
    "relu": "relu",
    "relu6": "relu6",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "tanh": "tanh",
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "elu": "elu",
    "selu": "selu",
    "swish": "swish",
    "silu": "swish",
    "gelu": "gelu",
    "leaky_relu": "leakyrelu",
    "mish": "mish",
}

_INITIALIZERS = {
    "glorot_uniform": "xavier_uniform",
    "glorot_normal": "xavier",
    "he_uniform": "relu_uniform",
    "he_normal": "relu",
    "lecun_uniform": "lecun_uniform",
    "lecun_normal": "lecun_normal",
    "zeros": "zero",
    "ones": "ones",
    "random_uniform": "uniform",
    "random_normal": "normal",
    "uniform": "uniform",
    "normal": "normal",
    "identity": "identity",
    "variance_scaling": "var_scaling",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse",
    "mse": "mse",
    "mean_absolute_error": "mae",
    "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "squared_hinge": "squared_hinge",
    "hinge": "hinge",
    "poisson": "poisson",
    "cosine_proximity": "cosine_proximity",
    "kullback_leibler_divergence": "kl_divergence",
}


def map_activation(name: str) -> str:
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise InvalidKerasConfigurationException(f"unsupported Keras activation: {name}")


def map_initializer(cfg: Any) -> Optional[str]:
    """Keras 2 initializers are {'class_name', 'config'} dicts; 1.x strings."""
    if cfg is None:
        return None
    name = cfg.get("class_name") if isinstance(cfg, dict) else cfg
    if name is None:
        return None
    # normalize CamelCase class names (GlorotUniform → glorot_uniform)
    s = "".join("_" + c.lower() if c.isupper() else c for c in str(name)).lstrip("_")
    return _INITIALIZERS.get(s)


def map_loss(name: str) -> str:
    from ..ops.losses import get_loss

    mapped = _LOSSES.get(name)
    if mapped is None:
        raise InvalidKerasConfigurationException(f"unsupported Keras loss: {name}")
    get_loss(mapped)  # raise early if our registry lacks it
    return mapped


def _check_data_format(cfg: dict, name: str) -> None:
    fmt = cfg.get("data_format", "channels_last")
    if fmt == "channels_first":
        raise InvalidKerasConfigurationException(
            f"layer {name}: data_format=channels_first is not supported "
            "(this framework is natively NHWC / channels_last)")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_mode(padding: str) -> str:
    if padding == "same":
        return "same"
    if padding == "valid":
        return "truncate"
    raise InvalidKerasConfigurationException(f"unsupported Keras padding: {padding}")


# ---------------------------------------------------------------------------
# per-layer mappers (keras/layers/* parity)
# ---------------------------------------------------------------------------

# A mapper returns (layer_or_None, input_type_or_None).  None layer means
# "structural only" (InputLayer/Flatten/Dropout-less etc. handled by caller).


def _common(layer: Layer, cfg: dict) -> Layer:
    layer.name = cfg.get("name")
    init = map_initializer(cfg.get("kernel_initializer") or cfg.get("init"))
    if init:
        layer.weight_init = init
    act = cfg.get("activation")
    if act is not None:
        layer.activation = map_activation(act)
    return layer


def _map_dense(cfg: dict) -> Layer:
    return _common(Dense(n_out=int(cfg["units"]),
                         has_bias=bool(cfg.get("use_bias", True))), cfg)


def _map_conv2d(cfg: dict) -> Layer:
    _check_data_format(cfg, cfg.get("name", "conv2d"))
    if "kernel_size" in cfg:
        kernel = _pair(cfg["kernel_size"])
    else:  # Keras 1.x: separate nb_row / nb_col
        kernel = (int(cfg.get("nb_row", 3)), int(cfg.get("nb_col", 3)))
    return _common(Convolution2D(
        n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
        kernel=kernel,
        stride=_pair(cfg.get("strides", (1, 1))),
        dilation=_pair(cfg.get("dilation_rate", (1, 1))),
        convolution_mode=_conv_mode(cfg.get("padding", cfg.get("border_mode", "valid"))),
        has_bias=bool(cfg.get("use_bias", True)),
    ), cfg)


def _map_conv2d_transpose(cfg: dict) -> Layer:
    """Keras Conv2DTranspose / 1.x Deconvolution2D → Deconvolution2D.
    Weight conversion happens in _set_layer_params (keras stores
    [kh,kw,out,in] and tf.nn.conv2d_transpose spatially flips; our layer
    runs lax.conv_transpose over an HWIO kernel without flipping)."""
    _check_data_format(cfg, cfg.get("name", "conv2d_transpose"))
    op = cfg.get("output_padding")
    if op is not None and any(int(v) != 0 for v in
                              (op if isinstance(op, (list, tuple)) else (op,))):
        raise InvalidKerasConfigurationException(
            f"Conv2DTranspose '{cfg.get('name')}': output_padding={op} is "
            "not supported (the imported layer's output shape would "
            "silently diverge from the source model)")
    if "kernel_size" in cfg:
        kernel = _pair(cfg["kernel_size"])
    else:  # Keras 1.x
        kernel = (int(cfg.get("nb_row", 3)), int(cfg.get("nb_col", 3)))
    return _common(Deconvolution2D(
        n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
        kernel=kernel,
        stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
        dilation=_pair(cfg.get("dilation_rate", (1, 1))),
        convolution_mode=_conv_mode(cfg.get("padding", cfg.get("border_mode", "valid"))),
        has_bias=bool(cfg.get("use_bias", True)),
    ), cfg)


def _map_zeropad1d(cfg: dict) -> Layer:
    p = cfg.get("padding", 1)
    pad = (int(p), int(p)) if isinstance(p, int) else (int(p[0]), int(p[1]))
    layer = ZeroPadding1D(padding=pad)
    layer.name = cfg.get("name")
    return layer


def _map_cropping2d(cfg: dict) -> Layer:
    _check_data_format(cfg, cfg.get("name", "cropping2d"))
    c = cfg.get("cropping", ((0, 0), (0, 0)))
    if isinstance(c, int):
        crop = (c, c, c, c)
    elif isinstance(c[0], (list, tuple)):
        crop = (int(c[0][0]), int(c[0][1]), int(c[1][0]), int(c[1][1]))
    else:  # (sym_h, sym_w)
        crop = (int(c[0]), int(c[0]), int(c[1]), int(c[1]))
    layer = Cropping2D(cropping=crop)
    layer.name = cfg.get("name")
    return layer


def _map_separable_conv2d(cfg: dict) -> Layer:
    _check_data_format(cfg, cfg.get("name", "separable_conv2d"))
    if "kernel_size" in cfg:
        kernel = _pair(cfg["kernel_size"])
    else:  # Keras 1.x: separate nb_row / nb_col
        kernel = (int(cfg.get("nb_row", 3)), int(cfg.get("nb_col", 3)))
    return _common(SeparableConvolution2D(
        n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
        kernel=kernel,
        stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
        dilation=_pair(cfg.get("dilation_rate", (1, 1))),
        convolution_mode=_conv_mode(cfg.get("padding",
                                            cfg.get("border_mode", "valid"))),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        has_bias=bool(cfg.get("use_bias", True)),
    ), cfg)


def _map_conv1d(cfg: dict) -> Layer:
    _check_data_format(cfg, cfg.get("name", "conv1d"))
    return _common(Convolution1D(
        n_out=int(cfg["filters"]),
        kernel=int(cfg["kernel_size"][0] if isinstance(cfg.get("kernel_size"), (list, tuple))
                   else cfg.get("kernel_size", 3)),
        stride=int(cfg.get("strides", [1])[0] if isinstance(cfg.get("strides"), (list, tuple))
                   else cfg.get("strides", 1)),
        convolution_mode=_conv_mode(cfg.get("padding", "valid")),
        has_bias=bool(cfg.get("use_bias", True)),
    ), cfg)


def _map_pool2d(cfg: dict, kind: str) -> Layer:
    _check_data_format(cfg, cfg.get("name", "pool"))
    pool = Subsampling2D(
        pooling=kind,
        kernel=_pair(cfg.get("pool_size", (2, 2))),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
        convolution_mode=_conv_mode(cfg.get("padding", cfg.get("border_mode", "valid"))),
    )
    pool.name = cfg.get("name")
    return pool


def _map_pool1d(cfg: dict, kind: str) -> Layer:
    _check_data_format(cfg, cfg.get("name", "pool1d"))
    k = cfg.get("pool_size", 2)
    k = int(k[0]) if isinstance(k, (list, tuple)) else int(k)
    s = cfg.get("strides") or k
    s = int(s[0]) if isinstance(s, (list, tuple)) else int(s)
    pool = Subsampling1D(pooling=kind, kernel=k, stride=s,
                         convolution_mode=_conv_mode(cfg.get("padding", "valid")))
    pool.name = cfg.get("name")
    return pool


def _map_global_pool(cfg: dict, kind: str) -> Layer:
    g = GlobalPooling(pooling=kind)
    g.name = cfg.get("name")
    return g


def _map_batchnorm(cfg: dict, rank_hint: Optional[int] = None) -> Layer:
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0]
    # This framework normalizes the trailing axis (channels_last). A positive
    # Keras axis counts from the batch dim, so with known input rank it must
    # equal rank-1 (KerasBatchNormalization.java's axis validation).
    if axis != -1 and rank_hint is not None and axis != rank_hint - 1:
        raise InvalidKerasConfigurationException(
            f"BatchNormalization {cfg.get('name')}: axis={axis} on rank-"
            f"{rank_hint} input — only trailing-axis (channels_last) BN is "
            "supported")
    bn = BatchNormalization(
        eps=float(cfg.get("epsilon", 1e-3)),
        decay=float(cfg.get("momentum", 0.99)),
    )
    bn.name = cfg.get("name")
    return bn


def _map_layernorm(cfg: dict) -> Layer:
    ln = LayerNorm(eps=float(cfg.get("epsilon", 1e-3)))
    ln.name = cfg.get("name")
    return ln


def _map_activation(cfg: dict) -> Layer:
    a = ActivationLayer(activation=map_activation(cfg["activation"]))
    a.name = cfg.get("name")
    return a


def _map_dropout(cfg: dict) -> Layer:
    d = DropoutLayer(dropout=float(cfg.get("rate", cfg.get("p", 0.5))))
    d.name = cfg.get("name")
    return d


def _map_spatial_dropout(cfg: dict) -> Layer:
    """SpatialDropout2D drops whole channels — mapping it to element-wise
    Dropout would silently change fine-tuning noise structure (reference
    KerasSpatialDropout → dl4j SpatialDropout)."""
    from ..nn.conf.regularizers import SpatialDropout
    d = DropoutLayer(dropout=SpatialDropout(
        p=float(cfg.get("rate", cfg.get("p", 0.5)))))
    d.name = cfg.get("name")
    return d


def _map_gaussian_noise(cfg: dict) -> Layer:
    from ..nn.conf.regularizers import GaussianNoise
    # Keras 1.x used 'sigma'
    std = float(cfg.get("stddev", cfg.get("sigma", 0.1)))
    d = DropoutLayer(dropout=GaussianNoise(stddev=std))
    d.name = cfg.get("name")
    return d


def _map_gaussian_dropout(cfg: dict) -> Layer:
    from ..nn.conf.regularizers import GaussianDropout
    # Keras 1.x used 'p'
    d = DropoutLayer(dropout=GaussianDropout(
        rate=float(cfg.get("rate", cfg.get("p", 0.5)))))
    d.name = cfg.get("name")
    return d


def _map_alpha_dropout(cfg: dict) -> Layer:
    from ..nn.conf.regularizers import AlphaDropout
    d = DropoutLayer(dropout=AlphaDropout(p=float(cfg.get("rate", 0.5))))
    d.name = cfg.get("name")
    return d


def _map_lstm(cfg: dict) -> Layer:
    # return_sequences=False is handled by the import loops, which append a
    # LastTimeStep layer / LastTimeStepVertex after this one
    # (KerasLstm.java's getUnderReturnSequences handling).
    layer = LSTM(
        n_out=int(cfg["units"]),
        forget_gate_bias_init=1.0 if cfg.get("unit_forget_bias", True) else 0.0,
    )
    layer.activation = map_activation(cfg.get("activation", "tanh"))
    layer.gate_activation = map_activation(cfg.get("recurrent_activation", "sigmoid"))
    init = map_initializer(cfg.get("kernel_initializer"))
    if init:
        layer.weight_init = init
    layer.name = cfg.get("name")
    return layer


def _map_simple_rnn(cfg: dict) -> Layer:
    layer = SimpleRnn(n_out=int(cfg["units"]))
    layer.activation = map_activation(cfg.get("activation", "tanh"))
    layer.name = cfg.get("name")
    return layer


def _map_embedding(cfg: dict) -> Layer:
    e = EmbeddingSequence(n_in=int(cfg["input_dim"]), n_out=int(cfg["output_dim"]),
                          has_bias=False)
    e.name = cfg.get("name")
    return e


def _map_zeropad2d(cfg: dict) -> Layer:
    pad = cfg.get("padding", 1)
    if isinstance(pad, (list, tuple)) and len(pad) == 2 and isinstance(pad[0], (list, tuple)):
        padding = (int(pad[0][0]), int(pad[0][1]), int(pad[1][0]), int(pad[1][1]))
    else:
        ph, pw = _pair(pad)
        padding = (ph, ph, pw, pw)
    z = ZeroPadding2D(padding=padding)
    z.name = cfg.get("name")
    return z


def _map_upsampling2d(cfg: dict) -> Layer:
    u = Upsampling2D(size=_pair(cfg.get("size", (2, 2))))
    u.name = cfg.get("name")
    return u


_LAYER_MAP: Dict[str, Callable[[dict], Layer]] = {
    "Dense": _map_dense,
    "Conv2D": _map_conv2d,
    "Convolution2D": _map_conv2d,
    "Conv1D": _map_conv1d,
    "Convolution1D": _map_conv1d,
    "MaxPooling2D": lambda c: _map_pool2d(c, "max"),
    "AveragePooling2D": lambda c: _map_pool2d(c, "avg"),
    "MaxPooling1D": lambda c: _map_pool1d(c, "max"),
    "AveragePooling1D": lambda c: _map_pool1d(c, "avg"),
    "GlobalMaxPooling2D": lambda c: _map_global_pool(c, "max"),
    "GlobalAveragePooling2D": lambda c: _map_global_pool(c, "avg"),
    "GlobalMaxPooling1D": lambda c: _map_global_pool(c, "max"),
    "GlobalAveragePooling1D": lambda c: _map_global_pool(c, "avg"),
    "BatchNormalization": _map_batchnorm,
    "LayerNormalization": _map_layernorm,
    "Activation": _map_activation,
    # Keras advanced activations carry their alpha on the layer config
    # (LeakyReLU default 0.3, ELU default 1.0) — preserved via the
    # parametric "name(alpha)" activation syntax
    "LeakyReLU": lambda c: ActivationLayer(
        activation=f"leakyrelu({float(c.get('alpha', 0.3))})"),
    "ELU": lambda c: ActivationLayer(
        activation=f"elu({float(c.get('alpha', 1.0))})"),
    "Dropout": _map_dropout,
    "SpatialDropout2D": _map_spatial_dropout,
    "GaussianNoise": _map_gaussian_noise,
    "GaussianDropout": _map_gaussian_dropout,
    "AlphaDropout": _map_alpha_dropout,
    "SeparableConv2D": _map_separable_conv2d,
    "SeparableConvolution2D": _map_separable_conv2d,
    "Conv2DTranspose": _map_conv2d_transpose,
    "Deconvolution2D": _map_conv2d_transpose,
    "ZeroPadding1D": _map_zeropad1d,
    "Cropping2D": _map_cropping2d,
    "LSTM": _map_lstm,
    "SimpleRNN": _map_simple_rnn,
    "Embedding": _map_embedding,
    "ZeroPadding2D": _map_zeropad2d,
    "UpSampling2D": _map_upsampling2d,
}

# structural layers consumed by the importer itself
_STRUCTURAL = {"InputLayer", "Flatten", "Reshape"}

_RANK4 = {"Conv2D", "Convolution2D", "SeparableConv2D",
          "SeparableConvolution2D", "Conv2DTranspose", "Deconvolution2D",
          "MaxPooling2D", "AveragePooling2D",
          "ZeroPadding2D", "Cropping2D", "UpSampling2D", "SpatialDropout2D"}
_RANK3 = {"LSTM", "SimpleRNN", "Embedding", "Conv1D", "Convolution1D",
          "MaxPooling1D", "AveragePooling1D", "ZeroPadding1D"}
# Dense is rank-preserving in Keras (broadcasts over leading dims)
_RANK2 = {"GlobalMaxPooling2D", "GlobalAveragePooling2D",
          "GlobalMaxPooling1D", "GlobalAveragePooling1D"}


def _rank_after(cls: str, cur: Optional[int]) -> Optional[int]:
    """Activation rank (incl. batch) after a Keras layer, for BN axis checks."""
    if cls in _RANK4:
        return 4
    if cls in _RANK3:
        return 3
    if cls in _RANK2:
        return 2
    return cur  # rank-preserving (BN, Activation, Dropout, ...)


def _input_type_from_shape(shape) -> InputType:
    """Input shape WITHOUT the batch dim → InputType.
    (time, features) → rnn, (h, w, c) → cnn, (features,) → ff."""
    if len(shape) == 3:
        h, w, c = shape
        return InputType.convolutional(h, w, c)
    if len(shape) == 2:
        t, f = shape
        if f is None:
            raise InvalidKerasConfigurationException(
                f"cannot infer recurrent feature size from {shape}")
        return InputType.recurrent(int(f), t)
    if len(shape) == 1 and shape[0] is not None:
        return InputType.feed_forward(int(shape[0]))
    raise InvalidKerasConfigurationException(f"unsupported input shape: {shape}")


# ---------------------------------------------------------------------------
# config parsing (KerasModel / KerasSequentialModel parity)
# ---------------------------------------------------------------------------


def _parse_model_config(model_config: Any) -> Tuple[str, List[dict], dict]:
    """Returns (kind, layer_dicts, extras).  kind ∈ {sequential, functional}."""
    if isinstance(model_config, list):  # Keras 1.x Sequential: bare list
        return "sequential", model_config, {}
    class_name = model_config.get("class_name", "Sequential")
    cfg = model_config.get("config", model_config)
    if class_name == "Sequential":
        layers = cfg if isinstance(cfg, list) else cfg.get("layers", [])
        return "sequential", layers, {}
    if class_name in ("Model", "Functional"):
        extras = {
            "input_layers": cfg.get("input_layers", []),
            "output_layers": cfg.get("output_layers", []),
        }
        return "functional", cfg.get("layers", []), extras
    raise InvalidKerasConfigurationException(f"unsupported model class: {class_name}")


def _layer_class_and_cfg(ld: dict) -> Tuple[str, dict]:
    cls = ld.get("class_name")
    cfg = ld.get("config", {})
    if isinstance(cfg, dict) and "name" not in cfg and "name" in ld:
        cfg = dict(cfg, name=ld["name"])
    return cls, cfg


# ---------------------------------------------------------------------------
# weight loading + conversion
# ---------------------------------------------------------------------------


def _weights_root(archive: Hdf5Archive) -> str:
    return "model_weights" if archive.has_group("model_weights") else "/"


def _layer_weight_arrays(archive: Hdf5Archive, root: str, layer_name: str) -> Dict[str, np.ndarray]:
    """{short weight name: array} for one Keras layer group."""
    base = f"{root}/{layer_name}" if root != "/" else layer_name
    if not archive.has_group(base):
        return {}
    g = archive.group(base)
    out: Dict[str, np.ndarray] = {}

    def visit(name, obj):
        import h5py

        if isinstance(obj, h5py.Dataset):
            short = name.split("/")[-1]
            short = short.split(":")[0]  # strip ':0' tensor suffix
            out[short] = np.asarray(obj)

    g.visititems(visit)
    return out


def _convert_lstm_kernel(k: np.ndarray, units: int) -> np.ndarray:
    """Keras gate blocks [i|f|c|o] → our fused order [i|f|o|g] (g = c)."""
    i, f, c, o = (k[..., :units], k[..., units:2 * units],
                  k[..., 2 * units:3 * units], k[..., 3 * units:])
    return np.concatenate([i, f, o, c], axis=-1)


def _set_layer_params(layer: Layer, params: Dict[str, Any], state: Dict[str, Any],
                      w: Dict[str, np.ndarray], dtype) -> None:
    """Write Keras weight arrays into our param/state dicts in place."""
    import jax.numpy as jnp

    def put(dst: Dict, key: str, arr: np.ndarray):
        dst[key] = jnp.asarray(arr, dtype)

    if isinstance(layer, (Dense, OutputLayer)):
        if "kernel" in w:
            put(params, "W", w["kernel"])          # (in, out) — same layout
        elif "W" in w:
            put(params, "W", w["W"])
        if layer.has_bias and ("bias" in w or "b" in w):
            put(params, "b", w.get("bias", w.get("b")))
    elif isinstance(layer, SeparableConvolution2D):
        # keras depthwise [kh,kw,in,dm] -> our dW [kh,kw,1,in*dm]: with
        # feature_group_count=n_in, output group i holds channel i's dm
        # multipliers — exactly the C-order flatten of keras's (in, dm)
        if "depthwise_kernel" in w:
            dk = w["depthwise_kernel"]
            kh, kw, cin, dm = dk.shape
            put(params, "dW", dk.reshape(kh, kw, 1, cin * dm))
        if "pointwise_kernel" in w:
            put(params, "pW", w["pointwise_kernel"])   # [1,1,in*dm,out] — same
        if layer.has_bias and ("bias" in w or "b" in w):
            put(params, "b", w.get("bias", w.get("b")))
    elif isinstance(layer, Deconvolution2D):
        # keras Conv2DTranspose kernel [kh,kw,out,in] with tf's implicit
        # spatial flip → our HWIO [kh,kw,in,out] for plain
        # lax.conv_transpose: transpose the channel dims AND flip H/W
        # (verified elementwise against tf.nn.conv2d_transpose —
        # tests/test_modelimport.py::TestConv2DTranspose)
        if "kernel" in w:
            put(params, "W", w["kernel"].transpose(0, 1, 3, 2)[::-1, ::-1])
        if layer.has_bias and ("bias" in w or "b" in w):
            put(params, "b", w.get("bias", w.get("b")))
    elif isinstance(layer, (Convolution2D, Convolution1D)):
        if "kernel" in w:
            put(params, "W", w["kernel"])          # HWIO — same layout
        elif "W" in w:
            put(params, "W", w["W"])
        if layer.has_bias and ("bias" in w or "b" in w):
            put(params, "b", w.get("bias", w.get("b")))
    elif isinstance(layer, BatchNormalization):
        if "gamma" in w:
            put(params, "gamma", w["gamma"])
        if "beta" in w:
            put(params, "beta", w["beta"])
        if "moving_mean" in w:
            put(state, "mean", w["moving_mean"])
        if "moving_variance" in w:
            put(state, "var", w["moving_variance"])
    elif isinstance(layer, LayerNorm):
        if "gamma" in w:
            put(params, "gamma", w["gamma"])
        if "beta" in w:
            put(params, "beta", w["beta"])
    elif isinstance(layer, LSTM):
        n = layer.n_out
        if "kernel" in w:
            put(params, "W", _convert_lstm_kernel(w["kernel"], n))
            put(params, "RW", _convert_lstm_kernel(w["recurrent_kernel"], n))
            if "bias" in w:
                put(params, "b", _convert_lstm_kernel(w["bias"], n))
    elif isinstance(layer, SimpleRnn):
        if "kernel" in w:
            put(params, "W", w["kernel"])
            put(params, "RW", w["recurrent_kernel"])
            if "bias" in w:
                put(params, "b", w["bias"])
    elif isinstance(layer, EmbeddingSequence):
        if "embeddings" in w:
            put(params, "W", w["embeddings"])
        elif "W" in w:
            put(params, "W", w["W"])
    # pooling/activation/dropout/padding: no params


# ---------------------------------------------------------------------------
# sequential import
# ---------------------------------------------------------------------------


def _read_model_config(archive: Hdf5Archive) -> Any:
    if not archive.has_attr("model_config"):
        raise InvalidKerasConfigurationException(
            "no model_config attribute — is this a save_weights-only file? "
            "Full-model files are required for architecture import")
    return archive.read_attr_as_json("model_config")


def import_keras_sequential_model_and_weights(
        path: str, enforce_training_config: bool = False) -> MultiLayerNetwork:
    """Keras Sequential .h5 → MultiLayerNetwork with weights
    (KerasModelImport.importKerasSequentialModelAndWeights:120-180)."""
    with Hdf5Archive(path) as archive:
        kind, layer_dicts, _ = _parse_model_config(_read_model_config(archive))
        if kind != "sequential":
            raise InvalidKerasConfigurationException(
                "functional model passed to sequential import — use "
                "import_keras_model_and_weights")
        return _import_sequential(archive, layer_dicts, enforce_training_config)


def _import_sequential(archive: Hdf5Archive, layer_dicts: List[dict],
                       enforce_training_config: bool) -> MultiLayerNetwork:
    training_cfg = None
    if archive.has_attr("training_config"):
        training_cfg = archive.read_attr_as_json("training_config")
    elif enforce_training_config:
        raise InvalidKerasConfigurationException(
            "enforce_training_config=True but file has no training_config")

    conf = MultiLayerConfiguration()
    input_type: Optional[InputType] = None
    our_layers: List[Layer] = []
    keras_names: List[Optional[str]] = []  # keras layer name per our layer
    cur_rank: Optional[int] = None  # rank incl. batch dim, for BN axis check

    for ld in layer_dicts:
        cls, cfg = _layer_class_and_cfg(ld)
        shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
        if input_type is None and shape is not None:
            stripped = list(shape)[1:]
            if cls == "Embedding":
                # Keras Embedding input (batch, T) carries int indices:
                # model it as a length-T sequence (size is the index col)
                input_type = InputType.recurrent(1, stripped[0])
                cur_rank = 3
            else:
                input_type = _input_type_from_shape(stripped)
                cur_rank = len(shape)
        if cls in _STRUCTURAL:
            # InputLayer → input_type only; Flatten/Reshape → rely on the
            # automatic preprocessor pass (_infer_types inserts
            # CnnToFeedForward when a Dense follows a conv stack)
            if cls in ("Flatten", "Reshape"):
                cur_rank = 2
            continue
        if cls not in _LAYER_MAP:
            raise InvalidKerasConfigurationException(f"unsupported Keras layer: {cls}")
        if cls == "BatchNormalization":
            layer = _map_batchnorm(cfg, rank_hint=cur_rank)
        else:
            layer = _LAYER_MAP[cls](cfg)
        if cls in ("LSTM", "SimpleRNN") and not cfg.get("return_sequences", False):
            wrapped = LastTimeStep(layer=layer)
            wrapped.name = layer.name
            layer = wrapped
            cur_rank = 2
        else:
            cur_rank = _rank_after(cls, cur_rank)
        our_layers.append(layer)
        keras_names.append(cfg.get("name"))

    if input_type is None:
        raise InvalidKerasConfigurationException(
            "could not determine input shape (no batch_input_shape on the "
            "first layer)")

    # loss head: translate the final Dense into an OutputLayer when a
    # training_config names a loss (KerasModel.java's enforceTrainingConfig)
    if training_cfg is not None:
        loss_name = training_cfg.get("loss")
        if isinstance(loss_name, dict):
            loss_name = next(iter(loss_name.values()))
        if isinstance(loss_name, str) and our_layers:
            mapped = map_loss(loss_name)
            last = our_layers[-1]
            if type(last) is Dense:
                out = OutputLayer(n_in=last.n_in, n_out=last.n_out,
                                  has_bias=last.has_bias, loss=mapped)
                out.activation, out.weight_init = last.activation, last.weight_init
                out.name = last.name
                our_layers[-1] = out
            else:
                # parameter-free loss head — Keras keeps the loss in the
                # optimizer, DL4J appends a LossLayer (KerasLoss.java)
                our_layers.append(LossLayer(loss=mapped, activation="identity"))
                keras_names.append(None)

    conf.layers = our_layers
    conf.input_type = input_type
    net = MultiLayerNetwork(conf)
    net.init()

    # weights
    root = _weights_root(archive)
    import jax.numpy as jnp

    dtype = jnp.dtype(conf.param_dtype)
    for i, (layer, kname) in enumerate(zip(our_layers, keras_names)):
        if kname is None:
            continue
        w = _layer_weight_arrays(archive, root, kname)
        if not w:
            continue
        target = layer.layer if isinstance(layer, LastTimeStep) else layer
        p = dict(net.params[i])
        s = dict(net.state[i])
        _set_layer_params(target, p, s, w, dtype)
        net.params[i] = p
        net.state[i] = s
    return net


# ---------------------------------------------------------------------------
# functional import
# ---------------------------------------------------------------------------


def _check_concatenate_axis(cfg: dict, name: str, in_rank: Optional[int]) -> None:
    """MergeVertex always concatenates the trailing axis; a Keras
    Concatenate on any other axis would import silently wrong — reject it
    loudly (mirrors the channels_first rejection)."""
    axis = cfg.get("axis", -1)
    ok = axis == -1 or (in_rank is not None and axis == in_rank - 1)
    if not ok:
        raise InvalidKerasConfigurationException(
            f"Concatenate layer '{name}' uses axis={axis}; only the "
            f"trailing feature axis (-1) is supported by MergeVertex")


def _inbound_names(ld: dict) -> List[str]:
    """Flatten Keras inbound_nodes (nested [[name, node_idx, tensor_idx, {}]])."""
    nodes = ld.get("inbound_nodes", [])
    if not nodes:
        return []
    if len(nodes) > 1:
        raise InvalidKerasConfigurationException(
            f"layer {ld.get('name') or ld.get('config', {}).get('name')} is "
            "applied at multiple call sites (shared layer) — not supported")
    first = nodes[0]
    names: List[str] = []
    if isinstance(first, dict):  # Keras 3 style {'args': [...]}
        def walk(o):
            if isinstance(o, dict):
                if o.get("class_name") == "__keras_tensor__":
                    names.append(o["config"]["keras_history"][0])
                else:
                    for v in o.values():
                        walk(v)
            elif isinstance(o, (list, tuple)):
                for v in o:
                    walk(v)
        walk(first)
    else:
        for entry in first:
            if isinstance(entry, (list, tuple)) and entry and isinstance(entry[0], str):
                names.append(entry[0])
    return names


def import_keras_model_and_weights(path: str,
                                   enforce_training_config: bool = False):
    """Keras .h5 → model. Sequential → MultiLayerNetwork; functional →
    ComputationGraph (KerasModelImport.importKerasModelAndWeights:41-119)."""
    with Hdf5Archive(path) as archive:
        kind, layer_dicts, extras = _parse_model_config(_read_model_config(archive))
        if kind == "sequential":
            return _import_sequential(archive, layer_dicts, enforce_training_config)
        return _import_functional(archive, layer_dicts, extras)


# Keras merge layers → ElementWiseVertex ops (KerasMerge.java mapping)
_MERGE_OPS = {
    "Add": "add",
    "Subtract": "subtract",
    "Multiply": "product",
    "Maximum": "max",
    "Average": "average",
}
_MERGE_OPS.update({k.lower(): v for k, v in _MERGE_OPS.items()})


def _import_functional(archive: Hdf5Archive, layer_dicts: List[dict],
                       extras: dict) -> ComputationGraph:
    builder = GraphBuilder()
    input_types: Dict[str, InputType] = {}
    keras_to_vertex: Dict[str, str] = {}
    layer_by_name: Dict[str, Layer] = {}
    vertex_rank: Dict[str, Optional[int]] = {}  # incl. batch dim, for BN

    for ld in layer_dicts:
        cls, cfg = _layer_class_and_cfg(ld)
        name = cfg.get("name") or ld.get("name")
        inputs = [keras_to_vertex[n] for n in _inbound_names(ld)]
        in_rank = vertex_rank.get(inputs[0]) if inputs else None
        if cls == "InputLayer":
            shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
            builder.add_inputs(name)
            input_types[name] = _input_type_from_shape(list(shape)[1:])
            keras_to_vertex[name] = name
            vertex_rank[name] = len(shape)
            continue
        if cls == "Flatten":
            builder.add_vertex(name, PreprocessorVertex(CnnToFeedForward()), *inputs)
            keras_to_vertex[name] = name
            vertex_rank[name] = 2
            continue
        if cls in _MERGE_OPS:
            builder.add_vertex(name, ElementWiseVertex(op=_MERGE_OPS[cls]), *inputs)
            keras_to_vertex[name] = name
            vertex_rank[name] = in_rank
            continue
        if cls in ("Concatenate", "Merge"):
            if cls == "Concatenate":
                _check_concatenate_axis(cfg, name, in_rank)
            builder.add_vertex(name, MergeVertex(), *inputs)
            keras_to_vertex[name] = name
            vertex_rank[name] = in_rank
            continue
        if cls not in _LAYER_MAP:
            raise InvalidKerasConfigurationException(f"unsupported Keras layer: {cls}")
        if cls == "BatchNormalization":
            layer = _map_batchnorm(cfg, rank_hint=in_rank)
        else:
            layer = _LAYER_MAP[cls](cfg)
        builder.add_layer(name, layer, *inputs)
        layer_by_name[name] = layer
        keras_to_vertex[name] = name
        vertex_rank[name] = _rank_after(cls, in_rank)
        if cls in ("LSTM", "SimpleRNN") and not cfg.get("return_sequences", False):
            builder.add_vertex(name + "__last", LastTimeStepVertex(), name)
            keras_to_vertex[name] = name + "__last"
            vertex_rank[name + "__last"] = 2

    outs = []
    for o in extras.get("output_layers", []):
        raw = o[0] if isinstance(o, (list, tuple)) else o
        outs.append(keras_to_vertex.get(raw, raw))
    builder.set_outputs(*outs)
    builder.set_input_types(**input_types)
    graph = ComputationGraph(builder.build())
    graph.init()

    # weights
    root = _weights_root(archive)
    import jax.numpy as jnp

    dtype = jnp.dtype(graph.conf.param_dtype) if hasattr(graph.conf, "param_dtype") else jnp.float32
    for name, layer in layer_by_name.items():
        w = _layer_weight_arrays(archive, root, name)
        if not w:
            continue
        if name in graph.params:
            p = dict(graph.params[name])
            s = dict(graph.state.get(name, {}))
            _set_layer_params(layer, p, s, w, dtype)
            graph.params[name] = p
            if name in graph.state:
                graph.state[name] = s
    return graph


class KerasModelImport:
    """Static entry points (KerasModelImport.java:41-269 parity)."""

    import_keras_sequential_model_and_weights = staticmethod(
        import_keras_sequential_model_and_weights)
    import_keras_model_and_weights = staticmethod(import_keras_model_and_weights)
