"""Classification evaluation — accuracy/precision/recall/F1/confusion.

Parity target: reference eval/Evaluation.java (1,627 LoC) + ConfusionMatrix.
Streamable (eval() accumulates per batch) and mergeable (merge()), the two
properties Spark/parallel evaluation rely on
(spark: IEvaluateFlatMapFunction aggregates Evaluation objects).
Accumulation is numpy on host — metric math is not a TPU workload.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    """Dense class-by-class count matrix (reference eval/ConfusionMatrix.java)."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def grow_to(self, n: int) -> None:
        if n > self.n_classes:
            m = np.zeros((n, n), dtype=np.int64)
            m[: self.n_classes, : self.n_classes] = self.matrix
            self.matrix = m
            self.n_classes = n

    def add(self, actual: np.ndarray, predicted: np.ndarray) -> None:
        hi = int(max(actual.max(initial=-1), predicted.max(initial=-1))) + 1
        self.grow_to(hi)
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def merge(self, other: "ConfusionMatrix") -> None:
        self.grow_to(other.n_classes)
        self.matrix[: other.n_classes, : other.n_classes] += other.matrix


class Evaluation:
    """Multiclass classification metrics (reference eval/Evaluation.java).

    ``eval(labels, predictions)`` accepts one-hot or index labels and
    probability or index predictions; rank-3 ``[mb, t, c]`` time series are
    flattened with the labels mask applied (reference evalTimeSeries).
    """

    def __init__(self, n_classes: Optional[int] = None):
        self.n_classes = n_classes
        self.confusion: Optional[ConfusionMatrix] = None

    # -- accumulation ------------------------------------------------------
    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [mb, t, c] time series
            c = labels.shape[-1]
            labels = labels.reshape(-1, c)
            predictions = predictions.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:  # per-example mask on 2-D labels
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        actual = labels.argmax(-1) if labels.ndim == 2 else labels.astype(np.int64)
        pred = predictions.argmax(-1) if predictions.ndim == 2 else predictions.astype(np.int64)
        if self.confusion is None:
            n = self.n_classes or int(max(labels.shape[-1] if labels.ndim == 2 else actual.max() + 1,
                                          predictions.shape[-1] if predictions.ndim == 2 else pred.max() + 1))
            self.n_classes = n
            self.confusion = ConfusionMatrix(n)
        self.confusion.add(actual, pred)
        self.n_classes = self.confusion.n_classes  # may have grown (index labels)

    def merge(self, other: "Evaluation") -> None:
        if other.confusion is None:
            return
        if self.confusion is None:
            self.n_classes = other.n_classes
            self.confusion = ConfusionMatrix(other.n_classes)
        self.confusion.merge(other.confusion)
        self.n_classes = self.confusion.n_classes

    # -- per-class counts --------------------------------------------------
    def _m(self) -> np.ndarray:
        if self.confusion is None:
            raise ValueError("no data accumulated; call eval() first")
        return self.confusion.matrix

    def true_positives(self) -> np.ndarray:
        return np.diag(self._m())

    def false_positives(self) -> np.ndarray:
        return self._m().sum(0) - np.diag(self._m())

    def false_negatives(self) -> np.ndarray:
        return self._m().sum(1) - np.diag(self._m())

    def true_negatives(self) -> np.ndarray:
        total = self._m().sum()
        return total - self.true_positives() - self.false_positives() - self.false_negatives()

    # -- aggregate metrics -------------------------------------------------
    def accuracy(self) -> float:
        m = self._m()
        return float(np.diag(m).sum() / max(m.sum(), 1))

    def precision(self, cls: Optional[int] = None) -> float:
        tp, fp = self.true_positives(), self.false_positives()
        if cls is not None:
            denom = tp[cls] + fp[cls]
            return float(tp[cls] / denom) if denom else 0.0
        # macro-average over classes that appear (reference: excludes classes
        # with no predictions from the average)
        denom = tp + fp
        valid = denom > 0
        return float(np.mean(tp[valid] / denom[valid])) if valid.any() else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        tp, fn = self.true_positives(), self.false_negatives()
        if cls is not None:
            denom = tp[cls] + fn[cls]
            return float(tp[cls] / denom) if denom else 0.0
        denom = tp + fn
        valid = denom > 0
        return float(np.mean(tp[valid] / denom[valid])) if valid.any() else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        tp = float(self.true_positives()[cls])
        tn = float(self.true_negatives()[cls])
        fp = float(self.false_positives()[cls])
        fn = float(self.false_negatives()[cls])
        denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return (tp * tn - fp * fn) / denom if denom else 0.0

    def stats(self) -> str:
        """Printable summary (reference Evaluation.stats())."""
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes: {self.n_classes}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "=================================================================",
        ]
        return "\n".join(lines)
