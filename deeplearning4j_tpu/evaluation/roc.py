"""ROC / AUC — parity with reference eval/ROC.java (706 LoC), ROCBinary,
ROCMultiClass.

Like the reference's thresholded mode, probabilities are bucketed into
``threshold_steps`` bins so accumulation is streaming and mergeable; AUC is
computed by trapezoidal integration over the resulting curve.  (The
reference also has an exact mode; the binned mode is the default there too
for large data.)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC: labels [n] or [n,1] in {0,1} (or two-column one-hot with
    column 1 = positive, reference convention)."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        # counts[i] accumulates at threshold i/steps
        self.tp = np.zeros(threshold_steps + 1, dtype=np.int64)
        self.fp = np.zeros(threshold_steps + 1, dtype=np.int64)
        self.pos = 0
        self.neg = 0

    @staticmethod
    def _binary_prob(labels, predictions) -> Tuple[np.ndarray, np.ndarray]:
        y = np.asarray(labels)
        p = np.asarray(predictions)
        if y.ndim == 2 and y.shape[1] == 2:
            y, p = y[:, 1], p[:, 1]
        elif y.ndim == 2 and y.shape[1] == 1:
            y, p = y[:, 0], p[:, 0]
        return y.astype(np.float64), p.astype(np.float64)

    def eval(self, labels, predictions, mask=None) -> None:
        y, p = self._binary_prob(labels, predictions)
        thresholds = np.linspace(0.0, 1.0, self.steps + 1)
        pos_mask = y >= 0.5
        self.pos += int(pos_mask.sum())
        self.neg += int((~pos_mask).sum())
        # vectorized: for each threshold, count p >= t among pos/neg
        p_pos = np.sort(p[pos_mask])
        p_neg = np.sort(p[~pos_mask])
        self.tp += len(p_pos) - np.searchsorted(p_pos, thresholds, side="left")
        self.fp += len(p_neg) - np.searchsorted(p_neg, thresholds, side="left")

    def merge(self, other: "ROC") -> None:
        self.tp += other.tp
        self.fp += other.fp
        self.pos += other.pos
        self.neg += other.neg

    def get_roc_curve(self) -> Tuple[np.ndarray, np.ndarray]:
        tpr = self.tp / max(self.pos, 1)
        fpr = self.fp / max(self.neg, 1)
        return fpr, tpr

    def calculate_auc(self) -> float:
        fpr, tpr = self.get_roc_curve()
        order = np.argsort(fpr, kind="stable")
        return float(np.trapezoid(tpr[order], fpr[order]))

    def calculate_auprc(self) -> float:
        """Area under precision-recall curve (reference calculateAUCPR)."""
        tp = self.tp.astype(np.float64)
        fp = self.fp.astype(np.float64)
        recall = tp / max(self.pos, 1)
        precision = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 1.0)
        order = np.argsort(recall, kind="stable")
        return float(np.trapezoid(precision[order], recall[order]))


class ROCBinary:
    """Per-output-column binary ROC (reference ROCBinary: multi-label)."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self.rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.atleast_2d(np.asarray(labels))
        p = np.atleast_2d(np.asarray(predictions))
        if self.rocs is None:
            self.rocs = [ROC(self.steps) for _ in range(y.shape[1])]
        for i, roc in enumerate(self.rocs):
            roc.eval(y[:, i], p[:, i])

    def calculate_auc(self, col: int) -> float:
        return self.rocs[col].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))


class ROCMultiClass:
    """One-vs-all ROC per class (reference ROCMultiClass)."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self.rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.asarray(labels)
        p = np.asarray(predictions)
        if y.ndim == 3:
            c = y.shape[-1]
            y, p = y.reshape(-1, c), p.reshape(-1, c)
        if self.rocs is None:
            self.rocs = [ROC(self.steps) for _ in range(y.shape[1])]
        for i, roc in enumerate(self.rocs):
            roc.eval(y[:, i], p[:, i])

    def calculate_auc(self, cls: int) -> float:
        return self.rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))
