"""EvaluationCalibration — reliability diagram + histogram data.

Parity with reference eval/EvaluationCalibration.java: accumulates
reliability-diagram bins (mean predicted probability vs. observed frequency
per bin), residual-plot and probability histograms.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class EvaluationCalibration:
    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.n_bins = reliability_bins
        self.hist_bins = histogram_bins
        self._init = False

    def _ensure(self, n_classes: int) -> None:
        if not self._init:
            self.n_classes = n_classes
            self.bin_counts = np.zeros((n_classes, self.n_bins), dtype=np.int64)
            self.bin_pos = np.zeros((n_classes, self.n_bins), dtype=np.int64)
            self.bin_prob_sum = np.zeros((n_classes, self.n_bins), dtype=np.float64)
            self.prob_hist = np.zeros((n_classes, self.hist_bins), dtype=np.int64)
            self._init = True

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.asarray(labels)
        p = np.asarray(predictions)
        if y.ndim == 3:
            c = y.shape[-1]
            y, p = y.reshape(-1, c), p.reshape(-1, c)
        self._ensure(y.shape[1])
        bins = np.clip((p * self.n_bins).astype(int), 0, self.n_bins - 1)
        hbins = np.clip((p * self.hist_bins).astype(int), 0, self.hist_bins - 1)
        for c in range(self.n_classes):
            np.add.at(self.bin_counts[c], bins[:, c], 1)
            np.add.at(self.bin_pos[c], bins[:, c], (y[:, c] >= 0.5).astype(np.int64))
            np.add.at(self.bin_prob_sum[c], bins[:, c], p[:, c])
            np.add.at(self.prob_hist[c], hbins[:, c], 1)

    def reliability_diagram(self, cls: int) -> Tuple[np.ndarray, np.ndarray]:
        """(mean predicted prob, observed frequency) per bin."""
        counts = np.maximum(self.bin_counts[cls], 1)
        mean_pred = self.bin_prob_sum[cls] / counts
        obs_freq = self.bin_pos[cls] / counts
        return mean_pred, obs_freq

    def expected_calibration_error(self, cls: int) -> float:
        counts = self.bin_counts[cls]
        total = max(counts.sum(), 1)
        mean_pred, obs_freq = self.reliability_diagram(cls)
        return float(np.sum(counts / total * np.abs(mean_pred - obs_freq)))
