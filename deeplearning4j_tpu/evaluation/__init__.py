from .evaluation import Evaluation, ConfusionMatrix
from .regression import RegressionEvaluation
from .roc import ROC, ROCBinary, ROCMultiClass
from .binary import EvaluationBinary
from .calibration import EvaluationCalibration
