"""EvaluationBinary — per-output binary classification metrics.

Parity with reference eval/EvaluationBinary.java: independent binary
accuracy/precision/recall/F1 per output column (multi-label networks with
sigmoid outputs), with optional decision threshold per column.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class EvaluationBinary:
    def __init__(self, n_columns: Optional[int] = None, decision_threshold: float = 0.5):
        self.n_columns = n_columns
        self.threshold = decision_threshold
        self._init = False

    def _ensure(self, n: int) -> None:
        if not self._init:
            self.n_columns = n
            z = lambda: np.zeros(n, dtype=np.int64)
            self.tp, self.fp, self.tn, self.fn = z(), z(), z(), z()
            self._init = True

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.atleast_2d(np.asarray(labels))
        p = np.atleast_2d(np.asarray(predictions))
        if y.ndim == 3:
            c = y.shape[-1]
            y, p = y.reshape(-1, c), p.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                y, p = y[m], p[m]
        self._ensure(y.shape[1])
        yb = y >= 0.5
        pb = p >= self.threshold
        self.tp += (yb & pb).sum(0)
        self.fp += (~yb & pb).sum(0)
        self.tn += (~yb & ~pb).sum(0)
        self.fn += (yb & ~pb).sum(0)

    def merge(self, other: "EvaluationBinary") -> None:
        if not other._init:
            return
        if not self._init:
            self._ensure(other.n_columns)
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn

    def accuracy(self, col: int = 0) -> float:
        total = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / max(total, 1))

    def precision(self, col: int = 0) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int = 0) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int = 0) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self) -> str:
        lines = ["Column    Accuracy     Precision    Recall       F1"]
        for c in range(self.n_columns):
            lines.append(f"col_{c:<5} {self.accuracy(c):<12.4f} {self.precision(c):<12.4f} "
                         f"{self.recall(c):<12.4f} {self.f1(c):<12.4f}")
        return "\n".join(lines)
