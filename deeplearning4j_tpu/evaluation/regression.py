"""Regression metrics — parity with reference eval/RegressionEvaluation.java:
per-column MSE, MAE, RMSE, RSE, PC (Pearson correlation), R².  Streaming
accumulation via sufficient statistics so batches merge exactly.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n_columns = n_columns
        self._init = False

    def _ensure(self, n: int) -> None:
        if not self._init:
            self.n_columns = n
            z = lambda: np.zeros(n, dtype=np.float64)
            self.count = z()
            self.sum_err2 = z()       # Σ(y-ŷ)²
            self.sum_abs_err = z()    # Σ|y-ŷ|
            self.sum_y = z()
            self.sum_y2 = z()
            self.sum_p = z()
            self.sum_p2 = z()
            self.sum_yp = z()
            self._init = True

    def eval(self, labels, predictions, mask=None) -> None:
        y = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        if y.ndim == 3:
            c = y.shape[-1]
            y, p = y.reshape(-1, c), p.reshape(-1, c)
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
                y, p = y[m], p[m]
        self._ensure(y.shape[-1])
        err = y - p
        self.count += y.shape[0]
        self.sum_err2 += (err ** 2).sum(0)
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_y += y.sum(0)
        self.sum_y2 += (y ** 2).sum(0)
        self.sum_p += p.sum(0)
        self.sum_p2 += (p ** 2).sum(0)
        self.sum_yp += (y * p).sum(0)

    def merge(self, other: "RegressionEvaluation") -> None:
        if not getattr(other, "_init", False):
            return
        if not self._init:
            self._ensure(other.n_columns)
        for f in ("count", "sum_err2", "sum_abs_err", "sum_y", "sum_y2",
                  "sum_p", "sum_p2", "sum_yp"):
            setattr(self, f, getattr(self, f) + getattr(other, f))

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / max(self.count[col], 1))

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs_err[col] / max(self.count[col], 1))

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int = 0) -> float:
        n = self.count[col]
        mean_y = self.sum_y[col] / n
        ss_tot = self.sum_y2[col] - n * mean_y ** 2
        return float(self.sum_err2[col] / ss_tot) if ss_tot else float("inf")

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.count[col]
        cov = self.sum_yp[col] - self.sum_y[col] * self.sum_p[col] / n
        vy = self.sum_y2[col] - self.sum_y[col] ** 2 / n
        vp = self.sum_p2[col] - self.sum_p[col] ** 2 / n
        denom = np.sqrt(vy * vp)
        return float(cov / denom) if denom else 0.0

    def r_squared(self, col: int = 0) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_err2 / np.maximum(self.count, 1)))

    def stats(self) -> str:
        cols = range(self.n_columns)
        lines = ["Column    MSE          MAE          RMSE         RSE          PC           R^2"]
        for c in cols:
            lines.append(
                f"col_{c:<5} {self.mean_squared_error(c):<12.5g} {self.mean_absolute_error(c):<12.5g} "
                f"{self.root_mean_squared_error(c):<12.5g} {self.relative_squared_error(c):<12.5g} "
                f"{self.pearson_correlation(c):<12.5g} {self.r_squared(c):<12.5g}")
        return "\n".join(lines)
