"""Module-level call graph + jit-boundary inference (stdlib ``ast`` only).

The traced-code set is the load-bearing input to every GC1xx purity rule
and the severity escalation of the GC2xx determinism rules, so it is
computed once here and shared:

1. **Seeds** — functions that enter a JAX trace directly:
   ``jax.jit(f)`` / ``@jax.jit`` / ``@partial(jax.jit, ...)``,
   ``shard_map(f, ...)``, ``pl.pallas_call(kernel, ...)``,
   ``@jax.custom_vjp`` / ``@custom_jvp`` and ``f.defvjp(fwd, bwd)``,
   ``jax.grad``/``value_and_grad``/``vmap``/``pmap``/``checkpoint``/
   ``remat``, and ``jax.lax.{scan,while_loop,fori_loop,cond,map}``
   bodies.  Aliases are normalized through each module's import table,
   so ``from ..utils.jax_compat import shard_map`` and
   ``from jax.experimental import pallas as pl`` both resolve.
2. **Closure** — traced-ness propagates through resolved call edges
   (calling ``g()`` from traced ``f`` runs ``g`` at trace time) and
   through function *references* (passing ``loss_fn`` to
   ``value_and_grad`` inside a traced step).  Resolution is lexical
   (nested defs, skipping class scopes), then ``self.method`` within
   the innermost class, then module functions, then cross-module
   through ``from ..x import y`` / ``import x as m`` of analyzed
   modules.

The same graph answers determinism-reachability queries: given root
patterns (the step / checkpoint-replay / trace-export entry points),
``reachable_from`` returns every function on such a path plus which
root reaches it — that is what turns a GC201 wall-clock *warning* into
"this one backs a bit-identity gate".
"""

from __future__ import annotations

import ast
import fnmatch
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

# leaf callable names that trace their function-valued arguments.
# Bare-name matches are restricted to the unambiguous ones; generic leaves
# (scan, cond, ...) additionally need a jax-ish prefix to match.
_TRACER_LEAVES = {
    "jit", "pmap", "vmap", "grad", "value_and_grad", "shard_map",
    "pallas_call", "custom_vjp", "custom_jvp", "checkpoint", "remat",
    "scan", "while_loop", "fori_loop", "cond", "map", "associative_scan",
    "switch",
}
_BARE_OK = {"jit", "shard_map", "pallas_call", "custom_vjp", "custom_jvp",
            "value_and_grad"}


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FunctionInfo:
    __slots__ = ("qual", "node", "module", "class_name", "scope",
                 "calls", "refs", "traced_reason", "params")

    def __init__(self, qual: str, node: ast.AST, module: "ModuleInfo",
                 class_name: Optional[str], scope: Tuple[Tuple[str, str], ...]):
        self.qual = qual
        self.node = node
        self.module = module
        self.class_name = class_name
        self.scope = scope          # ((kind, name), ...) enclosing chain
        self.calls: Set[Tuple] = set()   # ("name", n) | ("self", m) | ("attr", base, leaf)
        self.refs: Set[str] = set()      # bare Name loads (potential fn refs)
        self.traced_reason: Optional[str] = None
        self.params: Set[str] = set()

    @property
    def gid(self) -> str:
        return f"{self.module.relpath}::{self.qual}"

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 0)


class ModuleInfo:
    __slots__ = ("relpath", "modkey", "tree", "source", "lines",
                 "functions", "classes", "imports")

    def __init__(self, relpath: str, modkey: str, tree: ast.Module,
                 source: str):
        self.relpath = relpath      # repo-relative posix path
        self.modkey = modkey        # package-relative dotted, e.g. "nn.multilayer"
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, Set[str]] = {}       # class -> method names
        self.imports: Dict[str, Tuple] = {}  # alias -> ("module", key) | ("symbol", modkey, name)

    def normalize(self, dotted_name: str) -> str:
        """Rewrite a leading import alias to its target dotted path."""
        head, _, rest = dotted_name.partition(".")
        imp = self.imports.get(head)
        if imp is None:
            return dotted_name
        if imp[0] == "module":
            base = imp[1]
        else:
            base = f"{imp[1]}.{imp[2]}"
        return f"{base}.{rest}" if rest else base


def _resolve_relative(modkey: str, module: Optional[str], level: int) -> str:
    """'from ..ops import x' inside 'parallel.trainer' -> 'ops[.x]'."""
    if level == 0:
        return module or ""
    parts = modkey.split(".") if modkey else []
    # level 1 = current package (drop the module segment), each extra
    # level drops one more package
    base = parts[:-level] if level <= len(parts) else []
    if module:
        base = base + module.split(".")
    return ".".join(base)


class _Collector(ast.NodeVisitor):
    """One pass per module: functions, classes, imports, per-function
    call/ref edges, and trace seeds."""

    def __init__(self, mod: ModuleInfo, graph: "CallGraph"):
        self.mod = mod
        self.graph = graph
        self.stack: List[Tuple[str, str]] = []   # (kind, name)
        self.fn_stack: List[FunctionInfo] = []

    # -- imports -------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            target = a.name if a.asname else a.name.split(".")[0]
            self.mod.imports[alias] = ("module", target)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = _resolve_relative(self.mod.modkey, node.module, node.level)
        for a in node.names:
            alias = a.asname or a.name
            self.mod.imports[alias] = ("symbol", base, a.name)

    # -- scopes --------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.mod.classes.setdefault(node.name, set())
        self.stack.append(("class", node.name))
        self.generic_visit(node)
        self.stack.pop()

    def _qual(self, name: str) -> str:
        return ".".join([n for _, n in self.stack] + [name])

    def _enter_function(self, node) -> None:
        qual = self._qual(node.name)
        class_name = None
        for kind, name in reversed(self.stack):
            if kind == "class":
                class_name = name
                break
        fi = FunctionInfo(qual, node, self.mod, class_name,
                          tuple(self.stack))
        a = node.args
        for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            fi.params.add(arg.arg)
        self.mod.functions[qual] = fi
        if self.stack and self.stack[-1][0] == "class":
            self.mod.classes[self.stack[-1][1]].add(node.name)
        # decorators are evaluated in the ENCLOSING scope
        for dec in node.decorator_list:
            self._check_decorator(dec, fi)
            self.visit(dec)
        self.stack.append(("func", node.name))
        self.fn_stack.append(fi)
        for child in ast.iter_child_nodes(node):
            if child in node.decorator_list:
                continue
            self.visit(child)
        self.fn_stack.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_function(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.generic_visit(node)

    # -- trace seeds ---------------------------------------------------
    def _is_tracer(self, dotted_name: Optional[str]) -> bool:
        if not dotted_name:
            return False
        norm = self.mod.normalize(dotted_name)
        leaf = norm.split(".")[-1]
        if leaf not in _TRACER_LEAVES:
            return False
        prefix = norm.rsplit(".", 1)[0] if "." in norm else ""
        if prefix:
            return "jax" in prefix or "jax_compat" in prefix \
                or "pallas" in prefix
        return leaf in _BARE_OK

    def _seed_arg(self, arg: ast.AST, reason: str) -> None:
        tgt = None
        if isinstance(arg, ast.Name):
            tgt = ("name", arg.id)
        elif isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            tgt = ("self", arg.attr)
        if tgt is None:
            return
        fn = self.fn_stack[-1] if self.fn_stack else None
        # defer: the target may live later in this module or in a module
        # not collected yet
        self.graph._pending_arg_seeds.append((self.mod, fn, tgt, reason))

    def _check_decorator(self, dec: ast.AST, fi: FunctionInfo) -> None:
        name = dotted(dec)
        if name is None and isinstance(dec, ast.Call):
            fname = dotted(dec.func)
            if fname and fname.split(".")[-1] == "partial" and dec.args:
                name = dotted(dec.args[0])
            else:
                name = fname
        if name and self._is_tracer(name):
            self.graph._seed(fi.gid,
                             f"@{name} at {self.mod.relpath}:{fi.line}")

    def visit_Call(self, node: ast.Call) -> None:
        fname = dotted(node.func)
        fn = self.fn_stack[-1] if self.fn_stack else None
        # record the call edge
        if fn is not None and fname:
            parts = fname.split(".")
            if len(parts) == 1:
                fn.calls.add(("name", parts[0]))
            elif parts[0] == "self" and len(parts) == 2:
                fn.calls.add(("self", parts[1]))
            elif len(parts) >= 2:
                fn.calls.add(("attr", parts[0], parts[-1]))
        # trace seeds: f.defvjp(fwd, bwd)
        if fname and fname.split(".")[-1] == "defvjp":
            for a in node.args:
                self._seed_arg(a, f"defvjp at {self.mod.relpath}:"
                                  f"{node.lineno}")
        # trace seeds: jit(f) / shard_map(f) / pallas_call(k) / grad(f)...
        seed_name = fname
        if fname and fname.split(".")[-1] == "partial" and node.args:
            seed_name = dotted(node.args[0])
            if seed_name and self._is_tracer(seed_name) and len(node.args) > 1:
                self._seed_arg(node.args[1],
                               f"partial({seed_name}) at "
                               f"{self.mod.relpath}:{node.lineno}")
        elif fname and self._is_tracer(fname) and node.args:
            self._seed_arg(node.args[0],
                           f"{fname} at {self.mod.relpath}:{node.lineno}")
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and self.fn_stack:
            self.fn_stack[-1].refs.add(node.id)


class CallGraph:
    """All analyzed modules + the traced set + reachability queries."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}     # modkey -> info
        self.functions: Dict[str, FunctionInfo] = {}  # gid -> info
        self._pending_seeds: List[Tuple[str, str]] = []
        self._pending_arg_seeds: List[Tuple] = []
        self.traced: Dict[str, str] = {}             # gid -> reason
        self._edges: Dict[str, Set[str]] = {}

    # -- construction --------------------------------------------------
    @classmethod
    def build(cls, files: Sequence[Tuple[str, str, str]]) -> "CallGraph":
        """files: (relpath, modkey, source) triples."""
        g = cls()
        collectors = []
        for relpath, modkey, source in files:
            tree = ast.parse(source, filename=relpath)
            mod = ModuleInfo(relpath, modkey, tree, source)
            g.modules[modkey] = mod
            collectors.append(mod)
        # two passes: register all functions first so seeds recorded while
        # visiting module A can resolve into module B
        for mod in collectors:
            _Collector(mod, g).visit(mod.tree)
        for mod in collectors:
            for fi in mod.functions.values():
                g.functions[fi.gid] = fi
        # seeds recorded during collection are replayed now that every
        # function is registered (decorator seeds carry gids; argument
        # seeds carry unresolved callee tuples)
        for gid, reason in g._pending_seeds:
            if gid in g.functions and gid not in g.traced:
                g.traced[gid] = reason
        for mod, fn, tgt, reason in g._pending_arg_seeds:
            gid = g._resolve(mod, fn, tgt)
            if gid is not None and gid not in g.traced:
                g.traced[gid] = reason
        g._close_traced()
        return g

    def _seed(self, gid: str, reason: str) -> None:
        self._pending_seeds.append((gid, reason))

    # -- resolution ----------------------------------------------------
    def _lexical_prefixes(self, fn: Optional[FunctionInfo]):
        """Quals to prepend when looking up a bare name from inside fn:
        own body, then enclosing FUNCTION scopes (class scopes are not
        visible from method bodies), then module level."""
        if fn is None:
            yield ""
            return
        chain = list(fn.scope) + [("func", fn.qual.split(".")[-1])]
        for i in range(len(chain), 0, -1):
            if chain[i - 1][0] != "func":
                continue
            yield ".".join(n for _, n in chain[:i])
        yield ""

    def _resolve(self, mod: ModuleInfo, fn: Optional[FunctionInfo],
                 callee: Tuple) -> Optional[str]:
        kind = callee[0]
        if kind == "name":
            name = callee[1]
            for prefix in self._lexical_prefixes(fn):
                qual = f"{prefix}.{name}" if prefix else name
                if qual in mod.functions:
                    return mod.functions[qual].gid
            imp = mod.imports.get(name)
            if imp and imp[0] == "symbol" and imp[1] in self.modules:
                target = self.modules[imp[1]]
                if imp[2] in target.functions:
                    return target.functions[imp[2]].gid
        elif kind == "self":
            name = callee[1]
            if fn is not None and fn.class_name:
                qual = f"{fn.class_name}.{name}"
                # the class may be nested; search any class-qualified match
                if qual in mod.functions:
                    return mod.functions[qual].gid
                for q, f2 in mod.functions.items():
                    if f2.class_name == fn.class_name and \
                            q.split(".")[-1] == name:
                        return f2.gid
        elif kind == "attr":
            base, leaf = callee[1], callee[2]
            imp = mod.imports.get(base)
            if imp and imp[0] == "module" and imp[1] in self.modules:
                target = self.modules[imp[1]]
                if leaf in target.functions:
                    return target.functions[leaf].gid
            if imp and imp[0] == "symbol":
                # from ..pkg import submodule  (symbol that IS a module)
                subkey = f"{imp[1]}.{imp[2]}" if imp[1] else imp[2]
                if subkey in self.modules:
                    target = self.modules[subkey]
                    if leaf in target.functions:
                        return target.functions[leaf].gid
        return None

    def edges_of(self, fi: FunctionInfo) -> Set[str]:
        cached = self._edges.get(fi.gid)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for callee in fi.calls:
            gid = self._resolve(fi.module, fi, callee)
            if gid is not None:
                out.add(gid)
        for name in fi.refs:
            gid = self._resolve(fi.module, fi, ("name", name))
            if gid is not None and gid != fi.gid:
                out.add(gid)
        self._edges[fi.gid] = out
        return out

    # -- traced closure ------------------------------------------------
    def _close_traced(self) -> None:
        work = list(self.traced)
        while work:
            gid = work.pop()
            fi = self.functions.get(gid)
            if fi is None:
                continue
            reason = f"called from traced {fi.qual}"
            for callee in self.edges_of(fi):
                if callee not in self.traced:
                    self.traced[callee] = reason
                    work.append(callee)

    def is_traced(self, fi: FunctionInfo) -> bool:
        return fi.gid in self.traced

    # -- reachability --------------------------------------------------
    def match(self, patterns: Sequence[str]) -> List[FunctionInfo]:
        """Match 'Class.method' / '*.fit_batch' / 'mod.py::qual' globs
        against every function's gid and qual."""
        out = []
        for fi in self.functions.values():
            for pat in patterns:
                if fnmatch.fnmatch(fi.qual, pat) or \
                        fnmatch.fnmatch(fi.gid, pat):
                    out.append(fi)
                    break
        return out

    def reachable_from(self, roots: Sequence[FunctionInfo]) -> Dict[str, str]:
        """gid -> root qual for everything transitively reachable."""
        seen: Dict[str, str] = {}
        work: List[Tuple[str, str]] = [(r.gid, r.qual) for r in roots]
        while work:
            gid, root = work.pop()
            if gid in seen:
                continue
            seen[gid] = root
            fi = self.functions.get(gid)
            if fi is None:
                continue
            for callee in self.edges_of(fi):
                if callee not in seen:
                    work.append((callee, root))
        return seen


def load_package(root: str, package_dir: str,
                 exclude: Sequence[str] = ()) -> List[Tuple[str, str, str]]:
    """Collect (relpath, modkey, source) for every .py under package_dir."""
    out = []
    base = os.path.join(root, package_dir)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and d not in exclude)
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            rel = os.path.relpath(full, root).replace(os.sep, "/")
            if any(fnmatch.fnmatch(rel, e) for e in exclude):
                continue
            inner = os.path.relpath(full, base).replace(os.sep, "/")
            modkey = inner[:-3].replace("/", ".")
            if modkey.endswith("__init__"):
                modkey = modkey[: -len("__init__")].rstrip(".")
            with open(full, "r", encoding="utf-8") as f:
                out.append((rel, modkey, f.read()))
    return out
