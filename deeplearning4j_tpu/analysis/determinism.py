"""GC2xx — determinism rules.

Wall-clock reads and global-RNG draws are flagged EVERYWHERE in the
package (the repo's bit-identity gates — chaos-off identity, A/B loss
parity, replay equality — are only as strong as the set of
nondeterminism sources someone has consciously signed off on).  Each
site must either be migrated to an injectable clock / threaded seeded
generator, or carry a `# graftcheck: disable=GC201 (wall-anchor: ...)`
pragma saying why wall time is the *point* (dashboard timestamps, trace
time bases, heartbeat staleness).

The call graph sharpens the message: a site reachable from a step /
checkpoint-replay / trace-export root is labelled with that root, which
is the difference between "cosmetic" and "backs a bit-identity gate".
"""

from __future__ import annotations

import ast
from typing import Dict, List

from .callgraph import CallGraph, dotted
from .findings import Finding

# entry points whose behavior the bit-identity gates pin (ROADMAP
# tier-1 + bench hard gates).  Traced functions are implicit roots.
DETERMINISTIC_ROOTS = (
    "*.fit_batch", "*.fit_batches", "*._fit_batch_guarded",
    "ElasticTrainer.fit", "ElasticTrainer.resume",
    "CheckpointManager.save*", "CheckpointManager.restore*",
    "*.save_model", "*.load_model",
    "TraceRecorder.save", "TraceRecorder.export",
)

_WALL_CLOCK = {"time.time", "time.time_ns", "datetime.datetime.now",
               "datetime.datetime.utcnow", "datetime.date.today",
               "datetime.now", "datetime.utcnow", "date.today"}

# global-state RNG draws (instance methods on a threaded Generator /
# RandomState / jax.random key are the sanctioned pattern and do not
# match — those are `rng.normal(...)` on a Name, not `np.random.*`)
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_RNG_EXEMPT_LEAVES = {"default_rng", "RandomState", "Generator",
                      "PCG64", "Philox", "SeedSequence", "Random"}


def check_determinism(graph: CallGraph) -> List[Finding]:
    roots = graph.match(DETERMINISTIC_ROOTS)
    reach: Dict[str, str] = graph.reachable_from(roots)
    out: List[Finding] = []
    for fi in graph.functions.values():
        ctx = ""
        if fi.gid in graph.traced:
            ctx = "on a TRACED path"
        elif fi.gid in reach:
            ctx = f"reachable from deterministic root {reach[fi.gid]}"
        out.extend(_check_fn(fi, ctx))
    out.extend(_module_level(graph, reach))
    return out


def _check_nodes(nodes, rel: str, symbol: str, ctx: str) -> List[Finding]:
    out: List[Finding] = []
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        if name in _WALL_CLOCK:
            out.append(Finding(
                "GC201", rel, node.lineno, node.col_offset, symbol,
                f"{name}() is a wall-clock read — inject a clock "
                "(clock=time.time parameter) or pragma-tag the site as "
                "a wall-anchor", ctx))
        elif name.startswith(_RNG_PREFIXES) and \
                name.split(".")[-1] not in _RNG_EXEMPT_LEAVES:
            out.append(Finding(
                "GC202", rel, node.lineno, node.col_offset, symbol,
                f"{name}() draws from process-global RNG state — "
                "thread a seeded generator instead", ctx))
        elif name.split(".")[-1] == "default_rng" and not node.args:
            out.append(Finding(
                "GC202", rel, node.lineno, node.col_offset, symbol,
                "default_rng() without a seed is entropy-seeded — pass "
                "an explicit seed", ctx))
        elif name == "hash" and node.args and not _is_self_arg(node.args[0]):
            if symbol.split(".")[-1] in ("__hash__", "__eq__"):
                continue
            out.append(Finding(
                "GC203", rel, node.lineno, node.col_offset, symbol,
                "builtin hash() of str/bytes varies per process "
                "(PYTHONHASHSEED) — use hashlib or a stable key", ctx))
    return out


def _is_self_arg(arg: ast.AST) -> bool:
    return isinstance(arg, ast.Name) and arg.id == "self"


def _check_fn(fi, ctx: str) -> List[Finding]:
    nodes = []
    stack = list(ast.iter_child_nodes(fi.node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        nodes.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return _check_nodes(nodes, fi.module.relpath, fi.qual, ctx)


def _module_level(graph: CallGraph, reach) -> List[Finding]:
    """Statements outside any def (import-time clock/RNG reads)."""
    out: List[Finding] = []
    for mod in graph.modules.values():
        nodes = []
        stack = list(ast.iter_child_nodes(mod.tree))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                # class bodies: walk non-function statements only
                if isinstance(n, ast.ClassDef):
                    stack.extend(c for c in ast.iter_child_nodes(n)
                                 if not isinstance(
                                     c, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)))
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        out.extend(_check_nodes(nodes, mod.relpath, "", "at import time"))
    return out
